"""Feature↔label statistical tests.

Ref parity: the numeric cores of flink-ml-lib stats/{chisqtest,anovatest,
fvaluetest} and the univariate feature selector. Implemented with scipy
(host-side — these are keyed aggregations over modest cardinalities, not
MXU work).

Each function takes features (n, d) and labels (n,) and returns
(statistics (d,), p_values (d,), degrees_of_freedom (d,)).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
from scipy import stats as sstats

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray]


def chi_square_test(features: np.ndarray, labels: np.ndarray) -> Arrays:
    """Pearson chi-squared independence test per feature column
    (ref: stats/chisqtest/ChiSqTest.java — categorical feature vs
    categorical label)."""
    features = np.asarray(features)
    labels = np.asarray(labels)
    stats_, ps, dofs = [], [], []
    for j in range(features.shape[1]):
        col = features[:, j]
        f_vals, f_idx = np.unique(col, return_inverse=True)
        l_vals, l_idx = np.unique(labels, return_inverse=True)
        table = np.zeros((len(f_vals), len(l_vals)))
        np.add.at(table, (f_idx, l_idx), 1.0)
        chi2, p, dof, _ = sstats.chi2_contingency(table, correction=False)
        stats_.append(chi2)
        ps.append(p)
        dofs.append(dof)
    return np.asarray(stats_), np.asarray(ps), np.asarray(dofs, np.int64)


def _is_device(x) -> bool:
    return not isinstance(x, np.ndarray) and hasattr(x, "addressable_shards")


def _group_sums_kernel(x, y, c):
    import jax.numpy as jnp
    import jax.nn

    oh = jax.nn.one_hot(y.astype(jnp.int32), c, dtype=x.dtype)  # (n, c)
    return jnp.concatenate([oh.sum(axis=0)[:, None], oh.T @ x], axis=1)


def _group_ssw_kernel(x, y, means):
    import jax.numpy as jnp

    centered = x - means[y.astype(jnp.int32)]
    return jnp.sum(centered * centered, axis=0)


def anova_f_test(features: np.ndarray, labels: np.ndarray) -> Arrays:
    """One-way ANOVA F-test per feature (ref: stats/anovatest/ANOVATest.java
    — continuous feature vs categorical label).

    A device-resident feature matrix reduces ON device (two passes: group
    counts/sums, then centered within-group sum of squares against the
    replicated group means — float32-stable); only the (c, d) group stats
    cross to host, where the F/p math runs in float64."""
    labels = np.asarray(labels)
    classes, y_idx = np.unique(labels, return_inverse=True)
    c = len(classes)
    if _is_device(features):
        from flink_ml_tpu.ops import columnar

        n, d = features.shape
        y32 = y_idx.astype(np.int32)
        packed = np.asarray(columnar.apply_multi(
            _group_sums_kernel, (features, y32), static=(c,)), np.float64)
        counts, sums = packed[:, 0], packed[:, 1:]
        means = sums / np.maximum(counts[:, None], 1.0)
        ssw = np.asarray(columnar.apply_multi(
            _group_ssw_kernel, (features, y32),
            consts=(means.astype(np.float32),)), np.float64)
        grand = sums.sum(axis=0) / n
        ssb = (counts[:, None] * (means - grand[None, :]) ** 2).sum(axis=0)
        dfb, dfw = c - 1, n - c
        # IEEE semantics mirror scipy.f_oneway: ssw=0 with signal → F=inf
        # (p=0); 0/0 (constant feature) → NaN, as on the host path
        with np.errstate(divide="ignore", invalid="ignore"):
            f = (ssb / dfb) / (ssw / dfw)
        p = sstats.f.sf(f, dfb, dfw)
        return f, p, np.full(d, dfw, np.int64)
    features = np.asarray(features, np.float64)
    stats_, ps, dofs = [], [], []
    n = features.shape[0]
    for j in range(features.shape[1]):
        groups = [features[labels == cl, j] for cl in classes]
        f, p = sstats.f_oneway(*groups)
        stats_.append(f)
        ps.append(p)
        dofs.append(n - len(classes))
    return np.asarray(stats_), np.asarray(ps), np.asarray(dofs, np.int64)


def _sums_kernel(x, y):
    import jax.numpy as jnp

    return jnp.concatenate([jnp.sum(x, axis=0), jnp.sum(y)[None]])


def _centered_products_kernel(x, y, xmean, ymean):
    import jax.numpy as jnp

    xc = x - xmean[None, :]
    yc = y - ymean
    return jnp.stack([jnp.sum(xc * yc[:, None], axis=0),
                      jnp.sum(xc * xc, axis=0),
                      jnp.full(x.shape[1], jnp.sum(yc * yc))])


def f_value_test(features: np.ndarray, labels: np.ndarray) -> Arrays:
    """Univariate linear-regression F-test per feature
    (ref: stats/fvaluetest/FValueTest.java — continuous vs continuous).

    Device-resident features reduce on device (two float32-stable passes);
    the (d,)-sized correlation → F → p tail runs in float64 on host."""
    if _is_device(features):
        from flink_ml_tpu.ops import columnar

        n, d = features.shape
        y32 = np.asarray(labels, np.float32)
        sums = np.asarray(columnar.apply_multi(
            _sums_kernel, (features, y32)), np.float64)
        xmean, ymean = sums[:-1] / n, sums[-1] / n
        packed = np.asarray(columnar.apply_multi(
            _centered_products_kernel, (features, y32),
            consts=(xmean.astype(np.float32), np.float32(ymean))),
            np.float64)
        sxy, sxx, syy = packed[0], packed[1], packed[2][0]
        dof = n - 2
        denom = np.sqrt(sxx * syy)
        corr = np.where(denom > 0, sxy / np.where(denom > 0, denom, 1.0),
                        0.0)
        corr = np.clip(corr, -1.0, 1.0)
        f = np.where(corr ** 2 < 1.0,
                     corr ** 2 / np.maximum(1.0 - corr ** 2, 1e-300) * dof,
                     np.inf)
        p = sstats.f.sf(f, 1, dof)
        return f, p, np.full(d, dof, np.int64)
    x = np.asarray(features, np.float64)
    y = np.asarray(labels, np.float64)
    n, d = x.shape
    dof = n - 2
    xc = x - x.mean(axis=0)
    yc = y - y.mean()
    denom = np.sqrt((xc * xc).sum(axis=0) * (yc * yc).sum())
    corr = np.where(denom > 0, (xc * yc[:, None]).sum(axis=0)
                    / np.where(denom > 0, denom, 1.0), 0.0)
    corr = np.clip(corr, -1.0, 1.0)
    f = np.where(corr ** 2 < 1.0,
                 corr ** 2 / np.maximum(1.0 - corr ** 2, 1e-300) * dof,
                 np.inf)
    p = sstats.f.sf(f, 1, dof)
    return f, p, np.full(d, dof, np.int64)
