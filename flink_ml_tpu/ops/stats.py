"""Feature↔label statistical tests.

Ref parity: the numeric cores of flink-ml-lib stats/{chisqtest,anovatest,
fvaluetest} and the univariate feature selector. Implemented with scipy
(host-side — these are keyed aggregations over modest cardinalities, not
MXU work).

Each function takes features (n, d) and labels (n,) and returns
(statistics (d,), p_values (d,), degrees_of_freedom (d,)).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
from scipy import stats as sstats

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray]


def chi_square_test(features: np.ndarray, labels: np.ndarray) -> Arrays:
    """Pearson chi-squared independence test per feature column
    (ref: stats/chisqtest/ChiSqTest.java — categorical feature vs
    categorical label)."""
    features = np.asarray(features)
    labels = np.asarray(labels)
    stats_, ps, dofs = [], [], []
    for j in range(features.shape[1]):
        col = features[:, j]
        f_vals, f_idx = np.unique(col, return_inverse=True)
        l_vals, l_idx = np.unique(labels, return_inverse=True)
        table = np.zeros((len(f_vals), len(l_vals)))
        np.add.at(table, (f_idx, l_idx), 1.0)
        chi2, p, dof, _ = sstats.chi2_contingency(table, correction=False)
        stats_.append(chi2)
        ps.append(p)
        dofs.append(dof)
    return np.asarray(stats_), np.asarray(ps), np.asarray(dofs, np.int64)


def anova_f_test(features: np.ndarray, labels: np.ndarray) -> Arrays:
    """One-way ANOVA F-test per feature (ref: stats/anovatest/ANOVATest.java
    — continuous feature vs categorical label)."""
    features = np.asarray(features, np.float64)
    labels = np.asarray(labels)
    classes = np.unique(labels)
    stats_, ps, dofs = [], [], []
    n = features.shape[0]
    for j in range(features.shape[1]):
        groups = [features[labels == c, j] for c in classes]
        f, p = sstats.f_oneway(*groups)
        stats_.append(f)
        ps.append(p)
        dofs.append(n - len(classes))
    return np.asarray(stats_), np.asarray(ps), np.asarray(dofs, np.int64)


def f_value_test(features: np.ndarray, labels: np.ndarray) -> Arrays:
    """Univariate linear-regression F-test per feature
    (ref: stats/fvaluetest/FValueTest.java — continuous vs continuous)."""
    x = np.asarray(features, np.float64)
    y = np.asarray(labels, np.float64)
    n, d = x.shape
    dof = n - 2
    xc = x - x.mean(axis=0)
    yc = y - y.mean()
    denom = np.sqrt((xc * xc).sum(axis=0) * (yc * yc).sum())
    corr = np.where(denom > 0, (xc * yc[:, None]).sum(axis=0)
                    / np.where(denom > 0, denom, 1.0), 0.0)
    corr = np.clip(corr, -1.0, 1.0)
    f = np.where(corr ** 2 < 1.0,
                 corr ** 2 / np.maximum(1.0 - corr ** 2, 1e-300) * dof,
                 np.inf)
    p = sstats.f.sf(f, 1, dof)
    return f, p, np.full(d, dof, np.int64)
