"""Shared numeric kernels: losses, regularization, optimizers.

Ref parity: flink-ml-lib/.../common/{lossfunc,optimizer}/ — the ⚙ rows of
SURVEY.md §2.4 whose inner loops become compiled XLA here.
"""

from flink_ml_tpu.ops.losses import (  # noqa: F401
    BinaryLogisticLoss,
    HingeLoss,
    LeastSquareLoss,
    LossFunc,
)
from flink_ml_tpu.ops.regularization import regularize  # noqa: F401
from flink_ml_tpu.ops.optimizer import SGD, SGDParams  # noqa: F401
