"""Shared device-side columnar transform path for dense feature ops.

The ⚙ "compiled XLA" tier of SURVEY.md §2.1/§2.4: dense numeric feature
transforms (scalers, IDF, Normalizer, ElementwiseProduct, PolynomialExpansion,
DCT, Binarizer, Bucketizer, Interaction, slicers/selectors) run as one jitted
elementwise/reduce program per op, with the (n, d) column sharded over the
mesh's data axis and model statistics replicated. The reference runs these as
per-record Java map functions (e.g. feature/standardscaler/
StandardScalerModel.java); here one XLA program handles the whole column and
fuses the elementwise chain.

Residency: outputs are left as device arrays inside the Table, so chained
Pipeline stages (scale → normalize → ...) hand sharded device buffers to one
another with no host round-trip. The host off-ramp happens only when a
consumer reads rows / converts to numpy.

Dtype policy (documented deviation, docs/deviations.md): device transforms
compute in float32 (TPU-native width; the MXU/VPU have no fast float64),
while fit-time statistics stay float64 on host. The reference computes both
in Java double.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from flink_ml_tpu.parallel.mesh import data_pspec, local_mesh


def is_device_array(x) -> bool:
    return isinstance(x, jax.Array)


def to_device(x, mesh=None) -> jax.Array:
    """Device on-ramp: shard dim 0 (rows) over the mesh's data axis.

    Already-device arrays pass through untouched (chained stages keep their
    residency and sharding). Host arrays are cast to float32 — see the
    module dtype policy. Row counts that don't divide the shard count are
    zero-padded for the transfer and sliced back on device (same recipe as
    parallel.collective.shard_batch; elementwise transforms are unaffected
    by padding rows, and the slice keeps the user-visible length exact).
    """
    if is_device_array(x):
        return x
    mesh = mesh or local_mesh()
    x = np.asarray(x)
    if x.dtype.kind == "f" and x.dtype != np.float32:
        x = x.astype(np.float32)
    from flink_ml_tpu.parallel.mesh import data_shard_count

    n = x.shape[0]
    pad = (-n) % data_shard_count(mesh)
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
    spec = P(data_pspec(mesh), *([None] * (x.ndim - 1)))
    arr = jax.device_put(x, NamedSharding(mesh, spec))
    # A divisible row count (every benchmark shape, and always on a single
    # chip) takes the clean path: sharded transfer, no slice. Uneven rows
    # pay one on-device slice whose result XLA may replicate — correct but
    # not bandwidth-optimal; acceptable for the odd-sized case.
    return arr[:n] if pad else arr


def replicated(c, mesh=None) -> jax.Array:
    """Model statistics / constants: replicated on every device."""
    mesh = mesh or local_mesh()
    c = np.asarray(c)
    if c.dtype.kind == "f" and c.dtype != np.float32:
        c = c.astype(np.float32)
    return jax.device_put(c, NamedSharding(mesh, P()))


@lru_cache(maxsize=None)
def _jitted(fn, n_static: int, n_args: int):
    static = tuple(range(n_args - n_static, n_args))
    return jax.jit(fn, static_argnums=static)


def apply(fn, x, consts: Sequence = (), static: Tuple = ()):
    """Run ``fn(x, *consts, *static)`` as one jitted program on device.

    ``fn`` must be a module/class-level function of jnp ops (stable object
    identity keys the jit cache). ``consts`` are replicated device operands
    (model stats); ``static`` are hashable compile-time arguments (flags,
    dims) that select the traced program.
    """
    return apply_multi(fn, (x,), consts, static)


def apply_multi(fn, xs: Sequence, consts: Sequence = (), static: Tuple = ()):
    """Like :func:`apply` but with several row-sharded inputs (e.g. the
    Interaction op's input columns): ``fn(*xs, *consts, *static)``."""
    mesh = local_mesh()
    xs_d = tuple(to_device(x, mesh) for x in xs)
    consts_d = tuple(replicated(c, mesh) for c in consts)
    n_args = len(xs_d) + len(consts_d) + len(static)
    return _jitted(fn, len(static), n_args)(*xs_d, *consts_d, *static)


def fit_vectors(table, col: str):
    """Fit-statistics on-ramp: returns ``(x, xp)``. A device-resident
    column keeps its residency — fit statistics then compute ON device in
    float32 (the module dtype policy) instead of off-ramping the whole
    table; a host column keeps the float64 host contract. The xp namespace
    (jnp vs np) tells the caller which path it got."""
    import numpy as np

    raw = table.column(col)
    if is_device_array(raw):
        return (raw if raw.ndim == 2 else raw[:, None]), jnp
    return table.vectors(col, np.float64), np


def input_vectors(table, col: str) -> jax.Array:
    """Table → sharded (n, d) device array (the device on-ramp for vector
    columns; passthrough when a previous stage already left the column on
    device)."""
    raw = table.column(col)
    if is_device_array(raw):
        return raw if raw.ndim == 2 else raw[:, None]
    return to_device(table.vectors(col, np.float32))


def input_scalars(table, col: str) -> jax.Array:
    raw = table.column(col)
    if is_device_array(raw):
        return raw
    return to_device(table.scalars(col, np.float32))


def to_host(x) -> np.ndarray:
    """Explicit off-ramp (one D2H transfer)."""
    return np.asarray(x)


def _head_rows_kernel(x, n):
    return jax.lax.slice_in_dim(x, 0, n)


def head_rows(x, n: int):
    """First ``n`` rows of a (possibly sharded) device array as a compiled
    static slice. Basic ``x[:n]`` indexing on a mesh-sharded array lowers
    to an unsharded gather that measured ~1.7 s WARM on the 8-device mesh
    (the whole execute cost of the VectorIndexer/KBinsDiscretizer fits,
    VERDICT r4 weak-#4); the jitted ``lax.slice_in_dim`` is 2-30 ms and
    keeps global first-n semantics on any mesh."""
    return _jitted(_head_rows_kernel, 1, 2)(x, int(min(n, x.shape[0])))


def _dynamic_rows_kernel(x, start, size):
    return jax.lax.dynamic_slice_in_dim(x, start, size)


def dynamic_rows(x, start: int, size: int):
    """Rows ``[start, start+size)`` of a device array (Table.take's
    device fast path).

    Single-device arrays (the real-chip benchmark case) slice through
    one compiled dynamic-slice per (shape, dtype, size): the start rides
    as a traced scalar, so a batch loop walking the column reuses a
    single program for every offset — no per-offset compile through the
    TPU tunnel. ``dynamic_slice`` clamps starts, so callers keep
    start+size <= n.

    Mesh-SHARDED arrays keep the eager gather: every sliced-program
    variant tried (traced-start dynamic slice, static slice) reshards
    through a runtime collective whose 8-thread rendezvous STARVES on
    this single-core host at benchmark scale (hard 40 s timeout crash,
    rendezvous.cc) — the gather is slower per call but collective-free
    at dispatch and was the long-standing streaming behavior on the
    CPU mesh."""
    if len(getattr(x.sharding, "device_set", ())) <= 1:
        return _jitted(_dynamic_rows_kernel, 1, 3)(
            x, jnp.asarray(start, jnp.int32), int(size))
    return x[np.arange(start, start + size)]


def _take_dims_kernel(x, dims):
    return x[:, np.asarray(dims)]


def take_dims(x, dims):
    """Column subset of a sharded (n, d) device array via a compiled
    static gather (same rationale as :func:`head_rows`: eager fancy
    indexing on sharded arrays is pathologically slow)."""
    return _jitted(_take_dims_kernel, 1, 2)(x, tuple(int(d) for d in dims))
