"""Regularization.

Ref parity: flink-ml-lib/.../common/optimizer/RegularizationUtils.java:47 —
post-update shrink/soft-threshold with the reference's exact formulas,
including its idiosyncrasies (the pure-L2 "loss" term uses ||w||₂ rather than
||w||₂², and the L1 loss term sums sign(w_i)); we reproduce them so loss
curves and tol-based termination match the reference bit-for-bit in spirit.
"""

from __future__ import annotations

import jax.numpy as jnp


def regularize(coeffs, reg: float, elastic_net: float, learning_rate: float,
               xp=jnp):
    """Returns (new_coeffs, reg_loss). Pure function of the coefficient
    vector; all branches are trace-time Python on static params. ``xp``
    selects the array backend: jnp inside compiled programs (default), np
    for the float64 host CSR fallback (jnp would downcast to float32)."""
    if reg == 0.0:
        return coeffs, xp.zeros((), coeffs.dtype)
    if elastic_net == 0.0:
        # pure L2 (ref lines 55-59)
        loss = reg / 2.0 * xp.linalg.norm(coeffs)
        return coeffs * (1.0 - learning_rate * reg), loss
    if elastic_net == 1.0:
        # pure L1 (ref lines 60-73): skip exact zeros
        sign = xp.sign(coeffs)
        loss = xp.sum(elastic_net * reg * sign)
        new = coeffs - learning_rate * elastic_net * reg * sign
        return new, loss
    # elastic net (ref lines 74-90)
    sign = xp.sign(coeffs)
    loss = xp.sum(elastic_net * reg * sign
                   + (1.0 - elastic_net) * (reg / 2.0) * coeffs * coeffs)
    new = coeffs - learning_rate * (elastic_net * reg * sign
                                    + (1.0 - elastic_net) * reg * coeffs)
    return new, loss
