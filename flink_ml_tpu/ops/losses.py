"""Loss functions.

Ref parity: flink-ml-lib/.../common/lossfunc/{LossFunc.java:40-49,
BinaryLogisticLoss.java:29, HingeLoss.java:33, LeastSquareLoss.java:29}.

The reference computes per-sample loss/gradient in a Java loop accumulating
into a shared vector; here each loss is a **batched** function over the whole
minibatch: one (b,d)x(d,) matvec for the margins, elementwise math for the
multipliers, and one (d,b)x(b,) matvec for the cumulative gradient — all of
which XLA fuses onto the MXU. Labels follow the reference convention
(binary labels in {0,1}, scaled internally to ±1).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["LossFunc", "BinaryLogisticLoss", "HingeLoss", "LeastSquareLoss"]


class LossFunc:
    """Batched loss: given coefficients and a weighted minibatch, return
    (loss_sum, grad_sum) — the reference's computeLoss/computeGradient
    accumulated over the batch (LossFunc.java:40-49).

    Every loss here decomposes as ``dots = X @ w``, elementwise
    ``terms(dots) -> (loss_sum, multipliers)``, ``grad = X.T @ multipliers``
    — which is what lets the tensor-parallel SGD path compute partial dots
    on a feature shard, psum them over the model axis, and keep the
    gradient matvec local (see optimizer._sgd_round_math)."""

    NAME = None

    def terms(self, dots, labels, weights, xp=jnp):
        """(b,) margins → (scalar loss sum, (b,) gradient multipliers).
        ``xp`` picks the array backend: jnp inside compiled programs
        (default), np for the float64 host CSR fallback."""
        raise NotImplementedError

    def loss_and_gradient(self, coeffs, features, labels, weights):
        """coeffs (d,), features (b, d), labels (b,), weights (b,) →
        (scalar loss sum, (d,) gradient sum)."""
        loss, multipliers = self.terms(features @ coeffs, labels, weights)
        return loss, features.T @ multipliers

    @staticmethod
    def by_name(name: str) -> "LossFunc":
        for cls in (BinaryLogisticLoss, HingeLoss, LeastSquareLoss):
            if cls.NAME == name:
                return cls()
        raise ValueError(f"unknown loss {name!r}")


class BinaryLogisticLoss(LossFunc):
    """Ref: BinaryLogisticLoss.java:29 — loss = w·log(1+e^{-dot·(2y-1)}),
    grad = w·(-(2y-1)/(e^{dot·(2y-1)}+1))·x."""

    NAME = "logistic"

    def terms(self, dots, labels, weights, xp=jnp):
        label_scaled = 2.0 * labels - 1.0
        margins = dots * label_scaled
        # log1p(exp(-m)) with the standard overflow-safe rewrite
        loss = xp.sum(weights * (xp.logaddexp(0.0, -margins)))
        multipliers = weights * (-label_scaled / (xp.exp(margins) + 1.0))
        return loss, multipliers


class HingeLoss(LossFunc):
    """Ref: HingeLoss.java:33 — loss = w·max(0, 1-(2y-1)·dot); subgradient
    -(2y-1)·w·x where the hinge is active."""

    NAME = "hinge"

    def terms(self, dots, labels, weights, xp=jnp):
        label_scaled = 2.0 * labels - 1.0
        hinge = 1.0 - label_scaled * dots
        loss = xp.sum(weights * xp.maximum(hinge, 0.0))
        active = (hinge > 0.0).astype(dots.dtype)
        multipliers = -label_scaled * weights * active
        return loss, multipliers


class LeastSquareLoss(LossFunc):
    """Ref: LeastSquareLoss.java:29 — loss = w·½(dot-y)², grad = w·(dot-y)·x."""

    NAME = "least_square"

    def terms(self, dots, labels, weights, xp=jnp):
        err = dots - labels
        loss = xp.sum(weights * 0.5 * err * err)
        return loss, weights * err
