"""Distributed SGD — the canonical training loop.

Ref parity: flink-ml-lib/.../common/optimizer/SGD.java:67 (optimize:82,
TrainIterationBody:97, CacheDataAndDoTrain:157) + Optimizer.java. Semantics
reproduced exactly:

- per-task local batch: ``globalBatchSize/numTasks`` (+1 for the first
  ``globalBatchSize%numTasks`` tasks) sliced sequentially from the task's
  cached shard with wrap-to-zero at the end (SGD.java:206-213, 262-284 —
  including the short-batch-at-the-end behavior of ``subList(offset,
  min(offset+lb, n))``);
- per round: minibatch loss/gradient/weight sums all-reduced, then every
  task applies ``w -= lr/totalWeight · grad`` followed by regularization
  (SGD.java:231-243); the model update count equals the round count;
- termination: maxIter rounds, or all-reduced ``loss/totalWeight < tol``
  (TrainIterationBody criteria map). Note the criteria loss is the *data*
  loss only: the reference's regLoss bookkeeping (SGD.java:238-241) mutates
  a local copy of the received feedback that is zeroed before the next
  collect, so regLoss never reaches the criteria stream — we mirror that.

TPU design: the whole optimization is ONE compiled SPMD program — a
``lax.while_loop`` inside ``shard_map`` over the data axis. The reference's
per-round machinery (feedback channel, epoch alignment, chunked all-reduce
over TCP) becomes: carry in device registers/HBM, lockstep rounds, one
``psum`` over ICI per round. Zero host round-trips for the entire fit.
Compiled programs are cached per (loss, mesh, hyperparams); shapes are
handled by jit's own cache — repeated fits do not retrace.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flink_ml_tpu.observability import health as _health
from flink_ml_tpu.ops.losses import LossFunc
from flink_ml_tpu.ops.regularization import regularize
from flink_ml_tpu.parallel.mesh import (
    MODEL_AXIS,
    data_axes,
    data_pspec,
    data_shard_count,
    default_mesh,
    model_axis_of,
)
from flink_ml_tpu.parallel import mapreduce as mr
from flink_ml_tpu.parallel import update_sharding as _upd
from flink_ml_tpu.parallel.collective import (
    ensure_on_mesh,
    ones_on_mesh,
)


@dataclasses.dataclass(frozen=True)
class SGDParams:
    """Ref: the SGDParams POJO consumed by SGD (SGD.java:67), extended
    with the stateful update rules (``method``): the reference's SGD is
    the stateless ``w -= lr/totalW · grad``; ``momentum`` and ``adam``
    carry per-coordinate moment accumulators through the fit — and
    under the cross-replica sharded update (update_sharding.py,
    arXiv:2004.13336) those accumulators live as ``1/N`` per-replica
    slices, which is the whole point: optimizer-state memory that
    scales DOWN with the mesh."""
    learning_rate: float = 0.1
    global_batch_size: int = 32
    max_iter: int = 20
    tol: float = 1e-6
    reg: float = 0.0
    elastic_net: float = 0.0
    #: update rule: "sgd" (stateless), "momentum", "adam"
    method: str = "sgd"
    momentum: float = 0.9
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8


#: moment VECTORS each rule carries (adam additionally carries the
#: scalar step counter for bias correction — see _opt_init)
_OPT_VECTORS = {"sgd": 0, "momentum": 1, "adam": 2}


def _check_method(prm: SGDParams) -> None:
    if prm.method not in _OPT_VECTORS:
        raise ValueError(
            f"SGDParams.method must be one of {sorted(_OPT_VECTORS)}, "
            f"got {prm.method!r}")


def _update_rule(prm: SGDParams, xp=jnp):
    """The per-coordinate update rule ``rule(grad_sum, total_w, w, opt)
    -> (w_new, opt_new)`` — elementwise along dim 0, so the SAME
    callable applies to the full replicated vector and to a replica's
    ``1/N`` slice under the sharded update, and (with ``xp=np``) to the
    host CSR path, keeping dense/sparse/sharded fits numerically
    aligned by construction. ``opt`` is the rule's moment state: ``()``
    for sgd, ``(m,)`` for momentum, ``(m, v, t)`` for adam (t is the
    replicated bias-correction step counter — never sliced).
    Regularization is applied by the caller AFTER the rule
    (SGD.java:231-243 order, shared by every method)."""
    _check_method(prm)
    lr = prm.learning_rate
    if prm.method == "sgd":
        def rule(grad, total_w, w, opt):
            # the exact historical expression — the replicated sgd path
            # must stay bit-identical to the pre-stateful programs
            return w - (lr / xp.maximum(total_w, 1e-30)) * grad, opt
    elif prm.method == "momentum":
        mu = prm.momentum

        def rule(grad, total_w, w, opt):
            g = grad / xp.maximum(total_w, 1e-30)
            m = mu * opt[0] + g
            return w - lr * m, (m,)
    else:  # adam
        b1, b2, eps = prm.beta1, prm.beta2, prm.eps

        def rule(grad, total_w, w, opt):
            g = grad / xp.maximum(total_w, 1e-30)
            m, v, t = opt
            t = t + 1.0
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * (g * g)
            m_hat = m / (1.0 - b1 ** t)
            v_hat = v / (1.0 - b2 ** t)
            return w - lr * m_hat / (xp.sqrt(v_hat) + eps), (m, v, t)
    return rule


def _opt_specs(prm: SGDParams, wspec, spec0, sharded: bool):
    """shard_map in/out specs for the opt-state tuple: moment vectors
    follow the coefficient placement — replicated (or model-sharded
    under TP) normally, dim-0-sharded ``1/N`` slices under the sharded
    update (they never all-gather: this is the 1/N memory) — and adam's
    step counter is always a replicated scalar."""
    vec = P(spec0) if sharded else wspec
    specs = (vec,) * _OPT_VECTORS[prm.method]
    if prm.method == "adam":
        specs = specs + (P(),)
    return specs


def _sgd_update_math(loss_func, prm: SGDParams, axes, model_axis=None,
                     sharded: bool = False):
    """The post-slice math of one round — loss/gradient on the minibatch,
    the fused [grad, weight, loss] reduction (the reference's
    feedbackArray layout, SGD.java:190), the model update +
    regularization (SGD.java:231-243) — shared by the while-loop,
    unrolled and host-driven programs so a change here propagates to
    every fit path.

    Returns ``(update, apply_packed)``: ``update(coeffs, opt, xb, yb,
    wb) -> (new_coeffs, new_opt, mean_loss)`` for the slice-based
    rounds, and ``apply_packed(coeffs, opt, packed_local) ->
    (new_coeffs, new_opt, mean_loss)`` for rounds whose local
    [grad | weight | loss] partials come from the fused pallas kernel —
    the cross-shard reduction and the model update are this one shared
    tail either way. ``opt`` is the stateful rule's moment tuple
    (:func:`_update_rule`): ``()`` for plain sgd, so the stateless
    programs carry nothing. Must be called inside a
    ``mapreduce.map_shards`` body over the mesh's data ``axes``.

    With ``sharded`` (update_sharding.py, DP meshes only) the tail is
    the cross-replica sharded update: the gradient reduce-scatters so
    each replica updates only its own ``1/N`` coefficient slice
    (regularization included — it is elementwise), then the fresh
    coefficients all-gather — while the moment slices (momentum's m,
    adam's m/v) STAY sharded across rounds, the 1/N optimizer memory of
    arXiv:2004.13336; the scalar [weight | loss] tail still
    all-reduces. The coefficient carry must be padded to the shard
    multiple (``optimize`` does). Results match the replicated tail up
    to float reassociation in the reduction order."""
    rule = _update_rule(prm)

    def apply_packed(coeffs, opt, packed_local):
        if sharded:
            tail = mr.reduce_sum(packed_local[-2:], axes)
            total_w, total_loss = tail[0], tail[1]
            grad_pad = _upd.pad_leading(packed_local[:-2], coeffs.shape[0])

            def apply_fn(g_slice, c_slice, opt_state):
                upd, new_opt = rule(g_slice, total_w, c_slice, opt_state)
                upd, _ = regularize(upd, prm.reg, prm.elastic_net,
                                    prm.learning_rate)
                return upd, new_opt

            updated, new_opt = _upd.sharded_apply(axes, grad_pad, coeffs,
                                                  opt, apply_fn)
        else:
            packed = mr.reduce_sum(packed_local, axes)
            grad, total_w, total_loss = packed[:-2], packed[-2], packed[-1]

            # ref updateModel (SGD.java:231-243); skip when no weight
            updated, new_opt = rule(grad, total_w, coeffs, opt)
            updated, _ = regularize(updated, prm.reg, prm.elastic_net,
                                    prm.learning_rate)
        coeffs_out = jnp.where(total_w > 0, updated, coeffs)
        # a zero-weight round must leave the moments untouched too
        opt_out = jax.tree_util.tree_map(
            lambda n, o: jnp.where(total_w > 0, n, o), new_opt, opt)
        mean_loss = total_loss / jnp.maximum(total_w, 1e-30)
        return coeffs_out, opt_out, mean_loss

    def update(coeffs, opt, xb, yb, wb):
        if model_axis is None:
            d = xb.shape[1]  # == coeffs length unless sharded padding
            loss_sum, grad_sum = loss_func.loss_and_gradient(coeffs[:d],
                                                             xb, yb, wb)
        else:
            dots = mr.reduce_sum(xb @ coeffs, model_axis)
            loss_sum, multipliers = loss_func.terms(dots, yb, wb)
            grad_sum = xb.T @ multipliers  # local feature shard
        packed = jnp.concatenate([
            grad_sum, jnp.sum(wb)[None].astype(grad_sum.dtype),
            loss_sum[None]])
        return apply_packed(coeffs, opt, packed)

    return update, apply_packed


def _sgd_round_math(loss_func, prm: SGDParams, p: int, axes,
                    model_axis=None, sharded: bool = False):
    """The per-shard math of ONE training round — shared verbatim by the
    all-device while_loop program and the host-driven round program so the
    two modes stay numerically identical by construction.

    Returns ``round(xl, yl, wl, coeffs, opt, offset) ->
    (coeffs, opt, new_offset, mean_loss)`` operating on this shard's
    slice; must be called inside shard_map over the mesh's data axes
    (``axes`` — a flat ("data",) mesh or a ("dcn", "data") hybrid).

    With ``model_axis`` (tensor parallelism for wide models — a TPU-native
    capability beyond the reference's DP-only design), the feature
    dimension of ``xl`` and ``coeffs`` is additionally sharded over that
    axis: the per-sample margins are partial dots psum'd over the model
    axis (every loss here is margin-decomposable, LossFunc.terms), the
    gradient matvec and the coefficient update stay local to the feature
    shard, and the loss/weight reduction crosses the data axes only."""
    gb = prm.global_batch_size
    lb_base, lb_rem = gb // p, gb % p
    update, _ = _sgd_update_math(loss_func, prm, axes, model_axis,
                                 sharded=sharded)

    def round_step(xl, yl, wl, coeffs, opt, offset):
        local_n = xl.shape[0]  # static at trace time
        lb_max = min(lb_base + (1 if lb_rem else 0), local_n)
        task_id = mr.shard_index(axes)
        # ref SGD.java:206-213 — low task ids take the remainder
        lb = jnp.minimum(lb_base + (task_id < lb_rem).astype(jnp.int32),
                         local_n)

        # minibatch slice with clip-at-end + wrap-to-zero (the reference's
        # contiguous subList, SGD.java:262-284) as ONE dynamic-slice DMA
        # instead of a row gather — a contiguous HBM window, not per-row
        # addressing. dynamic_slice clamps its start to keep the window
        # in bounds, so validity is remapped to SOURCE rows: rows outside
        # [offset, offset+lb) ∩ [0, local_n) get weight 0, and the
        # weight-scaled losses (losses.py terms — loss and multipliers
        # are both `weights * ...`) zero their loss and gradient exactly;
        # the batch values themselves need no masking.
        start = jnp.minimum(offset, local_n - lb_max)
        xb = jax.lax.dynamic_slice_in_dim(xl, start, lb_max, axis=0)
        yb = jax.lax.dynamic_slice_in_dim(yl, start, lb_max, axis=0)
        ws = jax.lax.dynamic_slice_in_dim(wl, start, lb_max, axis=0)
        src = start + jnp.arange(lb_max)
        valid = jnp.logical_and(src >= offset, src < offset + lb)
        wb = ws * valid.astype(xl.dtype)

        coeffs, opt, mean_loss = update(coeffs, opt, xb, yb, wb)
        new_offset = jnp.where(offset + lb >= local_n, 0, offset + lb)
        return coeffs, opt, new_offset, mean_loss

    return round_step


@functools.lru_cache(maxsize=128)
def _build_sgd_segment_program(loss_cls, mesh: Mesh, prm: SGDParams,
                               health: bool = False,
                               sharded: bool = False,
                               fused: bool = False):
    """A K-round slice of the training loop as ONE compiled SPMD program:
    ``segment(xs, ys, ws, coeffs, offsets, opt, epoch0, limit, hist,
    fin) -> (coeffs, offsets, opt, mean_loss, epoch, stop, hist, fin)``.
    The epoch bounds are device scalars, so every segment of a
    checkpointed fit reuses a single compilation; between segments the
    host snapshots the carry (iteration.run_segmented) — fault tolerance
    at fast-path speed, the composition the reference gets from
    checkpointing *through* the iteration (Checkpoints.java:43).

    ``opt`` is the stateful rule's moment tuple (:func:`_update_rule`):
    ``()`` for plain sgd — the stateless signature carries nothing — and
    (m,) / (m, v, t) for momentum / adam, donated with the carry; under
    the sharded update the moment vectors are dim-0-sharded ``1/N``
    slices that never leave their replicas between rounds
    (arXiv:2004.13336 — the 1/N optimizer memory).

    The plain (uncheckpointed) fit is the degenerate call
    ``segment(..., epoch0=0, limit=max_iter)`` — ONE program serves both,
    so the two paths cannot drift numerically.

    With ``health`` (observability/health.py), the signature grows two
    trailing carries and each round writes its ``(loss, update norm,
    param norm)`` convergence row into the ``hist`` buffer (a replicated
    ``(max_iter, 3)`` carry — the DrJAX-style first-class numeric
    output) and folds ONE non-finite sentinel scalar into ``fin``; the
    host reads both only at segment boundaries, so telemetry adds zero
    extra device syncs.

    With ``fused`` (iteration.segment_fusion_enabled) the per-boundary
    scalars come back STACKED as one int32 vector — ``[epoch, stop]``,
    or ``[epoch, stop, fin]`` with health — so the host pays ONE
    device→host transfer per segment boundary instead of one per
    scalar; the outputs become ``(coeffs, offsets, opt, mean_loss,
    bundle)`` (+ ``hist`` with health). The (coeffs, offsets, opt)
    carry — and the hist buffer with health — is DONATED in every build
    (the in-place update of the raw-speed ladder); sharded builds
    additionally route through ``instrumented_jit`` via their name for
    per-function compile accounting."""
    axes = data_axes(mesh)
    spec0 = data_pspec(mesh)
    p = data_shard_count(mesh)
    model_axis = model_axis_of(mesh)
    wspec = P(model_axis) if model_axis else P()
    round_step = _sgd_round_math(loss_cls(), prm, p, axes, model_axis,
                                 sharded=sharded)
    opt_specs = _opt_specs(prm, wspec, spec0, sharded)

    def run(xl, yl, wl, coeffs, offsets, opt, epoch0, limit, hist, fin):
        def cond(state):
            epoch, stop = state[4], state[5]
            return jnp.logical_and(epoch < limit, jnp.logical_not(stop))

        def step(state):
            coeffs, offset, opt, _, epoch, _, hist, fin = state
            new_coeffs, new_opt, new_offset, mean_loss = round_step(
                xl, yl, wl, coeffs, opt, offset)
            if health:
                row, row_fin = _health.convergence_row(
                    mean_loss, coeffs, new_coeffs, model_axis)
                hist = jax.lax.dynamic_update_slice(
                    hist, row[None], (epoch, jnp.int32(0)))
                fin = jnp.logical_and(fin, row_fin)
            return (new_coeffs, new_offset, new_opt, mean_loss,
                    epoch + 1, mean_loss < prm.tol, hist, fin)

        init = (coeffs, offsets[0], opt,
                jnp.asarray(jnp.inf, coeffs.dtype),
                epoch0, jnp.asarray(False), hist, fin)
        coeffs, offset, opt, mean_loss, epoch, stop, hist, fin = \
            jax.lax.while_loop(cond, step, init)
        return (coeffs, offset[None], opt, mean_loss, epoch, stop, hist,
                fin)

    if health:
        def per_shard(xl, yl, wl, coeffs, offsets, opt, epoch0, limit,
                      hist, fin):
            out = run(xl, yl, wl, coeffs, offsets, opt, epoch0, limit,
                      hist, fin)
            if not fused:
                return out
            coeffs, offsets, opt, mean_loss, epoch, stop, hist, fin = out
            bundle = jnp.stack([epoch, stop.astype(jnp.int32),
                                fin.astype(jnp.int32)])
            return coeffs, offsets, opt, mean_loss, bundle, hist

        extra_in = (P(), P())
        extra_out = (P(),) if fused else (P(), P())
        donate = (3, 4, 5, 8)
    else:
        def per_shard(xl, yl, wl, coeffs, offsets, opt, epoch0, limit):
            out = run(xl, yl, wl, coeffs, offsets, opt, epoch0, limit,
                      jnp.zeros((0, 3), jnp.float32),
                      jnp.asarray(True))[:6]
            if not fused:
                return out
            coeffs, offsets, opt, mean_loss, epoch, stop = out
            bundle = jnp.stack([epoch, stop.astype(jnp.int32)])
            return coeffs, offsets, opt, mean_loss, bundle

        extra_in, extra_out = (), ()
        donate = (3, 4, 5)

    scalar_out = (P(),) if fused else (P(), P())
    return mr.map_shards(
        per_shard, mesh,
        in_specs=(P(spec0, model_axis), P(spec0), P(spec0), wspec,
                  P(spec0), opt_specs, P(), P()) + extra_in,
        out_specs=(wspec, P(spec0), opt_specs, P()) + scalar_out
        + extra_out,
        donate_argnums=donate,
        name="sgd.segment" if sharded else None)


#: plain fits with at most this many rounds compile fully unrolled with
#: STATIC slice starts (the offset schedule is data-independent) — no
#: dynamic-slice machinery, no while-loop: XLA sees max_iter static-offset
#: windows and can pipeline their HBM reads. Large max_iter keeps the
#: while program (compile time scales with the unroll).
_UNROLL_MAX_ROUNDS = int(os.environ.get(
    "FLINK_ML_TPU_SGD_UNROLL_MAX", "64"))

# set on the first pallas lowering failure so later fits skip straight to
# the XLA rounds instead of re-tracing the kernel to the same exception
_pallas_sgd_broken = False


def _static_batch_schedule(local_n: int, lb: int, max_iter: int):
    """The per-shard minibatch schedule as Python ints — valid because the
    reference's slicing (SGD.java:262-284) depends only on (n, batch), not
    on data: round r slices [start, start+lb) with clip-at-end and
    wrap-to-zero. Returns [(start, first_valid)] per round; rows before
    ``first_valid`` (clip overlap) weigh 0. Requires offset 0 at entry and
    a uniform lb (gb % p == 0)."""
    sched, offset = [], 0
    for _ in range(max_iter):
        start = min(offset, local_n - lb)
        sched.append((start, offset - start))  # offset-start == 0 unless clipped
        offset = 0 if offset + lb >= local_n else offset + lb
    return sched


@functools.lru_cache(maxsize=128)
def _build_sgd_unrolled_program(loss_cls, mesh: Mesh, prm: SGDParams,
                                use_kernel: bool = False,
                                health: bool = False,
                                sharded: bool = False):
    """The plain (uncheckpointed, fresh-offset) fit as ONE fully-unrolled
    SPMD program: ``fit(xs, ys, ws, coeffs, offsets, opt) -> (coeffs,
    offsets, opt, mean_loss, epoch, stop)`` — the same carry as the
    segment program (``opt`` = the stateful rule's moment tuple, ``()``
    for plain sgd). The tol early-exit becomes masking (rounds after
    the stop compute and are discarded by ``where`` — moments
    included), so the result — coeffs, final offsets, the loss AT the
    stopping round, the executed-round count — is identical to the
    while program's by construction. Only valid for offsets == 0 and
    gb %% p == 0 (the dispatch in ``optimize`` guarantees both). With
    ``health`` the outputs grow ``(..., hist, fin)``: the stacked
    per-round ``(max_iter, 3)`` convergence rows (NaN past the stopping
    round) and the single non-finite sentinel folded over the executed
    rounds (observability/health.py).

    With ``use_kernel`` (TPU, DP-only mesh), rounds whose window aligns
    to a shared tile run the fused pallas batch-terms kernel — one pass
    over the window instead of a slice copy plus two reads; the psum and
    the model update stay in the one shared tail
    (``_sgd_update_math.apply_packed``), so results agree with the XLA
    rounds up to float reassociation in the per-tile partial sums."""
    axes = data_axes(mesh)
    spec0 = data_pspec(mesh)
    p = data_shard_count(mesh)
    model_axis = model_axis_of(mesh)
    wspec = P(model_axis) if model_axis else P()
    lb_base = prm.global_batch_size // p
    assert prm.global_batch_size % p == 0
    update, apply_packed = _sgd_update_math(loss_cls(), prm, axes,
                                            model_axis, sharded=sharded)
    opt_specs = _opt_specs(prm, wspec, spec0, sharded)

    def per_shard(xl, yl, wl, coeffs, offsets, opt):
        local_n = xl.shape[0]
        lb = min(lb_base, local_n)
        tile = 0
        if use_kernel and model_axis is None:
            from flink_ml_tpu.ops.pallas_kernels import sgd_round_tile
            tile = sgd_round_tile(lb, local_n, xl.shape[1])
        sched = _static_batch_schedule(local_n, lb, prm.max_iter)
        offset = offsets[0]
        mean_loss = jnp.asarray(jnp.inf, coeffs.dtype)
        epoch = jnp.int32(0)
        stop = jnp.asarray(False)
        rows = []
        fin = jnp.asarray(True)
        for start, clip in sched:
            if tile:
                from flink_ml_tpu.ops.pallas_kernels import sgd_batch_terms
                # the kernel sees the TRUE feature dim — coeffs may be
                # padded for the sharded update; apply_packed re-pads
                # the local [grad | w | loss] partials it returns
                packed = sgd_batch_terms(xl, yl, wl,
                                         coeffs[:xl.shape[1]], start,
                                         clip, lb, tile, loss_cls.NAME)
                updated, new_opt, new_loss = apply_packed(coeffs, opt,
                                                          packed)
            else:
                xb = jax.lax.slice_in_dim(xl, start, start + lb, axis=0)
                yb = jax.lax.slice_in_dim(yl, start, start + lb, axis=0)
                wb = jax.lax.slice_in_dim(wl, start, start + lb, axis=0)
                if clip:  # short batch at the end: clipped rows weigh 0
                    wb = wb * (np.arange(lb) >= clip).astype(xl.dtype)
                updated, new_opt, new_loss = update(coeffs, opt, xb, yb,
                                                    wb)
            new_off = jnp.int32(0 if start + clip + lb >= local_n
                                else start + clip + lb)
            active = jnp.logical_not(stop)
            if health:
                # first-class numeric telemetry: the round's convergence
                # row + ONE isfinite fold over loss and every parameter
                # element; rounds past the tol stop record NaN rows and
                # never poison the sentinel (they are masked out anyway)
                row, row_fin = _health.convergence_row(
                    new_loss, coeffs, updated, model_axis)
                rows.append(jnp.where(
                    active, row, jnp.full((3,), jnp.nan, jnp.float32)))
                fin = jnp.logical_and(fin, jnp.logical_or(
                    jnp.logical_not(active), row_fin))
            coeffs = jnp.where(active, updated, coeffs)
            opt = jax.tree_util.tree_map(
                lambda n, o: jnp.where(active, n, o), new_opt, opt)
            offset = jnp.where(active, new_off, offset)
            mean_loss = jnp.where(active, new_loss, mean_loss)
            epoch = epoch + active.astype(jnp.int32)
            stop = jnp.logical_or(stop, jnp.logical_and(
                active, new_loss < prm.tol))
        if health:
            return (coeffs, offset[None], opt, mean_loss, epoch, stop,
                    jnp.stack(rows), fin)
        return coeffs, offset[None], opt, mean_loss, epoch, stop

    # the (coeffs, offsets, opt) carry donates in EVERY build — the
    # update happens in place in the donated buffers; callers rebuild
    # the carry on the pallas-fallback retry (make_init in optimize)
    return mr.map_shards(
        per_shard, mesh,
        in_specs=(P(spec0, model_axis), P(spec0), P(spec0), wspec,
                  P(spec0), opt_specs),
        out_specs=(wspec, P(spec0), opt_specs, P(), P(), P())
        + ((P(), P()) if health else ()),
        donate_argnums=(3, 4, 5),
        name="sgd.unrolled" if sharded else None)


@functools.lru_cache(maxsize=128)
def _build_sgd_round_program(loss_cls, mesh: Mesh, prm: SGDParams,
                             sharded: bool = False):
    """ONE training round as a mapped (un-jitted) callable — the
    building block of the checkpointable host loop (iterate_bounded jits
    the round itself). Wraps the same _sgd_round_math as the all-device
    program, so device and host modes are numerically identical by
    construction."""
    axes = data_axes(mesh)
    spec0 = data_pspec(mesh)
    p = data_shard_count(mesh)
    model_axis = model_axis_of(mesh)
    wspec = P(model_axis) if model_axis else P()
    round_step = _sgd_round_math(loss_cls(), prm, p, axes, model_axis,
                                 sharded=sharded)
    opt_specs = _opt_specs(prm, wspec, spec0, sharded)

    def per_shard(xl, yl, wl, coeffs, offsets, opt):
        coeffs, opt, new_offset, mean_loss = round_step(
            xl, yl, wl, coeffs, opt, offsets[0])
        return coeffs, new_offset[None], mean_loss, opt

    return mr.map_shards(
        per_shard, mesh,
        in_specs=(P(spec0, model_axis), P(spec0), P(spec0), wspec,
                  P(spec0), opt_specs),
        out_specs=(wspec, P(spec0), P(), opt_specs), jit=False)


@functools.lru_cache(maxsize=128)
def _tp_prepare_program(rem: int, pad_d: int, sharding):
    """Compiled cast+pad for a device-resident feature matrix entering the
    tensor-parallel layout (rows to the data axes, features to the model
    axis) — no host round-trip. Row-major output layout (see
    collective.row_major_format)."""
    from flink_ml_tpu.parallel.collective import row_major_format

    def prep(a):
        a = a.astype(jnp.float32)
        if rem or pad_d:
            a = jnp.pad(a, ((0, rem), (0, pad_d)))
        return a

    return jax.jit(prep, out_shardings=row_major_format(sharding, 2))


def _health_tag(loss_func: LossFunc, tag: Optional[str]) -> str:
    if tag:
        return tag
    name = getattr(type(loss_func), "NAME", None)
    return f"SGD[{name or type(loss_func).__name__}]"


def _finish_fit_health(algo: str, health_on: bool, hist, fin, epochs,
                       mean_loss, coeffs_host, epoch0: int = 0) -> None:
    """The shared health tail of every SGD fit path: with telemetry
    armed, record the executed slice of the device-produced convergence
    history and classify divergence (raising the terminal NonFiniteState
    when the in-program sentinel tripped); otherwise run the cheap
    always-on guard over the already-fetched final state."""
    if health_on and hist is not None:
        h = np.asarray(hist, np.float64)
        lo = min(int(epoch0), h.shape[0])
        hi = min(int(epochs), h.shape[0])
        _health.check_fit(
            algo, {"loss": h[lo:hi, 0], "updateNorm": h[lo:hi, 1],
                   "paramNorm": h[lo:hi, 2]},
            finite=bool(fin), epoch0=lo)
    else:
        _health.guard_final_state(algo, coeffs_host, loss=mean_loss)


class SGD:
    """Ref: Optimizer/SGD — optimize(initModel, trainData) → fitted coeffs."""

    def __init__(self, params: SGDParams):
        self.params = params

    def optimize_csr(self, loss_func: LossFunc, init_coeffs: np.ndarray,
                     features_csr, labels: np.ndarray,
                     weights: Optional[np.ndarray] = None,
                     mesh: Optional[Mesh] = None,
                     config=None, listeners=(),
                     tag: Optional[str] = None):
        """Host CSR fallback for wide sparse input (HashingTF at 2^18 dims
        would need terabytes dense — ref trains SparseVector natively,
        OnlineLogisticRegression.java:364-388 / BLAS.java:78).

        Mirrors ``_sgd_round_math`` exactly — the same contiguous-chunk
        sharding as ``shard_batch`` (p padded shards of length ⌈n/p⌉), the
        same per-task batch share/clip/wrap (SGD.java:206-213,262-284) and
        the same update/termination — so sparse and dense fits agree on
        small dims (parity-tested). Math in float64 on host; gradients via
        scipy's CSR matvec kernels.

        ``config``/``listeners`` run the rounds through ``iterate_bounded``
        with an un-jitted host body (jit_round=False): the sparse fit
        checkpoints/resumes mid-iteration exactly like the dense path — the
        reference's state persistence is representation-agnostic
        (SGD.java:308-360) and so is ours.
        """
        prm = self.params
        # the mesh fixes the simulated task count p: a PURE function of the
        # mesh configuration, never of process state — sparse and dense
        # fits must slice batches identically (the parity contract below)
        # and a checkpointed carry must resume under the same p
        mesh = mesh or default_mesh()
        p = data_shard_count(mesh)
        n, d = features_csr.shape
        ls = -(-n // p) if n else 1  # padded local length (shard_batch)
        lb_base, lb_rem = prm.global_batch_size // p, \
            prm.global_batch_size % p
        y = np.asarray(labels, np.float64)
        w = (np.ones(n, np.float64) if weights is None
             else np.asarray(weights, np.float64))
        X = features_csr.tocsr()

        _check_method(prm)
        rule = _update_rule(prm, xp=np)

        def round_body(carry, epoch):
            coeffs, offsets, _, opt = carry
            offsets = offsets.copy()  # carry is functional (checkpointable)
            row_parts = []
            for s in range(p):
                lb = min(lb_base + (1 if s < lb_rem else 0), ls)
                rel = np.arange(lb)
                idx = offsets[s] + rel
                gidx = s * ls + idx[idx < ls]  # clip at shard end
                row_parts.append(gidx[gidx < n])  # padding rows weigh 0
                offsets[s] = 0 if offsets[s] + lb >= ls else offsets[s] + lb
            rows = np.concatenate(row_parts)
            Xb, yb, wb = X[rows], y[rows], w[rows]
            dots = Xb @ coeffs
            loss_sum, multipliers = loss_func.terms(dots, yb, wb, xp=np)
            loss_sum = float(loss_sum)
            grad = Xb.T @ np.asarray(multipliers, np.float64)
            total_w = float(wb.sum())
            if total_w > 0:
                updated, opt = rule(grad, np.float64(total_w), coeffs,
                                    opt)
                updated, _ = regularize(updated, prm.reg, prm.elastic_net,
                                        prm.learning_rate, xp=np)
                coeffs = np.asarray(updated, np.float64)
            mean_loss = loss_sum / max(total_w, 1e-30)
            return coeffs, offsets, np.float64(mean_loss), opt

        from flink_ml_tpu.iteration.iteration import iterate_bounded

        algo = _health_tag(loss_func, tag)
        health_on = _health.armed()
        if health_on:
            # host rounds: convergence telemetry rides a listener at the
            # epoch boundary — the carry is already host float64 here
            listeners = tuple(listeners) + (
                _health.ConvergenceListener.for_params(algo, init_coeffs),)

        opt0 = tuple(np.zeros(d, np.float64)
                     for _ in range(_OPT_VECTORS[prm.method]))
        if prm.method == "adam":
            opt0 = opt0 + (np.float64(0.0),)
        init = (np.asarray(init_coeffs, np.float64).copy(),
                np.zeros(p, np.int64), np.float64(np.inf), opt0)
        coeffs, _, mean_loss, _ = iterate_bounded(
            init, round_body, max_iter=prm.max_iter,
            terminate=lambda carry, epoch: carry[2] < prm.tol,
            config=config, listeners=listeners, jit_round=False)
        self.last_execution_path = "csr-host"
        if not health_on:
            _health.guard_final_state(algo, coeffs, loss=mean_loss)
        return coeffs, float(mean_loss)

    def optimize(self, loss_func: LossFunc, init_coeffs: np.ndarray,
                 features: np.ndarray, labels: np.ndarray,
                 weights: Optional[np.ndarray] = None,
                 mesh: Optional[Mesh] = None,
                 dtype=jnp.float32,
                 config=None, listeners=(),
                 tag: Optional[str] = None):
        """Returns (coeffs (d,) np.ndarray, final mean loss float).

        With ``config``/``listeners`` (an ``IterationConfig`` needing host
        hooks — checkpointing, per-round callbacks), training runs as host-
        driven rounds through ``iterate_bounded``: resumable mid-fit from a
        checkpoint with results identical to the all-device program (the
        fault-injection bar of BoundedAllRoundCheckpointITCase).

        ``tag`` labels this fit's model-health telemetry (the estimator
        class name from models/common.py); with telemetry armed
        (observability/health.py) the compiled programs return per-epoch
        convergence rows + a non-finite sentinel, and every path raises
        the terminal ``NonFiniteState`` on a NaN/Inf state instead of
        returning garbage coefficients."""
        algo = _health_tag(loss_func, tag)
        health_on = _health.armed()
        mesh = mesh or default_mesh()
        n = features.shape[0]
        d = features.shape[1]

        axes = data_axes(mesh)
        init_coeffs = np.asarray(init_coeffs)
        tp = model_axis_of(mesh) is not None
        # cross-replica sharded update (update_sharding.py; DP meshes
        # only — a TP mesh already splits the feature dim): pad the
        # coefficient carry to the shard multiple so the gradient
        # reduce-scatter and the per-replica slices line up (padded
        # coords stay exactly zero: zero grad → soft-threshold(0) = 0)
        sharded = _upd.enabled() and not tp
        if sharded:
            pad = (-d) % data_shard_count(mesh)
            if pad:
                init_coeffs = np.pad(init_coeffs, (0, pad))
        from jax.sharding import NamedSharding
        if tp:
            # tensor parallelism: feature dim padded to the model-axis size
            # and sharded over it (padded coords stay exactly zero: zero
            # features → zero grad → soft-threshold(0) = 0)
            tp_size = int(mesh.shape[MODEL_AXIS])
            pad = (-d) % tp_size
            if pad:
                init_coeffs = np.pad(init_coeffs, (0, pad))
            spec0 = data_pspec(mesh)
            rem = (-n) % data_shard_count(mesh)
            x_sharding = NamedSharding(mesh, P(spec0, MODEL_AXIS))
            from flink_ml_tpu.parallel.collective import row_major_format
            x_format = row_major_format(x_sharding, 2)
            if isinstance(features, jax.Array):
                # device-resident input: cast/pad/reshard on device — the
                # same residency contract as the DP branch; layout pinned
                # row-major like every other producer (a bare
                # NamedSharding put preserves a compiler-chosen
                # column-major layout and the fit re-pays the relayout)
                if pad or rem or features.dtype != jnp.float32:
                    features = _tp_prepare_program(
                        rem, pad, x_sharding)(features)
                xs = jax.device_put(features, x_format)
            else:
                features = np.asarray(features, np.float32)
                if pad or rem:
                    features = np.pad(features, ((0, rem), (0, pad)))
                xs = jax.device_put(features, x_format)
            w_sharding = NamedSharding(mesh, P(MODEL_AXIS))
        else:
            # device-resident features/labels (device datagen or a previous
            # device stage) stay on device end-to-end — no host round-trip
            xs, _ = ensure_on_mesh(mesh, features, axes, jnp.float32)
            w_sharding = NamedSharding(mesh, P())
        ys, _ = ensure_on_mesh(mesh, labels, axes, jnp.float32)
        if weights is None:
            ws = ones_on_mesh(mesh, n, axes, jnp.float32)
        else:
            ws, _ = ensure_on_mesh(mesh, weights, axes, jnp.float32)
        from flink_ml_tpu.iteration.iteration import (
            device_checkpoint_segment, needs_host_loop, run_segmented)
        p = data_shard_count(mesh)
        spec0 = data_pspec(mesh)

        # carry leaves must live on the full mesh (replicated or
        # model-sharded coeffs, per-task offsets, moment vectors sharded
        # 1/N under the sharded update) — both for the mapped
        # round/segment and so that checkpoint restore re-places leaves
        # onto the right shardings (a sharded-adam resume puts each
        # moment slice back on its owning replica). A closure, not a
        # tuple: the compiled programs DONATE the carry, so the pallas
        # fallback retry must rebuild it rather than re-pass consumed
        # buffers. The opt tuple rides at the END of the carry so a
        # method="sgd" checkpoint keeps the stateless-era leaf order.
        def make_init():
            opt_sharding = (NamedSharding(mesh, P(spec0)) if sharded
                            else w_sharding)
            opt = tuple(
                jax.device_put(jnp.zeros(init_coeffs.shape[0], dtype),
                               opt_sharding)
                for _ in range(_OPT_VECTORS[self.params.method]))
            if self.params.method == "adam":
                opt = opt + (jax.device_put(jnp.asarray(0.0, dtype),
                                            NamedSharding(mesh, P())),)
            return (
                jax.device_put(jnp.asarray(init_coeffs, dtype),
                               w_sharding),
                jax.device_put(jnp.zeros((p,), jnp.int32),
                               NamedSharding(mesh, P(spec0))),
                jax.device_put(jnp.asarray(jnp.inf, dtype),
                               NamedSharding(mesh, P())),
                opt,
            )

        _check_method(self.params)
        init = make_init()
        w0 = init[0]
        # per-replica update-state accounting (benchmark provenance):
        # measured from the carry's real buffers — SGD's coefficients
        # all-gather back to replicated every round, so this honestly
        # reports full size even under the sharded update; the moment
        # vectors are the state that genuinely shrinks 1/N (their
        # slices never all-gather), recorded both folded into the algo
        # total and as a standalone ".moments" record so the multihost
        # bench can gate on the moment bytes alone
        opt_leaves = list(jax.tree_util.tree_leaves(init[3]))
        if opt_leaves:
            _upd.record_state_bytes(f"{algo}.moments", opt_leaves, p,
                                    sharded)
        _upd.record_state_bytes(algo, [w0] + opt_leaves, p, sharded)

        seg_k = device_checkpoint_segment(config, listeners)
        if seg_k or not needs_host_loop(config, listeners):
            # the compiled fast path: a plain fit is one max_iter segment;
            # a checkpointed fit runs K-round segments with the carry
            # snapshotted between them (same single program either way).
            # A plain fit with a uniform batch share and a bounded round
            # count compiles fully UNROLLED instead: the offset schedule
            # is data-independent, so every slice start is static — no
            # dynamic-slice machinery, no while-loop (results identical
            # by construction; see _build_sgd_unrolled_program).
            if (not seg_k and self.params.global_batch_size % p == 0
                    and 0 < self.params.max_iter <= _UNROLL_MAX_ROUNDS):
                from flink_ml_tpu.ops.pallas_kernels import (
                    is_pallas_failure, pallas_supported)
                global _pallas_sgd_broken
                use_kernel = (pallas_supported() and not tp
                              and not _pallas_sgd_broken)
                try:
                    prog = _build_sgd_unrolled_program(
                        type(loss_func), mesh, self.params,
                        use_kernel=use_kernel, health=health_on,
                        sharded=sharded)
                    # materialize INSIDE the try: async dispatch surfaces
                    # kernel-execution failures only here
                    res = prog(xs, ys, ws, init[0], init[1], init[3])
                    coeffs, _, _, mean_loss, epoch, _ = res[:6]
                    hist, fin = (res[6:] if health_on else (None, True))
                    self.last_execution_path = (
                        "pallas-unrolled" if use_kernel else "xla-unrolled")
                    out = np.asarray(coeffs, np.float64)[:d]
                    _finish_fit_health(algo, health_on, hist, fin, epoch,
                                       mean_loss, out)
                    return out, float(mean_loss)
                except Exception as e:
                    if not use_kernel or not is_pallas_failure(e):
                        raise
                    import logging

                    logging.getLogger(__name__).warning(
                        "pallas SGD kernel failed; using the XLA rounds "
                        "for the rest of this process", exc_info=True)
                    _pallas_sgd_broken = True
                    prog = _build_sgd_unrolled_program(
                        type(loss_func), mesh, self.params,
                        use_kernel=False, health=health_on,
                        sharded=sharded)
                    # the failed attempt may have consumed the donated
                    # carry (the programs donate it) — rebuild
                    init = make_init()
                    res = prog(xs, ys, ws, init[0], init[1], init[3])
                    coeffs, _, _, mean_loss, epoch, _ = res[:6]
                    hist, fin = (res[6:] if health_on else (None, True))
                self.last_execution_path = "xla-unrolled"
                out = np.asarray(coeffs, np.float64)[:d]
                _finish_fit_health(algo, health_on, hist, fin, epoch,
                                   mean_loss, out)
                return out, float(mean_loss)
            from flink_ml_tpu.iteration.iteration import (
                read_boundary, segment_fusion_enabled)
            fused = segment_fusion_enabled()
            seg_prog = _build_sgd_segment_program(type(loss_func), mesh,
                                                  self.params,
                                                  health=health_on,
                                                  sharded=sharded,
                                                  fused=fused)
            # health carry lives OUTSIDE the checkpointed carry so the
            # snapshot format is identical with telemetry on or off; a
            # restore simply resumes the series at its epoch (earlier
            # rows stay NaN and are sliced off by `first`)
            repl = NamedSharding(mesh, P())
            # built under jit, not device_put: putting a host NaN array
            # onto a multi-process sharding trips jax's cross-process
            # value check (NaN != NaN in multihost_utils.assert_equal)
            hist_rows = self.params.max_iter if health_on else 0
            hstate = {
                "hist": jax.jit(
                    functools.partial(jnp.full, (hist_rows, 3),
                                      jnp.nan, jnp.float32),
                    out_shardings=repl)(),
                "fin": True, "first": None, "epoch": 0,
            }

            def run_segment(carry, epoch0, limit):
                coeffs, offsets, _, opt = carry
                if hstate["first"] is None:
                    hstate["first"] = int(epoch0)
                if health_on:
                    out = seg_prog(
                        xs, ys, ws, coeffs, offsets, opt,
                        jnp.int32(epoch0), jnp.int32(limit),
                        hstate["hist"], jnp.asarray(bool(hstate["fin"])))
                    if fused:
                        # ONE stacked [epoch, stop, fin] transfer per
                        # boundary instead of three scalar fetches
                        (coeffs, offsets, opt, mean_loss, bundle,
                         hstate["hist"]) = out
                        vals = read_boundary(bundle)
                        epoch, stop = int(vals[0]), bool(vals[1])
                        hstate["fin"] = bool(vals[2])
                    else:
                        (coeffs, offsets, opt, mean_loss, epoch, stop,
                         hstate["hist"], fin) = out
                        vals = read_boundary((epoch, stop, fin))
                        epoch, stop = int(vals[0]), bool(vals[1])
                        hstate["fin"] = bool(vals[2])
                    # epoch-boundary health check: the segment boundary
                    # is this mode's host sync point, so reading the
                    # sentinel costs no extra round-trip (it rides the
                    # fused bundle) — and a NaN state fails the fit NOW
                    # instead of burning the remaining segments
                    hstate["epoch"] = epoch
                    if not hstate["fin"]:
                        _finish_fit_health(
                            algo, True, hstate["hist"], False,
                            hstate["epoch"], mean_loss, None,
                            epoch0=hstate["first"])
                else:
                    out = seg_prog(
                        xs, ys, ws, coeffs, offsets, opt,
                        jnp.int32(epoch0), jnp.int32(limit))
                    if fused:
                        coeffs, offsets, opt, mean_loss, bundle = out
                        vals = read_boundary(bundle)
                    else:
                        (coeffs, offsets, opt, mean_loss, epoch,
                         stop) = out
                        vals = read_boundary((epoch, stop))
                    epoch, stop = int(vals[0]), bool(vals[1])
                return (coeffs, offsets, mean_loss, opt), epoch, stop

            if seg_k:
                coeffs, _, mean_loss, _ = run_segmented(
                    run_segment, init, self.params.max_iter, seg_k,
                    config.checkpoint_manager)
            else:
                (coeffs, _, mean_loss, _), _, _ = run_segment(
                    init, 0, self.params.max_iter)
            self.last_execution_path = ("xla-while-segments" if seg_k
                                        else "xla-while")
            out = np.asarray(coeffs, np.float64)[:d]
            _finish_fit_health(
                algo, health_on, hstate["hist"] if health_on else None,
                hstate["fin"], hstate["epoch"], mean_loss, out,
                epoch0=hstate["first"] or 0)
            return out, float(mean_loss)

        from flink_ml_tpu.iteration.iteration import iterate_bounded

        round_fn = _build_sgd_round_program(type(loss_func), mesh,
                                            self.params, sharded=sharded)

        def body(carry, epoch):
            coeffs, offsets, _, opt = carry
            coeffs, offsets, mean_loss, opt = round_fn(xs, ys, ws,
                                                       coeffs, offsets,
                                                       opt)
            return coeffs, offsets, mean_loss, opt

        if health_on:
            # host-driven rounds: the health series rides an extra
            # listener instead of a program variant (the listeners are
            # what forced this mode); it reads lagged carries so the
            # loop's listener-vs-device overlap survives
            listeners = tuple(listeners) + (
                _health.ConvergenceListener.for_params(
                    algo, np.asarray(w0)),)

        final = iterate_bounded(
            init, body, max_iter=self.params.max_iter,
            terminate=lambda carry, epoch: carry[2] < self.params.tol,
            config=config, listeners=listeners)
        coeffs, _, mean_loss, _ = final
        self.last_execution_path = "host-rounds"
        out = np.asarray(coeffs, np.float64)[:d]
        if not health_on:
            _health.guard_final_state(algo, out, loss=mean_loss)
        return out, float(mean_loss)
