"""ε-approximate quantiles.

Ref parity: flink-ml-lib/.../common/util/QuantileSummary.java:42 — the
Greenwald-Khanna summary (insert buffer, compress threshold 10000, merge,
query) backing the ``relativeError`` param of RobustScaler, Imputer and
KBinsDiscretizer.

Two tiers:
- :class:`QuantileSummary` — a faithful GK sketch for streaming/merge use
  (online pipelines, bounded memory).
- :func:`approx_quantiles` — the batch path: exact numpy quantiles over the
  materialized column (an exact answer trivially satisfies any ε bound; the
  reference only sketches because its input is an unbounded stream).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np


@dataclasses.dataclass
class _Tuple:
    value: float
    g: int       # rank gap to the previous tuple
    delta: int   # max rank uncertainty


class QuantileSummary:
    """Greenwald-Khanna ε-approximate quantile sketch
    (ref: QuantileSummary.java — defaultCompressThreshold 10000)."""

    COMPRESS_THRESHOLD = 10000

    def __init__(self, relative_error: float = 0.001,
                 compress_threshold: int = COMPRESS_THRESHOLD):
        if not 0 < relative_error <= 1:
            raise ValueError("relative_error must be in (0, 1]")
        self.eps = relative_error
        self.compress_threshold = compress_threshold
        self._sampled: List[_Tuple] = []
        self._buffer: List[float] = []
        self.count = 0

    # -- build ---------------------------------------------------------------
    def insert(self, value: float) -> None:
        self._buffer.append(value)
        if len(self._buffer) >= self.compress_threshold:
            self._flush()

    def insert_all(self, values) -> None:
        for v in np.asarray(values, np.float64).ravel():
            self.insert(float(v))

    def _flush(self) -> None:
        if not self._buffer:
            return
        self._buffer.sort()
        sampled = self._sampled
        merged: List[_Tuple] = []
        threshold = 2 * self.eps * max(self.count + len(self._buffer), 1)
        si, n_new = 0, len(self._buffer)
        for bi, value in enumerate(self._buffer):
            while si < len(sampled) and sampled[si].value <= value:
                merged.append(sampled[si])
                si += 1
            # head/tail inserts get delta 0 so min/max queries stay exact
            # (ref QuantileSummary.java insertion rule)
            is_min = not merged
            is_max = bi == n_new - 1 and si >= len(sampled)
            if is_min or is_max:
                delta = 0
            else:
                delta = max(int(np.floor(threshold)) - 1, 0)
            merged.append(_Tuple(value, 1, delta))
        merged.extend(sampled[si:])
        self.count += n_new
        self._buffer = []
        self._sampled = merged
        self._compress()

    def _compress(self) -> None:
        if len(self._sampled) < 2:
            return
        threshold = 2 * self.eps * self.count
        out = [self._sampled[0]]
        for t in self._sampled[1:-1]:
            last = out[-1]
            if last is not self._sampled[0] and \
                    last.g + t.g + t.delta < threshold:
                out[-1] = _Tuple(t.value, last.g + t.g, t.delta)
            else:
                out.append(t)
        out.append(self._sampled[-1])
        self._sampled = out

    def merge(self, other: "QuantileSummary") -> "QuantileSummary":
        result = QuantileSummary(min(self.eps, other.eps),
                                 self.compress_threshold)
        for s in (self, other):
            s._flush()
        merged = sorted(self._sampled + other._sampled,
                        key=lambda t: t.value)
        result._sampled = merged
        result.count = self.count + other.count
        result._compress()
        return result

    # -- query ---------------------------------------------------------------
    def query(self, prob: float) -> float:
        if not 0 <= prob <= 1:
            raise ValueError("prob must be in [0, 1]")
        self._flush()
        if not self._sampled:
            raise ValueError("query on empty summary")
        rank = prob * (self.count - 1) + 1
        # boundary ranks are exact (head/tail tuples carry delta 0)
        if rank <= 1:
            return self._sampled[0].value
        if rank >= self.count:
            return self._sampled[-1].value
        margin = self.eps * self.count
        min_rank = 0
        for t in self._sampled:
            min_rank += t.g
            max_rank = min_rank + t.delta
            if max_rank - margin <= rank <= min_rank + margin:
                return t.value
        return self._sampled[-1].value

    def query_all(self, probs: Sequence[float]) -> np.ndarray:
        return np.asarray([self.query(p) for p in probs])


def approx_quantiles(x: np.ndarray, probs: Sequence[float],
                     relative_error: float = 0.001) -> np.ndarray:
    """Per-column quantiles of a (n, d) array → (len(probs), d).

    Batch path: numpy's exact linear-interpolation-free 'lower' quantile
    matches the GK sketch's behavior of returning an actual data value.
    """
    x = np.asarray(x, np.float64)
    if x.ndim == 1:
        x = x[:, None]
    return np.quantile(x, np.asarray(probs), axis=0, method="lower")


def rank_select_device(x, probs: Sequence[float]):
    """Per-column order statistics of a DEVICE (n, d) float32 array →
    (m, d) device array, WITHOUT a device sort.

    ``jnp.quantile`` sorts every column — the whole fit cost of
    RobustScaler at benchmark scale (a (10M, 100) sort made it 22x
    slower than its sibling scalers, r3 sweep).  Instead: 32 rounds of
    bisection on the ORDER-PRESERVING uint32 bit image of float32 (the
    sign-magnitude flip radix-sort uses), each one fused compare-count
    pass over x inside a jitted ``fori_loop``.  XLA fuses the
    broadcast-compare into the (d, m) count reduction — nothing of shape
    (n, d, m) materializes.  Integer bisection converges EXACTLY to the
    bit pattern of the floor(q*(n-1))-th smallest element — the same
    element-of-dataset semantics as numpy's method='lower' and the
    reference's GK summary (QuantileSummary.java:42) — independent of
    the column's value range: outliers, denormals and infinities cost
    nothing (keys are just 32-bit integers; no midpoint overflow, no
    lost resolution).  NaN bit patterns sort outside the finite band
    (negative-payload NaNs below -inf, positive above +inf), matching a
    sort-based quantile's endpoint behavior.
    """
    from flink_ml_tpu.ops import columnar

    n = int(x.shape[0])
    ranks = np.floor(np.asarray(probs, np.float64) * (n - 1)) \
        .astype(np.int32)
    return columnar.apply(_rank_select_kernel, x, (ranks,))


def _rank_select_kernel(x, ranks):
    import jax
    import jax.numpy as jnp

    m = ranks.shape[0]
    # order-preserving uint32 image: non-negative floats map above
    # 0x80000000 keeping magnitude order; negative floats flip so larger
    # magnitude sorts lower. Total order == IEEE float order.
    u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    keys = jnp.where(u >= jnp.uint32(0x80000000),
                     jnp.uint32(0xFFFFFFFF) - u,
                     u + jnp.uint32(0x80000000))
    target = (ranks + 1)[:, None]                  # (m, 1)
    d = x.shape[1]
    LO = jnp.zeros((m, d), jnp.uint32)
    HI = jnp.full((m, d), jnp.uint32(0xFFFFFFFF))

    def step(_, state):
        LO, HI = state
        mid = LO + (HI - LO) // jnp.uint32(2)
        # (n, d, m) broadcast-compare fused into the count reduction
        cnt = jnp.sum(
            (keys[:, :, None] <= mid.T[None, :, :]).astype(jnp.int32),
            axis=0)
        ok = cnt.T >= target                       # (m, d)
        HI = jnp.where(ok, mid, HI)
        LO = jnp.where(ok, LO, mid + jnp.uint32(1))
        return LO, HI

    # 32 halvings of a 2^32 bracket: LO == HI == the answer's bit image
    _, HI = jax.lax.fori_loop(0, 32, step, (LO, HI))
    back = jnp.where(HI >= jnp.uint32(0x80000000),
                     HI - jnp.uint32(0x80000000),
                     jnp.uint32(0xFFFFFFFF) - HI)
    return jax.lax.bitcast_convert_type(back, jnp.float32)
