"""Pallas TPU kernels.

The framework's hot device loops are mostly single fused matmuls that XLA
already schedules well (SURVEY.md §7 layer 1: "Pallas where XLA fusion is
insufficient"). The case where hand-tiling pays is nearest-centroid
assignment with large k: XLA materializes the (n, k) distance matrix in HBM
between the matmul and the argmin; this kernel keeps each (tile_n, k)
distance block in VMEM and writes only the argmin — HBM traffic drops from
O(n·k) to O(n·d + k·d + n).

Used by KMeans/KNN paths when running on a real TPU backend; elsewhere the
plain XLA path runs. Tests exercise the kernel in interpreter mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_N = 1024


def _assign_kernel(x_ref, c_ref, csq_ref, out_ref):
    x = x_ref[:]                       # (tile_n, d)
    c = c_ref[:]                       # (k, d)
    # ‖x−c‖² up to the per-point constant ‖x‖² (irrelevant to the argmin)
    cross = jnp.dot(x, c.T, preferred_element_type=jnp.float32)
    d2 = csq_ref[:][None, :] - 2.0 * cross
    out_ref[:, 0] = jnp.argmin(d2, axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _assign_padded(x, centroids, interpret=False):
    n, d = x.shape
    k = centroids.shape[0]
    csq = jnp.sum(centroids * centroids, axis=1)
    grid = (n // TILE_N,)
    return pl.pallas_call(
        _assign_kernel,
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_N, d), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((k,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((TILE_N, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(x, centroids, csq)


def assign_nearest(x, centroids, interpret: bool = False):
    """Nearest-centroid index per row of x — fused distance+argmin.

    x: (n, d) float32; centroids: (k, d) float32 → (n,) int32.
    Pads n up to the tile size; callers slice with the true n.
    """
    x = jnp.asarray(x, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    n = x.shape[0]
    pad = (-n) % TILE_N
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    out = _assign_padded(x, centroids, interpret=interpret)
    return out[:n, 0]


def pallas_supported() -> bool:
    """True when the default backend can run compiled pallas kernels.
    FLINK_ML_TPU_DISABLE_PALLAS=1 is the central kill-switch — set by an
    operator, or by scripts/tpu_kernel_check.py's caller when the
    on-chip parity check fails (wrong RESULTS can't be caught by the
    exception-driven fallbacks)."""
    import os

    if os.environ.get("FLINK_ML_TPU_DISABLE_PALLAS") == "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # dead accelerator plugin raises here (mesh.py
        return False      # _all_devices) — no backend, no pallas


def is_pallas_failure(e: Exception) -> bool:
    """Heuristic: does this exception come from the pallas/Mosaic stack
    (lowering, compile, or kernel execution — including a Mosaic VMEM
    exhaustion) rather than from the surrounding program (e.g. an HBM
    RESOURCE_EXHAUSTED on a too-large dataset, whose message carries no
    Mosaic/vmem marker)? Drives the try-kernel-then-XLA fallbacks."""
    text = f"{type(e).__name__}: {e}"
    if "RESOURCE_EXHAUSTED" in text and "vmem" not in text.lower():
        # an HBM OOM can mention the pallas op in its allocation
        # breakdown without the kernel being at fault — only a VMEM
        # exhaustion is the kernel's own
        return False
    return any(s in text for s in ("Mosaic", "mosaic", "pallas", "Pallas",
                                   "memory space vmem"))


def is_surrounding_failure(e: Exception) -> bool:
    """Positive identification of a failure in the SURROUNDING program —
    today an HBM RESOURCE_EXHAUSTED (without a VMEM marker) from placing
    the inputs. Predict paths whose ``try`` wraps only the kernel call
    use this as the re-raise test: there, an unrecognized error is far
    more likely a kernel failure than a program one, so the default is
    fall-back-and-flag (the inverse of the fit paths, whose ``try``
    spans the whole program and which re-raise on
    ``not is_pallas_failure``)."""
    text = f"{type(e).__name__}: {e}"
    return "RESOURCE_EXHAUSTED" in text and "vmem" not in text.lower()


# -- fused Lloyd round: assign + accumulate (KMeans fit) ---------------------

#: VMEM the kernel's working set may claim: double-buffered (TILE_N, d)
#: x tiles, the (TILE_N, k) distance/one-hot blocks, the (k, d) centroids
#: and the (k, d+1) accumulator that persists across grid steps
LLOYD_VMEM_BUDGET_BYTES = 8 << 20


def lloyd_kernel_fits(k: int, d: int) -> bool:
    """True when the fused Lloyd kernel's working set fits the VMEM
    budget for these shapes — the gate kmeans.fit applies."""
    working = (2 * TILE_N * d + 3 * TILE_N * k + k * d
               + 2 * k * (d + 1)) * 4
    return working <= LLOYD_VMEM_BUDGET_BYTES


def _lloyd_accum_kernel(x_ref, v_ref, c_ref, csq_ref, out_ref):
    """One row tile of a Lloyd round, entirely in VMEM: nearest-centroid
    assignment and the weighted (sums, counts) accumulation read the tile
    ONCE — the XLA round reads the shard for the pairwise matmul, again
    for the row norms, and a third time for the one_hot.T @ x sums. The
    TPU grid iterates sequentially per core, so out_ref accumulates
    across tiles (init at step 0)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]                       # (tile_n, d)
    v = v_ref[:]                       # (tile_n, 1) validity weight
    c = c_ref[:]                       # (k, d)
    cross = jnp.dot(x, c.T, preferred_element_type=jnp.float32)
    # ‖x−c‖² up to the per-point constant ‖x‖² (irrelevant to the argmin)
    d2 = csq_ref[:][None, :] - 2.0 * cross
    a = jnp.argmin(d2, axis=1)
    k = c.shape[0]
    one_hot = (a[:, None] == jax.lax.broadcasted_iota(
        jnp.int32, (1, k), 1)).astype(jnp.float32) * v
    sums = jnp.dot(one_hot.T, x, preferred_element_type=jnp.float32)
    counts = jnp.sum(one_hot, axis=0)
    out_ref[:] += jnp.concatenate([sums, counts[:, None]], axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _lloyd_padded(x, v, centroids, interpret=False):
    n, d = x.shape
    k = centroids.shape[0]
    csq = jnp.sum(centroids * centroids, axis=1)
    return pl.pallas_call(
        _lloyd_accum_kernel,
        out_shape=jax.ShapeDtypeStruct((k, d + 1), jnp.float32),
        grid=(n // TILE_N,),
        in_specs=[
            pl.BlockSpec((TILE_N, d), lambda i: (i, 0)),
            pl.BlockSpec((TILE_N, 1), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((k,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((k, d + 1), lambda i: (0, 0)),
        interpret=interpret,
    )(x, v, centroids, csq)


def lloyd_partial_sums(x, v, centroids, interpret: bool = False):
    """Per-shard Lloyd partials — fused assign+accumulate, one pass over x.

    x: (n, d) float32; v: (n,) float32 validity/weight (0 for padding);
    centroids: (k, d) float32 → (k, d+1) float32 = [weighted sums | counts].
    Pads n up to the tile size with zero-weight rows; euclidean only
    (assignment by the same csq − 2·x·cᵀ argmin as ``assign_nearest``).
    Callers psum the result across data shards and renormalize.
    """
    x = jnp.asarray(x, jnp.float32)
    v = jnp.asarray(v, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    n = x.shape[0]
    if n == 0:  # empty grid would skip the step-0 init and return garbage
        k, d = centroids.shape
        return jnp.zeros((k, d + 1), jnp.float32)
    pad = (-n) % TILE_N
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        v = jnp.pad(v, (0, pad))
    return _lloyd_padded(x, v[:, None], centroids, interpret=interpret)


# -- fused SGD batch terms (one pass over the minibatch window) --------------


def _sgd_terms_kernel(terms, tile_n, scalars_ref, x_ref, y_ref, w_ref,
                      c_ref, out_ref):
    """One row tile of the minibatch: forward dots, loss terms and the
    gradient accumulate in VMEM — the batch window is read ONCE (the XLA
    round reads it for the forward matvec and again for the gradient,
    after a dynamic-slice copy). The window's start arrives as a
    prefetched scalar (block units), so ONE compiled kernel serves every
    round of the static schedule; ``scalars_ref[1]`` carries the
    clip-round cutoff (rows before it weigh 0)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]                       # (tile_n, d)
    y = y_ref[:]
    w = w_ref[:]
    c = c_ref[:]                       # (d,)
    row = jnp.reshape(
        jax.lax.broadcasted_iota(jnp.int32, (tile_n, 1), 0), (tile_n,))
    w = jnp.where(i * tile_n + row >= scalars_ref[1], w, 0.0)
    dots = jnp.dot(x, c, preferred_element_type=jnp.float32)
    loss_sum, mult = terms(dots, y, w)
    grad = jnp.dot(mult, x, preferred_element_type=jnp.float32)
    out_ref[:] += jnp.concatenate(
        [grad, jnp.stack([jnp.sum(w), loss_sum])])


#: VMEM budget for the SGD kernel working set: double-buffered (tile, d)
#: x blocks + the y/w vectors + coeffs + the (d+2,) accumulator
SGD_VMEM_BUDGET_BYTES = 8 << 20


def sgd_round_tile(lb: int, local_n: int, d: int) -> int:
    """The largest row tile ≤ 1024, a multiple of 8, dividing both the
    local batch and the shard length (the alignment that makes every
    static-schedule window start a whole number of blocks), whose
    working set fits the VMEM budget for feature width ``d``. 0 when no
    such tile exists (callers fall back to the XLA round) — a shape gate,
    so predictable wide-feature failures never burn the process-wide
    broken flag."""
    import math

    g = math.gcd(lb, local_n)
    for t in range(min(1024, g) - min(1024, g) % 8, 7, -8):
        if g % t != 0:
            continue
        working = (2 * t * d + 4 * t + 2 * d + (d + 2)) * 4
        if working <= SGD_VMEM_BUDGET_BYTES:
            return t
    return 0


@functools.partial(jax.jit,
                   static_argnames=("loss_name", "lb", "tile", "interpret"))
def _sgd_terms_padded(xl, yl, wl, coeffs, scalars, loss_name, lb, tile,
                      interpret=False):
    from jax.experimental.pallas import tpu as pltpu

    from flink_ml_tpu.ops.losses import LossFunc

    terms = LossFunc.by_name(loss_name).terms
    d = xl.shape[1]
    kernel = functools.partial(_sgd_terms_kernel, terms, tile)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(lb // tile,),
        in_specs=[
            pl.BlockSpec((tile, d), lambda i, s: (s[0] + i, 0)),
            pl.BlockSpec((tile,), lambda i, s: (s[0] + i,)),
            pl.BlockSpec((tile,), lambda i, s: (s[0] + i,)),
            pl.BlockSpec((d,), lambda i, s: (0,)),
        ],
        out_specs=pl.BlockSpec((d + 2,), lambda i, s: (0,)),
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((d + 2,), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(scalars, xl, yl, wl, coeffs)


def sgd_batch_terms(xl, yl, wl, coeffs, start, clip, lb: int, tile: int,
                    loss_name: str, interpret: bool = False):
    """Packed [grad sums | weight sum | loss sum] (d+2,) over the
    contiguous batch window [start, start+lb) of this shard — fused
    forward+terms+gradient, one pass over the window.

    ``start`` must be a whole number of ``tile`` blocks (the
    static-schedule gate ``sgd_round_tile`` guarantees it when lb and
    local_n share the tile); rows whose window-relative index is below
    ``clip`` weigh 0 (the clip-at-end round). ``start``/``clip`` may be
    traced scalars — they ride the scalar-prefetch slot, so every round
    reuses one compiled kernel.
    """
    scalars = jnp.stack([jnp.asarray(start, jnp.int32) // tile,
                         jnp.asarray(clip, jnp.int32)])
    return _sgd_terms_padded(xl, yl, wl, jnp.asarray(coeffs, jnp.float32),
                             scalars, loss_name, lb, tile,
                             interpret=interpret)


# -- fused segment-reduce (scatter-add by segment id) ------------------------

SEGREDUCE_TILE_N = 512

#: VMEM one grid step may claim: double-buffered (tile, d) value blocks,
#: the (tile, u) one-hot block, the ids tile and the (u, d) accumulator
#: that persists across grid steps
SEGREDUCE_VMEM_BUDGET_BYTES = 8 << 20


def segment_reduce_fits(num_segments: int, d: int) -> bool:
    """True when the fused segment-reduce kernel's working set fits the
    VMEM budget for these shapes — the gate callers apply. Scatter-add
    here is a one-hot matmul, so the segment domain must be small enough
    for a (tile, u) block; wide domains (hashed 2^18 features) keep
    XLA's native scatter."""
    t = SEGREDUCE_TILE_N
    working = (2 * t * d + t * num_segments + 2 * num_segments * d
               + 2 * t) * 4
    return 0 < num_segments and working <= SEGREDUCE_VMEM_BUDGET_BYTES


def _segreduce_kernel(x_ref, ids_ref, out_ref):
    """One row tile of a segment-sum, entirely in VMEM: the (tile, u)
    one-hot block exists only here — XLA's scatter-add lowers to a
    serialized per-row update on shapes this small, while the one-hot
    matmul runs on the MXU and reads the tile ONCE. The TPU grid
    iterates sequentially per core, so out_ref accumulates across tiles
    (init at step 0 — the Lloyd-partials idiom above). Out-of-range ids
    (negative padding included) match no one-hot column and contribute
    nothing, mirroring jax.ops.segment_sum's drop semantics."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    x = x_ref[:]                       # (tile, d)
    ids = ids_ref[:]                   # (tile, 1) int32
    u = out_ref.shape[0]
    one_hot = (ids == jax.lax.broadcasted_iota(
        jnp.int32, (1, u), 1)).astype(x.dtype)        # (tile, u)
    out_ref[:] += jnp.dot(one_hot.T, x,
                          preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def _segreduce_padded(x, ids, num_segments, interpret=False):
    n, d = x.shape
    return pl.pallas_call(
        _segreduce_kernel,
        out_shape=jax.ShapeDtypeStruct((num_segments, d), jnp.float32),
        grid=(n // SEGREDUCE_TILE_N,),
        in_specs=[
            pl.BlockSpec((SEGREDUCE_TILE_N, d), lambda i: (i, 0)),
            pl.BlockSpec((SEGREDUCE_TILE_N, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((num_segments, d), lambda i: (0, 0)),
        interpret=interpret,
    )(x, ids)


def segment_reduce_sum(values, segment_ids, num_segments: int,
                       interpret: bool = False):
    """Fused per-segment sums — ``out[s] = Σ values[i] where
    segment_ids[i] == s`` — the segment-reduce shape XLA serializes as a
    per-row scatter. values: (n,) or (n, d) float32; segment_ids: (n,)
    int32 → (num_segments,) / (num_segments, d) float32. Callers gate
    with :func:`segment_reduce_fits`; rows with out-of-range ids are
    dropped (segment_sum parity). Pads n up to the tile size with id -1
    rows; euclidean of use: the FTRL sparse program's per-coordinate
    gradient/weight sums."""
    values = jnp.asarray(values, jnp.float32)
    squeeze = values.ndim == 1
    if squeeze:
        values = values[:, None]
    ids = jnp.asarray(segment_ids, jnp.int32)
    n = values.shape[0]
    if n == 0:  # empty grid would skip the step-0 init and return garbage
        out = jnp.zeros((num_segments, values.shape[1]), jnp.float32)
        return out[:, 0] if squeeze else out
    pad = (-n) % SEGREDUCE_TILE_N
    if pad:
        values = jnp.pad(values, ((0, pad), (0, 0)))
        ids = jnp.pad(ids, (0, pad), constant_values=-1)
    out = _segreduce_padded(values, ids[:, None], num_segments,
                            interpret=interpret)
    return out[:, 0] if squeeze else out


# -- fused distance + top-k (KNN) -------------------------------------------

KNN_TILE_N = 256   # test rows per grid step
KNN_TILE_T = 2048  # train rows streamed per grid step
#: VMEM one grid step may claim — callers gate on
#: _knn_step_vmem_bytes(d, k) (the authoritative per-step estimate);
#: n_train itself is unbounded (streamed over the second grid axis)
KNN_VMEM_BUDGET_BYTES = 32 << 20


def _knn_step_vmem_bytes(d: int, k: int) -> int:
    """Upper estimate of one grid step's VMEM working set (bytes): the
    train/test tiles plus six (KNN_TILE_N, k + KNN_TILE_T)-ish blocks —
    d2, cross, tile_idx, comb_d, comb_i, and the fori_loop's masked
    comb_d copy. Deliberately generous: admitting a shape whose real
    footprint overflows VMEM trips _pallas_knn_broken and degrades EVERY
    later predict in the process to the XLA path."""
    return 4 * (KNN_TILE_T * d + KNN_TILE_N * d
                + 6 * KNN_TILE_N * (k + KNN_TILE_T))


def _knn_kernel(k: int, x_ref, t_ref, tsq_ref, idx_ref, bd_ref):
    """One test tile vs one STREAMED train tile: grid axis 1 walks the
    train set; the (KNN_TILE_N, k) best-distance/best-index carries ride
    in the revisited output blocks (the accumulate-across-grid idiom of
    the Lloyd partials above), so the (n_test, n_train) distance matrix
    never exists anywhere — not even tile-wise in HBM. Each step merges
    the carried top-k with the new tile's candidates in k argmin+mask
    passes (k is small; Mosaic has no native top_k).

    Tie-break: carried candidates (all from earlier tiles, hence lower
    train indices) sit BEFORE the new tile's columns in the merge block,
    and argmin takes the first minimum — so equal distances resolve to
    the lowest train index, matching lax.top_k. Padded train rows enter
    with tsq = +inf so they can never win a pick while a finite candidate
    remains (callers keep k ≤ n_train)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        bd_ref[:] = jnp.full(bd_ref.shape, jnp.inf, jnp.float32)
        idx_ref[:] = jnp.zeros(idx_ref.shape, jnp.int32)

    x = x_ref[:]                        # (tile_n, d)
    t = t_ref[:]                        # (tile_t, d)
    cross = jnp.dot(x, t.T, preferred_element_type=jnp.float32)
    # ‖x−t‖² up to the per-row constant ‖x‖² (rank-invariant)
    d2 = tsq_ref[:][None, :] - 2.0 * cross
    tile_n, tile_t = d2.shape
    tile_idx = j * tile_t + jax.lax.broadcasted_iota(
        jnp.int32, (tile_n, tile_t), 1)
    comb_d = jnp.concatenate([bd_ref[:], d2], axis=1)
    comb_i = jnp.concatenate([idx_ref[:], tile_idx], axis=1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (tile_n, k + tile_t), 1)

    def pick(p, carry):
        comb_d, bd, bi = carry
        m = jnp.min(comb_d, axis=1)
        taken = cols == jnp.argmin(comb_d, axis=1).astype(
            jnp.int32)[:, None]
        chosen = jnp.sum(jnp.where(taken, comb_i, 0), axis=1)
        bd = jax.lax.dynamic_update_slice(bd, m[:, None], (0, p))
        bi = jax.lax.dynamic_update_slice(bi, chosen[:, None], (0, p))
        return jnp.where(taken, jnp.inf, comb_d), bd, bi

    _, bd, bi = jax.lax.fori_loop(
        0, k, pick, (comb_d, jnp.zeros((tile_n, k), jnp.float32),
                     jnp.zeros((tile_n, k), jnp.int32)))
    bd_ref[:] = bd
    idx_ref[:] = bi


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def _knn_padded(x, train, k, interpret=False):
    n, d = x.shape
    nt = train.shape[0]
    tsq = jnp.sum(train * train, axis=1)
    pad_t = (-nt) % KNN_TILE_T
    if pad_t:
        train = jnp.pad(train, ((0, pad_t), (0, 0)))
        tsq = jnp.pad(tsq, (0, pad_t), constant_values=jnp.inf)
    kernel = functools.partial(_knn_kernel, k)
    idx, _ = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((n, k), jnp.int32),
                   jax.ShapeDtypeStruct((n, k), jnp.float32)),
        grid=(n // KNN_TILE_N, (nt + pad_t) // KNN_TILE_T),
        in_specs=[
            pl.BlockSpec((KNN_TILE_N, d), lambda i, j: (i, 0)),
            pl.BlockSpec((KNN_TILE_T, d), lambda i, j: (j, 0)),
            pl.BlockSpec((KNN_TILE_T,), lambda i, j: (j,)),
        ],
        out_specs=(pl.BlockSpec((KNN_TILE_N, k), lambda i, j: (i, 0)),
                   pl.BlockSpec((KNN_TILE_N, k), lambda i, j: (i, 0))),
        interpret=interpret,
    )(x, train, tsq)
    return idx


def knn_topk_indices(x, train, k: int, interpret: bool = False):
    """Indices of the k nearest train rows per test row — fused
    distance+top-k streaming over train tiles; the distance matrix exists
    only as one (KNN_TILE_N, KNN_TILE_T) block in VMEM. x: (n, d);
    train: (n_train, d), ANY n_train — callers gate on
    _knn_step_vmem_bytes(d, k) ≤ KNN_VMEM_BUDGET_BYTES → (n, k) int32.
    Ties resolve to the lowest index (argmin), matching lax.top_k."""
    x = jnp.asarray(x, jnp.float32)
    train = jnp.asarray(train, jnp.float32)
    k = min(k, train.shape[0])
    n = x.shape[0]
    pad = (-n) % KNN_TILE_N
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return _knn_padded(x, train, k, interpret=interpret)[:n]
