"""Pallas TPU kernels.

The framework's hot device loops are mostly single fused matmuls that XLA
already schedules well (SURVEY.md §7 layer 1: "Pallas where XLA fusion is
insufficient"). The case where hand-tiling pays is nearest-centroid
assignment with large k: XLA materializes the (n, k) distance matrix in HBM
between the matmul and the argmin; this kernel keeps each (tile_n, k)
distance block in VMEM and writes only the argmin — HBM traffic drops from
O(n·k) to O(n·d + k·d + n).

Used by KMeans/KNN paths when running on a real TPU backend; elsewhere the
plain XLA path runs. Tests exercise the kernel in interpreter mode on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_N = 1024


def _assign_kernel(x_ref, c_ref, csq_ref, out_ref):
    x = x_ref[:]                       # (tile_n, d)
    c = c_ref[:]                       # (k, d)
    # ‖x−c‖² up to the per-point constant ‖x‖² (irrelevant to the argmin)
    cross = jnp.dot(x, c.T, preferred_element_type=jnp.float32)
    d2 = csq_ref[:][None, :] - 2.0 * cross
    out_ref[:, 0] = jnp.argmin(d2, axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _assign_padded(x, centroids, interpret=False):
    n, d = x.shape
    k = centroids.shape[0]
    csq = jnp.sum(centroids * centroids, axis=1)
    grid = (n // TILE_N,)
    return pl.pallas_call(
        _assign_kernel,
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_N, d), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((k,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((TILE_N, 1), lambda i: (i, 0)),
        interpret=interpret,
    )(x, centroids, csq)


def assign_nearest(x, centroids, interpret: bool = False):
    """Nearest-centroid index per row of x — fused distance+argmin.

    x: (n, d) float32; centroids: (k, d) float32 → (n,) int32.
    Pads n up to the tile size; callers slice with the true n.
    """
    x = jnp.asarray(x, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    n = x.shape[0]
    pad = (-n) % TILE_N
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    out = _assign_padded(x, centroids, interpret=interpret)
    return out[:n, 0]


def pallas_supported() -> bool:
    """True when the default backend can run compiled pallas kernels."""
    return jax.default_backend() == "tpu"
