"""Load generator: closed/open-loop request driving with exact latency
accounting — the serving twin of the fit benchmark harness.

One request-driving code path for everything that throws traffic at a
servable: the serving benchmark (scripts/serve_bench.py), the CI smoke
(scripts/serve_smoke.py) and the runtime tests all call
:func:`run_loadgen` with a ``submit`` callable — either
``MicroBatcher.submit`` (futures; the batched path) or a bare
``servable.transform`` (the per-request baseline; wrapped into a
worker-thread future automatically) — and a ``frame_factory(i)``
producing the i-th request frame (the caller controls the row-size
mix).

Two loops (docs/serving.md):

- **closed** — ``concurrency`` workers, each keeping exactly one
  request outstanding: offered load adapts to capacity, the classic
  saturation probe;
- **open** — requests issue on a fixed ``rps`` schedule regardless of
  completions (capped by ``max_outstanding`` so an overloaded target
  sheds into rejections rather than an unbounded client backlog): the
  SLO-relevant regime, where queueing delay is visible.

Every request is classified ``ok`` / ``rejected``
(:class:`~flink_ml_tpu.servable.api.RejectedRequest` — shed load) /
``error`` (anything else), with per-class exact latency samples; the
result dict carries p50/p90/p99/mean/max over the OK samples plus
achieved and offered rates, ready for a BASELINE-style JSON record.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, List, Optional

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.servable.api import RejectedRequest

__all__ = ["LoadGenConfig", "percentiles", "run_loadgen"]


@dataclasses.dataclass(frozen=True)
class LoadGenConfig:
    """One load run. ``mode`` is ``"closed"`` or ``"open"``."""

    mode: str = "closed"
    #: total requests to issue
    requests: int = 100
    #: closed loop: concurrent workers (1 = strictly sequential)
    concurrency: int = 4
    #: open loop: offered request rate (requests/second)
    rps: float = 200.0
    #: open loop: issue cap — pending completions beyond this make the
    #: generator skip (count as ``skipped``) instead of queueing
    #: forever. One harvest thread per outstanding request, so the
    #: effective cap is min(max_outstanding, 64) — sized for the
    #: process-local targets this loadgen drives; a non-zero ``skipped``
    #: in the result means the offered schedule was NOT sustained
    max_outstanding: int = 64
    #: per-request completion timeout
    timeout_s: float = 30.0

    def __post_init__(self):
        if self.mode not in ("closed", "open"):
            raise ValueError(f"mode must be closed|open, got {self.mode!r}")
        if self.requests <= 0 or self.concurrency <= 0:
            raise ValueError("requests and concurrency must be > 0")
        if self.mode == "open" and self.rps <= 0:
            raise ValueError("open loop needs rps > 0")


def percentiles(samples_ms: List[float]) -> dict:
    """Exact order-statistic latency summary (nearest-rank) — the
    loadgen holds every sample, so no bucket interpolation error."""
    if not samples_ms:
        return {"p50": None, "p90": None, "p99": None, "mean": None,
                "max": None}
    s = sorted(samples_ms)
    n = len(s)

    def rank(q: float) -> float:
        return round(s[min(n - 1, max(0, int(q * n + 0.5) - 1))], 3)

    return {"p50": rank(0.50), "p90": rank(0.90), "p99": rank(0.99),
            "mean": round(sum(s) / n, 3), "max": round(s[-1], 3)}


class _Collector:
    def __init__(self):
        self.lock = make_lock("serving.loadgen.stats")
        self.ok_ms: List[float] = []
        self.rejected: dict = {}
        self.errors: dict = {}
        self.rows_ok = 0

    def record(self, t0: float, outcome, rows: int) -> None:
        ms = (time.perf_counter() - t0) * 1000.0
        with self.lock:
            if outcome is None:
                self.ok_ms.append(ms)
                self.rows_ok += rows
            elif isinstance(outcome, RejectedRequest):
                key = outcome.reason
                self.rejected[key] = self.rejected.get(key, 0) + 1
            else:
                key = type(outcome).__name__
                self.errors[key] = self.errors.get(key, 0) + 1


def _as_future(submit: Callable, frame) -> "Future":
    out = submit(frame)
    if isinstance(out, Future):
        return out
    done: Future = Future()
    done.set_result(out)
    return done


def run_loadgen(submit: Callable, frame_factory: Callable[[int], object],
                cfg: Optional[LoadGenConfig] = None,
                tick: Optional[Callable[[int], None]] = None,
                feedback: Optional[Callable[[int, object, Future],
                                            None]] = None) -> dict:
    """Drive ``cfg.requests`` requests through ``submit`` and return
    the result record. ``submit(frame)`` may return a Future (the
    micro-batcher) or the transformed frame directly (a bare
    ``transform`` — run in loadgen worker threads so closed-loop
    concurrency still applies). ``tick(i)`` (optional) runs after every
    completed request — the smoke's scrape-while-serving hook.
    ``feedback(i, frame, fut)`` (optional) runs after every request
    that completed OK — the delayed-ground-truth hook: the batcher
    stamps ``fut.request_id`` at submit, so a labeled driver can call
    :func:`~flink_ml_tpu.observability.evaluation.record_feedback`
    with it and close the prediction↔label join. Feedback exceptions
    are swallowed (the label plane must never fail the load run)."""
    cfg = cfg or LoadGenConfig()
    collector = _Collector()
    completed = [0]
    done_lock = make_lock("serving.loadgen.done")
    tick_errors: List[BaseException] = []

    def finish(i: int, t0: float, fut: Future, frame) -> None:
        rows = frame.num_rows() if hasattr(frame, "num_rows") else 0
        try:
            fut.result(timeout=cfg.timeout_s)
            collector.record(t0, None, rows)
            if feedback is not None:
                try:
                    feedback(i, frame, fut)
                except Exception:  # noqa: BLE001 — see docstring
                    pass
        except Exception as e:  # noqa: BLE001 — classification IS the job
            collector.record(t0, e, rows)
        if tick is not None:
            with done_lock:
                completed[0] += 1
                n = completed[0]
            try:
                tick(n)
            except BaseException as e:  # noqa: BLE001 — ticks run on
                # worker threads, where a raised SystemExit/assertion
                # would silently kill ONE worker and strand its share of
                # the run; collect and re-raise from the caller's thread
                with done_lock:
                    tick_errors.append(e)

    t_start = time.perf_counter()
    if cfg.mode == "closed":
        counter = [0]
        counter_lock = make_lock("serving.loadgen.counter")

        def worker() -> None:
            while True:
                with counter_lock:
                    if counter[0] >= cfg.requests:
                        return
                    i = counter[0]
                    counter[0] += 1
                frame = frame_factory(i)
                t0 = time.perf_counter()
                try:
                    fut = _as_future(submit, frame)
                except Exception as e:  # noqa: BLE001 — a submit-time
                    # raise (sync transform) classifies like a future
                    fut = Future()
                    fut.set_exception(e)
                finish(i, t0, fut, frame)

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"loadgen-{w}")
                   for w in range(min(cfg.concurrency, cfg.requests))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        skipped = 0
    else:
        # open loop: fixed-rate issue schedule; completions harvest on a
        # pool so a slow target never stalls the schedule. The
        # semaphore bound EQUALS the pool size: each harvest thread
        # blocks on one completion, so a larger semaphore would let
        # issues queue invisibly inside the executor and report a
        # sustained schedule the target never actually saw
        interval = 1.0 / cfg.rps
        workers = min(64, cfg.max_outstanding)
        outstanding = threading.Semaphore(workers)
        skipped = 0
        with ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix="loadgen") as pool:
            for i in range(cfg.requests):
                target_t = t_start + i * interval
                delay = target_t - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                if not outstanding.acquire(blocking=False):
                    skipped += 1
                    continue
                frame = frame_factory(i)

                # submit runs on the pool too: a synchronous target
                # (bare transform) must not stall the issue schedule
                def issue(i=i, frame=frame):
                    t0 = time.perf_counter()
                    try:
                        fut = _as_future(submit, frame)
                    except Exception as e:  # noqa: BLE001 — see above
                        fut = Future()
                        fut.set_exception(e)
                    try:
                        finish(i, t0, fut, frame)
                    finally:
                        outstanding.release()

                pool.submit(issue)
    wall_s = max(time.perf_counter() - t_start, 1e-9)
    if tick_errors:
        raise tick_errors[0]

    ok = len(collector.ok_ms)
    rejected = sum(collector.rejected.values())
    errors = sum(collector.errors.values())
    return {
        "mode": cfg.mode,
        "requests": cfg.requests,
        "ok": ok,
        "rejected": rejected,
        "rejectedByReason": dict(collector.rejected),
        "errors": errors,
        "errorsByClass": dict(collector.errors),
        "skipped": skipped,
        "rows_ok": collector.rows_ok,
        "wall_s": round(wall_s, 4),
        "offered_rps": (round(cfg.rps, 2) if cfg.mode == "open"
                        else round(cfg.requests / wall_s, 2)),
        "throughput_rps": round(ok / wall_s, 2),
        "rows_per_s": round(collector.rows_ok / wall_s, 2),
        "latency_ms": percentiles(collector.ok_ms),
    }
