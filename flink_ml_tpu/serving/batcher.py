"""Async micro-batching dispatcher: many callers, one device dispatch
per tick — pipelined, and mesh-sharded when given a mesh.

The synchronous servable path (servable/api.py) is one caller, one
``transform``, one dispatch — fine for a notebook, hopeless for traffic.
This module puts a queue in front of any
:class:`~flink_ml_tpu.servable.api.TransformerServable`:

- **submit** enqueues a request (a DataFrame) with a deadline and
  returns a future; admission control rejects immediately
  (:class:`~flink_ml_tpu.servable.api.RejectedRequest`) when the queue
  (including rows already drained into the pipeline) is full or the
  request cannot fit any batch bucket — shed load, never unbounded
  latency;
- the **pad/enqueue stage** drains whole requests once the oldest has
  waited ``window_ms`` or the largest bucket fills, drops requests whose
  deadline expired in queue, **pads** the concatenated rows up to the
  smallest bucket that fits (``buckets``, a small fixed table of batch
  shapes; pad rows come from a per-(schema, bucket) template cache —
  the ``paddingReuse`` counter) — so steady-state serving presents XLA
  with a closed set of batch shapes and never recompiles (the contract
  serving/warmup.py pre-compiles and tests assert via ``ml.compile``
  counters);
- the **device stage** takes prepared batches over a
  depth-``pipeline_depth`` handoff (default 1 — host padding of tick
  N+1 overlaps device compute of tick N), resolves the servable ONCE
  per tick, re-checks deadlines, asserts the dispatch ``mesh`` on the
  servable (buckets the mesh's shard count divides predict row-sharded
  over the devices — servable/lr.py, docs/serving.md "Mesh-sharded
  dispatch") and issues ONE ``transform`` on the batch;
- results split back per request, futures resolve from the fetch side,
  and in-flight requests pin the servable they were dispatched with — a
  model hot-swap (serving/registry.py) between device ticks never yanks
  a batch mid-flight.

Telemetry rides the PR 7 live endpoint: ``queueDepth`` /
``batchFill`` / ``paddingWaste`` gauges, per-request ``queueMs`` /
``batchMs`` windowed histograms and fill/waste distributions in
``ml.serving``, per-device ``shardRows`` / ``shardImbalance`` gauges on
sharded ticks, ``serving.pad`` + ``serving.batch`` spans per tick
(sharing a ``tick`` attr — overlapping spans ARE the pipelining proof),
and a ``/serving`` route (observability/server.py) exposing queue
depth, the bucket table, pipeline depth, mesh and the active model
version. Causal tracing (docs/observability.md "Causal tracing,
critical path & incidents"): every sampled request anchors a
``serving.submit`` span on the caller's thread whose
:class:`~flink_ml_tpu.observability.tracing.TraceContext` rides the
request through the admission queue AND the pad→device handoff — the
tick's pad/batch spans record explicit ``follows_from`` links back to
the requests they serve (and the batch to the pad that prepared it),
and a ``serving.resolve`` span in the request's own trace closes the
submit→pad→batch→resolve chain, so ``flink-ml-tpu-trace path``
decomposes per-request latency into queue/pad/handoff/device/resolve
segments. See docs/serving.md.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import List, Optional, Tuple

from flink_ml_tpu.common.locks import (
    install_thread_excepthook,
    make_condition,
)
from flink_ml_tpu.common.metrics import ML_GROUP, RATIO_BUCKETS, metrics
from flink_ml_tpu.observability import profiling, tracing
from flink_ml_tpu.observability.health import (
    COUNT_BUCKETS,
    SERVING_HORIZON_S,
    SERVING_SLICES,
    observe_serving_rejected,
    trace_sampled,
)
from flink_ml_tpu.servable.api import (
    DataFrame,
    RejectedRequest,
    TransformerServable,
    serving_name,
)

__all__ = ["DEFAULT_BUCKET_ROWS", "BUCKETS_ENV", "WINDOW_ENV",
           "DEADLINE_ENV", "QUEUE_ENV", "BatcherConfig", "MicroBatcher"]

#: default batch-shape table (rows) — covers singleton pings through
#: bulk scoring with <= 2x padding waste per bucket step
DEFAULT_BUCKET_ROWS = (1, 8, 32, 128)

#: deployment env vars (docs/serving.md): comma-separated bucket row
#: counts ("none" disables bucketing), batch window ms, default request
#: deadline ms ("none" disables), admission queue bound in rows
BUCKETS_ENV = "FLINK_ML_TPU_SERVE_BUCKETS"
WINDOW_ENV = "FLINK_ML_TPU_SERVE_WINDOW_MS"
DEADLINE_ENV = "FLINK_ML_TPU_SERVE_DEADLINE_MS"
QUEUE_ENV = "FLINK_ML_TPU_SERVE_MAX_QUEUE_ROWS"
PIPELINE_ENV = "FLINK_ML_TPU_SERVE_PIPELINE_DEPTH"


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """Micro-batcher tuning knobs (env-independent: the serving scripts
    map FLINK_ML_TPU_SERVE_* env vars onto this, docs/serving.md).

    ``buckets=None`` disables bucketing/padding — every tick dispatches
    the exact drained row count. That trades padding waste for a fresh
    XLA compile per distinct batch size: the recompile-storm
    configuration the negative tests exercise, not a production mode.
    """

    #: sorted row-count bucket table; None disables bucketing
    buckets: Optional[Tuple[int, ...]] = DEFAULT_BUCKET_ROWS
    #: max time (ms) the oldest queued request waits for batch fill
    window_ms: float = 5.0
    #: admission bound: queued rows beyond this are rejected queue-full
    max_queue_rows: int = 4096
    #: default per-request deadline (ms) from enqueue to dispatch;
    #: None = requests never expire in queue
    deadline_ms: Optional[float] = 1000.0
    #: cap on rows drained per tick without bucketing (with bucketing
    #: the largest bucket is the cap)
    max_batch_rows: int = 1024
    #: dispatcher pipelining: depth of the pad→device handoff queue.
    #: 0 runs the single-thread dispatcher (pad and dispatch serialized
    #: on one loop — the pre-pipeline behavior); the default 1 lets the
    #: pad stage prepare tick N+1 while the device stage computes
    #: tick N, overlapping host padding with device compute
    pipeline_depth: int = 1

    def __post_init__(self):
        if self.buckets is not None:
            b = tuple(int(x) for x in self.buckets)
            if not b or any(x <= 0 for x in b) or list(b) != sorted(set(b)):
                raise ValueError(
                    f"buckets must be sorted unique positive row "
                    f"counts, got {self.buckets!r}")
            object.__setattr__(self, "buckets", b)
        if self.window_ms < 0:
            raise ValueError("window_ms must be >= 0")
        if self.max_queue_rows <= 0 or self.max_batch_rows <= 0:
            raise ValueError("queue/batch row bounds must be > 0")
        if self.pipeline_depth < 0:
            raise ValueError("pipeline_depth must be >= 0")

    @classmethod
    def from_env(cls, **overrides) -> "BatcherConfig":
        """Config from the FLINK_ML_TPU_SERVE_* env vars (unset fields
        keep their defaults; keyword ``overrides`` win over env). A
        malformed value raises ValueError naming the variable — a
        mistyped deployment knob must fail loudly at startup, not serve
        with silent defaults."""
        import os

        def read(env, parse, key):
            raw = os.environ.get(env)
            if raw is None or key in overrides:
                return
            try:
                overrides[key] = parse(raw)
            except ValueError as e:
                raise ValueError(f"{env}={raw!r}: {e}") from e

        def parse_buckets(raw):
            if raw.strip().lower() in ("", "none", "off"):
                return None
            return tuple(int(b) for b in raw.split(","))

        def parse_optional_ms(raw):
            if raw.strip().lower() in ("", "none"):
                return None
            return float(raw)

        read(BUCKETS_ENV, parse_buckets, "buckets")
        read(WINDOW_ENV, float, "window_ms")
        read(DEADLINE_ENV, parse_optional_ms, "deadline_ms")
        read(QUEUE_ENV, int, "max_queue_rows")
        read(PIPELINE_ENV, int, "pipeline_depth")
        return cls(**overrides)

    @property
    def max_bucket(self) -> int:
        return (self.buckets[-1] if self.buckets
                else self.max_batch_rows)

    def bucket_for(self, rows: int) -> int:
        """Smallest bucket holding ``rows`` (== ``rows`` unbucketed)."""
        if self.buckets is None:
            return rows
        for b in self.buckets:
            if rows <= b:
                return b
        return rows  # caller enforces rows <= max_bucket at admission


def _row_signature(row) -> tuple:
    """Per-value shape fingerprint of one row — the pad-template cache
    key component the declared schema cannot provide (a ``vector``
    DataType is dimension-less): type name plus element count where one
    is discoverable."""
    sig = []
    for v in row.values:
        size = None
        try:
            if hasattr(v, "size"):
                size = int(v.size() if callable(v.size) else v.size)
            elif hasattr(v, "__len__"):
                size = len(v)
        except Exception:  # noqa: BLE001 — a fingerprint, not a parser
            size = None
        sig.append((type(v).__name__, size))
    return tuple(sig)


class _Request:
    __slots__ = ("df", "rows", "n", "schema", "future", "t_enqueue",
                 "deadline_s", "seq", "ctx")

    def __init__(self, df: DataFrame, deadline_ms: Optional[float]):
        self.df = df
        self.rows = df.collect()
        self.n = len(self.rows)
        # cached once at submit: the per-tick schema comparison is a
        # tuple identity check instead of a fresh column_names list
        # copy per request per tick
        self.schema = tuple(df.column_names)
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()
        self.deadline_s = (None if deadline_ms is None
                           else self.t_enqueue + deadline_ms / 1000.0)
        #: per-batcher request ordinal — the ``req=`` attr joining this
        #: request's serving.submit span to its serving.resolve span
        #: (observability/path.py)
        self.seq: Optional[int] = None
        #: the request's TraceContext (its serving.submit span, itself
        #: a child of whatever span the CALLER had open) — rides the
        #: Future to the device stage so the tick's serving.pad/
        #: serving.batch spans can link back follows_from, and the
        #: resolve span re-enters the caller's trace
        self.ctx = None


class _Prepared:
    """One padded batch, handed from the pad stage to the device stage.
    Everything the device dispatch needs travels here so the device
    thread never touches the admission queue."""

    __slots__ = ("requests", "batch_df", "bucket", "n_real", "pad",
                 "fill", "waste", "tick", "reused", "total_rows",
                 "pad_ctx")

    def __init__(self, requests, batch_df, bucket, n_real, pad, fill,
                 waste, tick, reused):
        self.requests = requests
        self.batch_df = batch_df
        self.bucket = bucket
        self.n_real = n_real
        self.pad = pad
        self.fill = fill
        self.waste = waste
        self.tick = tick
        self.reused = reused
        self.total_rows = 0  # drained-row accounting, set by the pad stage
        #: the serving.pad span's TraceContext, riding the pad→device
        #: queue handoff so the device stage's serving.batch span can
        #: record the follows_from edge (observability/tracing.py)
        self.pad_ctx = None


class MicroBatcher:
    """The dispatcher: a pad/enqueue stage draining an
    admission-controlled queue into padded, bucketed batches, and a
    device stage issuing one dispatch per batch — connected by a
    depth-``pipeline_depth`` handoff so host padding of tick N+1
    overlaps device compute of tick N (``pipeline_depth=0`` collapses
    both stages onto one thread, the pre-pipeline behavior).

    ``target`` is the servable itself, a zero-arg provider callable, or
    anything with an ``active`` attribute (a
    :class:`~flink_ml_tpu.serving.registry.ModelRegistry`) — resolved
    ONCE per device tick, so a hot-swap lands between batches, never
    inside one.

    ``mesh`` (optional) arms mesh-sharded dispatch: it is asserted on
    the resolved servable each device tick (``set_mesh``, idempotent),
    so buckets divisible by the mesh's data-shard count predict with
    the micro-batch row-sharded over the devices
    (docs/serving.md "Mesh-sharded dispatch")."""

    def __init__(self, target, config: Optional[BatcherConfig] = None,
                 mesh=None):
        self.config = config or BatcherConfig()
        self._mesh = mesh
        self._target = target  # for /serving status (version/canary)
        if isinstance(target, TransformerServable):
            self._provider = lambda: target
        elif hasattr(target, "resolve"):
            # the registry's per-tick routing seam: active, or the
            # canary for its traffic fraction (docs/ops.md) — resolving
            # once per tick keeps in-flight batches on one version
            self._provider = target.resolve
        elif hasattr(target, "active"):
            self._provider = lambda: target.active
        elif callable(target):
            self._provider = target
        else:
            raise TypeError(
                f"target must be a servable, a provider callable, or "
                f"have .active; got {type(target).__name__}")
        # append-right / pop-left only: deque keeps the dispatcher's
        # drain O(1) per request while it holds the condition lock
        self._queue = collections.deque()
        self._queued_rows = 0
        # rows drained by the pad stage but not yet resolved by the
        # device stage: admission counts them, or the pipeline would
        # quietly extend max_queue_rows by a tick per handoff slot
        self._inflight_rows = 0
        self._cond = make_condition("serving.batcher")
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        self._device_thread: Optional[threading.Thread] = None
        self._handoff: Optional[queue.Queue] = None
        self._ticks = 0
        self._tick_seq = 0
        # next() on itertools.count is atomic under the GIL — submit
        # runs on arbitrary caller threads before taking the cond lock
        self._req_counter = itertools.count()
        self._served_requests = 0
        self._prev_status = None
        self._fleet_token = None
        # pad-template cache, keyed by (schema, type key, bucket): the
        # duplicated-row values each tick's padding appends, extracted
        # once instead of re-copied from the tail request every tick
        self._pad_templates: dict = {}
        self._group = metrics.group(ML_GROUP, "serving")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            return self
        # a crashing tick/device daemon must surface in telemetry
        install_thread_excepthook()
        # under the cond: a submitter thread racing a restart must see
        # either the old True (and get rejected) or the new False —
        # never a torn interleaving with its own queue append
        with self._cond:
            self._stopping = False
        if self.config.pipeline_depth > 0:
            self._handoff = queue.Queue(
                maxsize=self.config.pipeline_depth)
            self._device_thread = threading.Thread(
                target=self._run_device,
                name="flink-ml-tpu-batcher-dev", daemon=True)
            self._device_thread.start()
        self._thread = threading.Thread(target=self._run,
                                        name="flink-ml-tpu-batcher",
                                        daemon=True)
        self._thread.start()
        # the live /serving route reflects THIS runtime while it runs;
        # the previous provider (a batcher we run alongside, e.g. a
        # benchmark sweep next to the main runtime) is restored on stop
        from flink_ml_tpu.observability import server

        self._prev_status = server.get_serving_status()
        server.set_serving_status(self.status)
        # join the fleet telemetry plane while serving: periodic
        # beacons carry this replica's windowed queueMs/batchMs slices
        # and load row (observability/fleet.py; no-op when no fleet
        # dir resolves)
        try:
            from flink_ml_tpu.observability import fleet

            self._fleet_token = fleet.start_beacon(role="serving")
        except Exception:
            self._fleet_token = None
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the dispatcher; with ``drain`` (default) queued requests
        are dispatched first, otherwise they are rejected ``shutdown``."""
        thread = self._thread
        if thread is None:
            return
        with self._cond:
            self._stopping = True
            if not drain:
                for req in self._queue:
                    self._reject(req, "shutdown")
                self._queue.clear()
                self._queued_rows = 0
            self._cond.notify_all()
        thread.join(timeout=30.0)
        self._thread = None
        # the pad stage put its sentinel on exit; wait for the device
        # stage to finish whatever was already in the handoff (a
        # prepared batch is in flight — it completes, never rejects)
        if self._device_thread is not None:
            self._device_thread.join(timeout=30.0)
            self._device_thread = None
            self._handoff = None
        from flink_ml_tpu.observability import server

        # only clear OUR registration (a later-started batcher may have
        # taken the /serving route over), handing back to whoever held
        # it when we started
        server.clear_serving_status(self.status, self._prev_status)
        self._prev_status = None
        try:
            from flink_ml_tpu.observability import fleet

            fleet.stop_beacon(getattr(self, "_fleet_token", None))
            self._fleet_token = None
        except Exception:
            pass

    def __enter__(self) -> "MicroBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission -----------------------------------------------------------
    def submit(self, df: DataFrame, deadline_ms=...) -> Future:
        """Enqueue one request; returns a future resolving to the
        transformed DataFrame. Rejections (queue full, too large for
        every bucket, shutdown, deadline expired in queue) surface as
        :class:`~flink_ml_tpu.servable.api.RejectedRequest` raised by
        ``future.result()`` — and are counted windowed per reason."""
        if deadline_ms is ...:
            deadline_ms = self.config.deadline_ms
        req = _Request(df, deadline_ms)
        req.seq = next(self._req_counter)
        # the continuous-evaluation join key (observability/
        # evaluation.py): callers read it off the future and hand it
        # back with the delayed ground-truth label
        # (evaluation.record_feedback) — the same ordinal the causal
        # trace carries as ``req=``
        req.future.request_id = req.seq
        if tracing.tracer.enabled and trace_sampled():
            # the request's causal anchor: a near-instant span on the
            # CALLER's thread — child of whatever span the caller has
            # open — whose context rides the request to the dispatcher
            # so the tick's pad/batch spans link back follows_from and
            # the resolve span closes the submit→pad→batch→resolve
            # chain in ONE trace (docs/observability.md "Causal
            # tracing"). Opened BEFORE admission: the context must be
            # attached before the pad stage can see the request, and a
            # rejected request keeps its anchor too. Gated on
            # ``enabled`` (an armed trace dir — the debugging/incident
            # investigation mode), NOT on the always-on ring: the
            # per-request chain serializes spans onto the device
            # thread, and the ring-only production shape must stay
            # within the serve_bench traceOverheadPct budget. Sampled
            # with the serving.request spans
            # (FLINK_ML_TPU_TRACE_SAMPLE).
            with tracing.tracer.span("serving.submit", req=req.seq,
                                     rows=req.n) as sp:
                req.ctx = tracing.context_of(sp)
        cfg = self.config
        with self._cond:
            if self._stopping or self._thread is None:
                self._reject(req, "shutdown")
                return req.future
            if req.n == 0:
                # nothing to batch — and the pad logic needs at least
                # one real row to duplicate
                self._reject(req, "empty")
                return req.future
            if cfg.buckets is not None and req.n > cfg.max_bucket:
                self._reject(req, "too-large")
                return req.future
            if (self._queued_rows + self._inflight_rows + req.n
                    > cfg.max_queue_rows):
                self._reject(req, "queue-full")
                return req.future
            self._queue.append(req)
            self._queued_rows += req.n
            self._group.gauge("queueDepth", self._queued_rows)
            self._cond.notify_all()
        return req.future

    def _reject(self, req: _Request, reason: str) -> None:
        name = self._label()
        observe_serving_rejected(name, reason)
        tracing.tracer.event("serving.rejected", servable=name,
                             reason=reason, rows=req.n)
        req.future.set_exception(RejectedRequest(name, reason))

    def _label(self) -> str:
        try:
            servable = self._provider()
        except Exception:  # noqa: BLE001 — labeling must never raise
            servable = None
        return (serving_name(servable) if servable is not None
                else "unbound")

    # -- pad/enqueue stage ---------------------------------------------------
    def _run(self) -> None:
        cfg = self.config
        window_s = cfg.window_ms / 1000.0
        try:
            while True:
                batch: List[_Request] = []
                with self._cond:
                    while not self._queue and not self._stopping:
                        self._cond.wait()
                    if not self._queue and self._stopping:
                        return
                    # fill-or-window: dispatch early only when the
                    # LARGEST bucket's worth of rows is queued (any
                    # smaller fill threshold would defeat batching —
                    # one row "fills" bucket 1), else when the oldest
                    # request's window lapses; window_ms is therefore
                    # the latency bound a partially-filled batch pays
                    while (self._queue
                           and self._queued_rows < cfg.max_bucket
                           and not self._stopping):
                        remaining = (self._queue[0].t_enqueue + window_s
                                     - time.perf_counter())
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                    if not self._queue:
                        continue
                    total = 0
                    while (self._queue
                           and total + self._queue[0].n
                           <= cfg.max_bucket):
                        req = self._queue.popleft()
                        total += req.n
                        batch.append(req)
                    if not batch:
                        # head request alone exceeds the cap (unbucketed
                        # mode — bucketed admission already rejected it)
                        req = self._queue.popleft()
                        total = req.n
                        self._reject(req, "too-large")
                    else:
                        self._inflight_rows += total
                    self._queued_rows -= total
                    self._group.gauge("queueDepth", self._queued_rows)
                if not batch:
                    continue
                tick = self._tick_seq
                self._tick_seq += 1
                try:
                    prepared = self._prepare(batch, tick)
                except Exception as e:  # noqa: BLE001 — a pad-stage bug
                    # must fail ITS batch, never kill the loop
                    for req in batch:
                        if not req.future.done():
                            req.future.set_exception(e)
                    self._release_inflight(total)
                    continue
                if prepared is None:
                    self._release_inflight(total)
                    continue
                prepared.total_rows = total
                if self._handoff is not None:
                    # depth-bounded, blocking: while the device stage
                    # computes tick N, at most ``pipeline_depth``
                    # prepared ticks wait here — backpressure, not an
                    # unbounded prepared-batch backlog
                    self._handoff.put(prepared)
                else:
                    self._dispatch_guarded(prepared)
        finally:
            if self._handoff is not None:
                self._handoff.put(None)  # sentinel: pad stage is done

    def _prepare(self, batch: List[_Request],
                 tick: int) -> Optional[_Prepared]:
        """Pad stage: deadline/schema vetting + bucket padding — all
        host work, no device touch, so it overlaps the device stage's
        compute of the previous tick. Rejections resolve immediately
        from here; accepted requests travel in the returned
        :class:`_Prepared`."""
        cfg = self.config
        now = time.perf_counter()
        live: List[_Request] = []
        for req in batch:
            if req.deadline_s is not None and now > req.deadline_s:
                self._reject(req, "deadline")
            else:
                live.append(req)
        if not live:
            return None
        schema = live[0].schema
        rows: List = []
        kept: List[_Request] = []
        for req in live:
            if req.schema != schema:
                self._reject(req, "schema")
                continue
            kept.append(req)
            rows.extend(req.rows)
        if not kept:
            return None
        n_real = len(rows)
        bucket = cfg.bucket_for(n_real)
        # pad by duplicating a row: same shapes, discarded output. An
        # exact bucket fit (and every unbucketed tick, where the
        # "bucket" IS the drained row count) pads nothing — pinned by
        # the tick-drain boundary tests.
        pad = bucket - n_real
        reused = 0
        # the tick follows from the requests it drained: explicit
        # follows_from links to each request's submit context — with no
        # local parent the pad span adopts the first link's trace id,
        # so a single-request tick shares the request's trace outright
        link_ctxs = [req.ctx for req in kept if req.ctx is not None]
        pad_ctx = None
        with tracing.tracer.span("serving.pad", tick=tick,
                                 bucket=bucket, rows=n_real,
                                 requests=len(kept), pad=pad,
                                 links=link_ctxs or None) as pad_sp:
            pad_ctx = tracing.context_of(pad_sp)
            if pad:
                types = kept[0].df.data_types
                # the value-shape signature rides the key: the declared
                # DataType carries no dimension ("vector" is dim-less),
                # so a hot-swap changing the feature dim must MISS —
                # a stale different-dim template would fail every
                # padded tick after the swap
                key = (schema,
                       tuple((t.basic, t.shape) for t in types),
                       _row_signature(rows[-1]), bucket)
                template = self._pad_templates.get(key)
                if template is None:
                    if len(self._pad_templates) >= 32:
                        self._pad_templates.clear()
                    template = (type(rows[-1]), list(rows[-1].values))
                    self._pad_templates[key] = template
                else:
                    reused = pad
                row_cls, values = template
                for _ in range(pad):
                    rows.append(row_cls(list(values)))
            else:
                types = kept[0].df.data_types
            batch_df = DataFrame(list(schema), list(types), rows)
        # drift seam (observability/drift.py): pad rows are DUPLICATES
        # appended at the tail — sketching them would overweight one
        # row and inflate the sample floor with dependent copies; the
        # _served wrapper slices features/predictions to this count
        batch_df.drift_real_rows = n_real
        # quality seam (observability/evaluation.py): the per-request
        # row layout of this batch, so the _served wrapper can park
        # each request's scores in the feedback-join ring under its
        # ``req`` ordinal — pad rows sit past the segments' sum
        batch_df.request_segments = tuple((req.seq, req.n)
                                          for req in kept)
        fill = n_real / bucket if bucket else 1.0
        waste = pad / bucket if bucket else 0.0
        prepared = _Prepared(kept, batch_df, bucket, n_real, pad, fill,
                             waste, tick, reused)
        prepared.pad_ctx = pad_ctx
        return prepared

    def _release_inflight(self, rows: int) -> None:
        # called the moment the device stage takes a batch over: rows
        # actively dispatching stop counting against max_queue_rows
        # (matching the single-thread dispatcher, where drained rows
        # left the admission window at drain) — only rows queued,
        # padding, or waiting in the handoff occupy it
        with self._cond:
            self._inflight_rows = max(0, self._inflight_rows - rows)

    def _dispatch_guarded(self, prepared: _Prepared) -> None:
        """One device tick, from either stage layout: release the
        admission window (the batch is actively dispatching now) and
        run the dispatch — a dispatch bug fails ITS batch's futures,
        never the loop that called it."""
        self._release_inflight(prepared.total_rows)
        try:
            self._dispatch_device(prepared)
        except Exception as e:  # noqa: BLE001 — see docstring
            for req in prepared.requests:
                if not req.future.done():
                    req.future.set_exception(e)

    # -- device stage --------------------------------------------------------
    def _run_device(self) -> None:
        while True:
            prepared = self._handoff.get()
            if prepared is None:
                return
            self._dispatch_guarded(prepared)

    def _dispatch_device(self, prep: _Prepared) -> None:
        # FLINK_ML_TPU_PROFILE_CAPTURE=1 arms a device profile spanning
        # the next N dispatch ticks (observability/profiling.py); the
        # unarmed steady state pays one env read
        profiling.batch_tick()
        kept = prep.requests
        now = time.perf_counter()
        # deadlines re-checked HERE, not just at pad time: a request
        # whose deadline lapsed while its tick waited in the pipeline
        # handoff was never dispatched in time — the accounting stays
        # honest even though its rows ride the padded batch (the
        # shapes are fixed; only its result assignment is skipped)
        live: List[_Request] = []
        for req in kept:
            if req.deadline_s is not None and now > req.deadline_s:
                self._reject(req, "deadline")
            else:
                live.append(req)
        if not live:
            return
        servable = self._provider()
        if servable is None:
            for req in live:
                self._reject(req, "no-model")
            return
        if self._mesh is not None and hasattr(servable, "set_mesh"):
            # idempotent per tick: a hot-swapped candidate gets the
            # mesh before its first sharded batch, the steady state
            # pays one identity check
            servable.set_mesh(self._mesh)
        name = serving_name(servable)
        labels = {"servable": name}
        for req in live:
            # queue time runs to DEVICE dispatch, not to pad time —
            # a tick waiting in the pipeline handoff is still queueing
            self._group.windowed_histogram(
                "queueMs", horizon_s=SERVING_HORIZON_S,
                slices=SERVING_SLICES, labels=labels).observe(
                    (now - req.t_enqueue) * 1000.0)
        t0 = time.perf_counter()
        # the causal edges of this tick: the pad span whose prepared
        # batch crossed the pipeline handoff, plus every request this
        # batch serves — the links satellite-fixing "pad/batch carry
        # only tick=": a request's latency now decomposes from the DAG
        batch_links = [prep.pad_ctx] if prep.pad_ctx is not None else []
        batch_links += [req.ctx for req in live if req.ctx is not None]
        batch_ctx = None
        with tracing.tracer.span("serving.batch", servable=name,
                                 bucket=prep.bucket, rows=prep.n_real,
                                 requests=len(kept), tick=prep.tick,
                                 pipeline_depth=self.config
                                 .pipeline_depth,
                                 links=batch_links or None) as batch_sp:
            batch_ctx = tracing.context_of(batch_sp)
            try:
                out = servable.transform(prep.batch_df)
            except Exception as e:  # noqa: BLE001 — the batch fails,
                # per-request; the _served seam already counted it once
                for req in live:
                    if not req.future.done():
                        req.future.set_exception(e)
                return
        batch_ms = (time.perf_counter() - t0) * 1000.0
        self._record_tick(labels, prep.bucket, prep.n_real, prep.pad,
                          prep.fill, prep.waste, batch_ms, len(live),
                          prep.reused)
        # futures resolve from the fetch side: the results are on host
        # before any caller's latency clock stops. Offsets walk ALL of
        # the tick's requests — a deadline-rejected one still occupies
        # its row slice of the padded batch
        out_rows = out.collect()
        names, types = out.column_names, out.data_types
        offset = 0
        for req in kept:
            if not req.future.done():
                result = DataFrame(
                    names, types, out_rows[offset:offset + req.n])
                if req.ctx is not None:
                    # close the request's causal chain: a resolve span
                    # in the REQUEST's trace (child of its submit span)
                    # following from the batch that computed it — the
                    # last segment `flink-ml-tpu-trace path` attributes
                    with tracing.tracer.span(
                            "serving.resolve", parent=req.ctx,
                            links=([batch_ctx] if batch_ctx is not None
                                   else None),
                            req=req.seq, tick=prep.tick, rows=req.n):
                        req.future.set_result(result)
                else:
                    req.future.set_result(result)
            offset += req.n

    def _record_tick(self, labels, bucket, n_real, pad, fill, waste,
                     batch_ms, n_requests, reused: int = 0) -> None:
        grp = self._group
        self._ticks += 1
        self._served_requests += n_requests
        grp.counter("batches", labels={**labels, "bucket": str(bucket)})
        if pad:
            grp.counter("padRows", pad, labels=labels)
        if reused:
            # pad rows built from the cached per-(schema, bucket)
            # template instead of re-extracting the tail request's row
            grp.counter("paddingReuse", reused, labels=labels)
        grp.gauge("batchFill", round(fill, 4), labels=labels)
        grp.gauge("paddingWaste", round(waste, 4), labels=labels)
        grp.histogram("batchFillFrac", buckets=RATIO_BUCKETS,
                      labels=labels).observe(fill)
        grp.histogram("paddingWasteFrac", buckets=RATIO_BUCKETS,
                      labels=labels).observe(waste)
        grp.histogram("batchRows", buckets=COUNT_BUCKETS,
                      labels=labels).observe(float(n_real))
        grp.windowed_histogram("batchMs", horizon_s=SERVING_HORIZON_S,
                               slices=SERVING_SLICES,
                               labels=labels).observe(batch_ms)

    # -- live status (the /serving route) ------------------------------------
    def status(self) -> dict:
        """Live runtime status for the ``/serving`` endpoint route."""
        with self._cond:
            depth_rows = self._queued_rows
            depth_requests = len(self._queue)
            inflight = self._inflight_rows
        cfg = self.config
        return {
            "servable": self._label(),
            "queue": {"rows": depth_rows, "requests": depth_requests,
                      "pipeline_rows": inflight,
                      "max_rows": cfg.max_queue_rows},
            "buckets": (list(cfg.buckets) if cfg.buckets is not None
                        else None),
            "window_ms": cfg.window_ms,
            "deadline_ms": cfg.deadline_ms,
            "ticks": self._ticks,
            "served_requests": self._served_requests,
            "running": self._thread is not None,
            "pipeline_depth": cfg.pipeline_depth,
            "mesh_devices": self.mesh_device_count(),
            "sharded_dispatch": self.sharded_dispatch(),
            "model_version": getattr(self._target, "version", None),
            "canary": self._canary_status(),
        }

    def _canary_status(self):
        """Canary version/fraction from a registry target (None when
        the target has no canary seam or no canary is live) — the
        rollout's live surface on the ``/serving`` route."""
        version = getattr(self._target, "canary_version", None)
        if version is None:
            return None
        return {"version": version,
                "fraction": getattr(self._target, "canary_fraction",
                                    None)}

    def mesh_device_count(self) -> int:
        """Devices of the dispatch mesh (1 without one) — provenance
        for the ``/serving`` route and BENCH_serving.json rows."""
        return (int(self._mesh.devices.size)
                if self._mesh is not None else 1)

    def sharded_dispatch(self) -> bool:
        """True when ticks can shard — the DATA-shard count decides,
        exactly as the servable's routing does (on a (data, model)
        mesh the device count alone would misreport)."""
        if self._mesh is None:
            return False
        try:
            from flink_ml_tpu.parallel.mesh import data_shard_count

            return data_shard_count(self._mesh) > 1
        except Exception:  # noqa: BLE001 — status must never raise
            return self.mesh_device_count() > 1
