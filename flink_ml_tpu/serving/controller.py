"""Self-healing ops controller: the closed train→serve→observe loop.

Everything below this module already exists as a dashboard — drift
verdicts against fit-time baselines (observability/drift.py), windowed
SLO burn rates (observability/slo.py), online FTRL with a warm-start
seam (models/online.py), atomic publish + probe-gated hot-swap
(serving/registry.py). This module is the actuator that connects them:
a supervised control loop that watches its own telemetry and reacts —
the continuous train-and-serve workload the reference's online
algorithms exist for, run with the partial-participation resilience
posture of "Just-in-Time Aggregation for Federated Learning"
(arXiv:2208.09740): every stage tolerates injected failure and the loop
converges back to a healthy serving state.

State machine (docs/ops.md has the diagram)::

    watching ──trigger (drift/SLO violation on the active version)──▶
    retraining ──▶ publishing ──▶ canary ──▶ ramping ──▶ baking ──▶
    watching                                    │           │
         ▲                                      ▼           ▼
         └────────────────────────────── rolling-back ◀─────┘

- **watching**: evaluate the active version's drift verdict
  (:func:`~flink_ml_tpu.observability.drift.evaluate`), its
  continuous-evaluation quality verdict
  (:func:`~flink_ml_tpu.observability.evaluation.evaluate` — live AUC
  from joined ground truth vs the published quality baseline) and any
  configured SLOs; a violation starts a cycle.
- **retraining**: the caller's ``retrain`` callable (typically an FTRL
  ``warm_start`` refit on recent traffic) under
  :func:`~flink_ml_tpu.resilience.supervisor.run_supervised` — an
  injected/transient failure is RETRYABLE with backoff, a
  :class:`~flink_ml_tpu.resilience.policy.NonFiniteState` (diverged
  refit) is TERMINAL and ends the cycle ``failed`` with the active
  version untouched.
- **publishing**: :func:`~flink_ml_tpu.serving.registry.publish_model`
  with the refit's FRESH drift baseline — the new version is compared
  against the distribution it was actually trained on.
- **canary**: :meth:`~flink_ml_tpu.serving.registry.ModelRegistry
  .load_candidate` — validate + probe without swapping.
  :class:`~flink_ml_tpu.resilience.policy.CandidateRejected` is
  terminal (``rejected`` outcome; rollback by construction — the
  serving version was never replaced).
- **ramping**: the canary rides at ``ramp_stages`` traffic fractions
  (:meth:`~flink_ml_tpu.serving.registry.ModelRegistry.resolve`); each
  stage must serve ``stage_min_requests`` and read healthy on the
  canary's error/drift/latency/finite gauges before the next; the last
  stage promotes (the committed swap).
- **baking**: post-swap observation on the SAME gauges; a regression
  triggers :meth:`~flink_ml_tpu.serving.registry.ModelRegistry
  .rollback` — v(N-1) re-activates WITHOUT re-probe, the demoted
  version is remembered and its drift windows forgotten.
- **rolling-back**: supervised like every other step (the
  ``model-rollback`` chaos site fires inside); the cycle ends
  ``rolled-back`` — the loop did its job, a bad candidate never kept
  serving.

Telemetry: every transition/cycle lands an ``ml.controller`` instant
event + ``transitions{model=,from=,to=}`` / ``cycles{model=,outcome=}``
counters, steps run inside ``controller.*`` spans, the live state
serves on the ``/controller`` route (observability/server.py) and the
artifacts render through ``flink-ml-tpu-trace controller <dir>
[--check]`` (exit 4 when the loop did not end healthy, 2 on missing
telemetry — the CI gate of scripts/ops_loop_smoke.py).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import tracing
from flink_ml_tpu.resilience import faults
from flink_ml_tpu.resilience.policy import (
    CandidateRejected,
    RestartsExhausted,
    RetryPolicy,
)
from flink_ml_tpu.resilience.supervisor import run_supervised
from flink_ml_tpu.serving.registry import publish_model

__all__ = [
    "WATCHING", "RETRAINING", "PUBLISHING", "CANARY", "RAMPING",
    "BAKING", "ROLLING_BACK", "STATES", "OUTCOMES",
    "CONTROLLER_EVENT", "EXIT_OK", "EXIT_INVALID", "EXIT_UNHEALTHY",
    "ControllerConfig", "OpsController", "main",
]

# -- states / outcomes --------------------------------------------------------

WATCHING = "watching"
RETRAINING = "retraining"
PUBLISHING = "publishing"
CANARY = "canary"
RAMPING = "ramping"
BAKING = "baking"
ROLLING_BACK = "rolling-back"

STATES = (WATCHING, RETRAINING, PUBLISHING, CANARY, RAMPING, BAKING,
          ROLLING_BACK)

#: cycle outcomes, the ``cycles{model=,outcome=}`` counter's label set:
#: ``swapped`` (healthy candidate promoted and baked), ``rolled-back``
#: (bad candidate demoted — the loop worked), ``rejected`` (candidate
#: failed the probe; the serving version was never replaced) and
#: ``failed`` (a step failed terminally; the loop gave the cycle up —
#: the only outcome ``--check`` treats as unhealthy)
OUTCOMES = ("swapped", "rolled-back", "rejected", "failed")

#: instant-event name for controller transitions/cycles in the trace
CONTROLLER_EVENT = "ml.controller"

EXIT_OK = 0
EXIT_INVALID = 2
#: the CLI's unhealthy exit — same class as slo/drift's violation 4
EXIT_UNHEALTHY = 4

_ENV_PREFIX = "FLINK_ML_TPU_OPS_"


def _env(name: str) -> str:
    return _ENV_PREFIX + name


# -- configuration ------------------------------------------------------------

@dataclasses.dataclass
class ControllerConfig:
    """Knobs of the control loop; every field has an env twin
    (``FLINK_ML_TPU_OPS_*``, :meth:`from_env` — docs/ops.md table)."""

    #: watcher cadence of the background thread (step mode ignores it)
    check_interval_s: float = 5.0
    #: canary traffic fractions ramped pre-swap, ascending; empty →
    #: promote straight after the probe and rely on the bake stage
    ramp_stages: Tuple[float, ...] = (0.25, 0.5, 1.0)
    #: requests the canary must serve in a stage before its verdict
    stage_min_requests: int = 50
    #: requests the promoted version must serve before the cycle ends
    bake_min_requests: int = 50
    #: threaded mode: a stage/bake starved of traffic past this passes
    #: with a ``no-evidence-timeout`` note instead of wedging the loop
    stage_timeout_s: float = 120.0
    #: canary/bake error-ratio bound (errors / (errors + transforms))
    max_error_ratio: float = 0.02
    #: optional canary/bake p-quantile latency bound (None = skip)
    latency_threshold_ms: Optional[float] = None
    latency_quantile: float = 0.99
    latency_window_s: float = 60.0
    #: consult the continuous-evaluation verdict (observability/
    #: evaluation.py — live AUC vs the published quality baseline) as a
    #: canary/bake stage; thresholds are evaluation's own
    #: ``FLINK_ML_TPU_QUALITY_*`` knobs. Only bites when a quality
    #: baseline was published with the candidate — versions published
    #: without one skip the stage (``source: missing``)
    quality_gate: bool = True
    #: quiet period after a finished cycle before the next trigger
    cooldown_s: float = 10.0
    #: retry/backoff budget for each supervised step (retrain, publish,
    #: canary adopt, swap, rollback)
    policy: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(max_restarts=4,
                                            backoff_s=0.05,
                                            max_backoff_s=2.0))
    #: extra SLOs evaluated as triggers beside the drift verdict
    slos: Optional[Sequence] = None

    def __post_init__(self):
        stages = tuple(float(f) for f in self.ramp_stages)
        if any(not 0.0 < f <= 1.0 for f in stages):
            raise ValueError("ramp_stages fractions must be in (0, 1]")
        if list(stages) != sorted(stages):
            raise ValueError("ramp_stages must be ascending")
        self.ramp_stages = stages
        if self.stage_min_requests < 1 or self.bake_min_requests < 1:
            raise ValueError("stage/bake min_requests must be >= 1")
        if not 0.0 <= self.max_error_ratio <= 1.0:
            raise ValueError("max_error_ratio must be in [0, 1]")
        if not 0.0 < self.latency_quantile <= 1.0:
            # fail at construction, not inside a live canary verdict
            # (a percent-style 99 would wedge every rollout mid-ramp)
            raise ValueError("latency_quantile must be in (0, 1] — "
                             "a fraction, not a percentage")
        if self.latency_window_s <= 0.0:
            raise ValueError("latency_window_s must be positive")

    @classmethod
    def from_env(cls, **overrides) -> "ControllerConfig":
        """Build from ``FLINK_ML_TPU_OPS_*`` (unset → field default);
        explicit ``overrides`` win. Malformed values raise ValueError —
        an ops misconfiguration must fail loudly at start, not steer a
        live rollout."""
        def read(env, parse, key):
            raw = os.environ.get(_env(env))
            if raw is not None and key not in overrides:
                try:
                    overrides[key] = parse(raw)
                except ValueError as e:
                    raise ValueError(
                        f"{_env(env)}={raw!r}: {e}") from e

        def parse_stages(raw):
            raw = raw.strip()
            if not raw:
                return ()
            return tuple(float(p) for p in raw.split(","))

        read("INTERVAL_S", float, "check_interval_s")
        read("STAGES", parse_stages, "ramp_stages")
        read("STAGE_MIN_REQUESTS", int, "stage_min_requests")
        read("BAKE_MIN_REQUESTS", int, "bake_min_requests")
        read("STAGE_TIMEOUT_S", float, "stage_timeout_s")
        read("MAX_ERROR_RATIO", float, "max_error_ratio")
        read("LATENCY_MS", float, "latency_threshold_ms")
        read("LATENCY_QUANTILE", float, "latency_quantile")
        read("LATENCY_WINDOW_S", float, "latency_window_s")
        read("COOLDOWN_S", float, "cooldown_s")

        def parse_bool(raw):
            lowered = raw.strip().lower()
            if lowered in ("1", "true", "yes", "on"):
                return True
            if lowered in ("0", "false", "no", "off"):
                return False
            raise ValueError("expected a boolean (1/0/true/false)")

        read("QUALITY_GATE", parse_bool, "quality_gate")
        return cls(**overrides)


# -- the controller -----------------------------------------------------------

class OpsController:
    """The supervised control loop over a
    :class:`~flink_ml_tpu.serving.registry.ModelRegistry`.

    ``retrain(trigger)`` is the caller's refit seam: given the trigger
    dict (``reasons``, ``servable``, ``version``), return
    ``(leaves, baseline)`` — the model arrays to publish and the fresh
    :class:`~flink_ml_tpu.observability.drift.DriftBaseline` captured
    on the data it refit over — or ``(leaves, baseline,
    quality_baseline)`` to also publish the fit-time
    :class:`~flink_ml_tpu.observability.evaluation.QualityBaseline`
    that arms the canary's live-AUC quality stage (or a bare
    ``leaves`` list; publishing without baselines degrades the NEXT
    cycle's drift trigger to ``source: missing`` and skips the quality
    stage). Typically an
    :meth:`~flink_ml_tpu.models.online.OnlineLogisticRegression
    .warm_start` FTRL fit on recent traffic.

    Drive it synchronously (:meth:`step` — deterministic, what the
    chaos smoke and tests use) or as a background thread
    (:meth:`start`/:meth:`stop`, ``check_interval_s`` cadence). The
    loop itself is supervised: an escaping step bug is counted
    (``stepErrors{model=}``), backed off and re-entered — the
    controller must outlive any single bad evaluation.
    """

    def __init__(self, registry, retrain: Callable,
                 config: Optional[ControllerConfig] = None):
        self.registry = registry
        self.model = registry.model
        self._retrain_fn = retrain
        self.config = config or ControllerConfig()
        self.state = WATCHING
        self.cycle = 0
        #: [(from, to, reason, cycle)] — the deterministic transition
        #: log the chaos smoke compares across same-seed runs
        self.transitions: List[dict] = []
        self._outcomes: Dict[str, int] = {}
        self._trigger: Optional[dict] = None
        self._pending: dict = {}
        self._cooldown_until = 0.0
        self._cycle_t0: Optional[float] = None
        # the live cycle's trace: the step span where the trigger fired
        # mints it (its ml.drift/ml.slo trigger events are INSIDE that
        # span), every later step of the cycle links follows_from to
        # the previous step's context and adopts the same trace id —
        # one retrain→publish→canary→…→watching cycle reads as ONE
        # trace chained across steps (docs/observability.md)
        self._cycle_ctx = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = make_lock("serving.controller")
        self._group = metrics.group(ML_GROUP, "controller")
        # the /controller route reflects this controller from
        # construction — step-driven controllers (tests, the smoke)
        # never start the thread but are just as live
        from flink_ml_tpu.observability import server

        server.set_controller_status(self.status)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "OpsController":
        """Run the loop on a daemon thread (``check_interval_s``
        cadence while watching)."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="flink-ml-tpu-ops-controller",
            daemon=True)
        self._thread.start()
        # join the fleet telemetry plane: the controller's beacon
        # carries its recent ml.controller events and gauges
        # (observability/fleet.py; no-op when no fleet dir resolves)
        try:
            from flink_ml_tpu.observability import fleet

            self._fleet_token = fleet.start_beacon(role="controller")
        except Exception:
            self._fleet_token = None
        return self

    def stop(self) -> None:
        """Stop the thread (if running) and release the ``/controller``
        provider; a canary left mid-ramp is dropped (NOT condemned) —
        an unsupervised canary must not keep taking traffic."""
        thread = self._thread
        if thread is not None:
            self._stop.set()
            thread.join(timeout=30.0)
            self._thread = None
        try:
            from flink_ml_tpu.observability import fleet

            fleet.stop_beacon(getattr(self, "_fleet_token", None))
            self._fleet_token = None
        except Exception:
            pass
        from flink_ml_tpu.observability import server

        server.clear_controller_status(self.status)
        if self.registry.canary_version is not None:
            self.registry.drop_canary("controller-stopped")
            self._transition(WATCHING, "controller-stopped")
        version = self._pending.get("version")
        if version is not None:
            # a cycle abandoned between publish and adopt must not
            # keep its version held against the watcher forever
            self.registry.release_version(version)

    def __enter__(self) -> "OpsController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        errors = 0
        while not self._stop.is_set():
            try:
                self.step()
                errors = 0
            except Exception as e:  # noqa: BLE001 — the loop survives
                # its own bugs: count, back off, re-enter
                errors += 1
                self._group.counter("stepErrors",
                                    labels={"model": self.model})
                tracing.tracer.event(CONTROLLER_EVENT, kind="step-error",
                                     model=self.model,
                                     error=type(e).__name__,
                                     detail=str(e))
            idle = self.state == WATCHING
            delay = (self.config.check_interval_s if idle else 0.05)
            if errors:
                delay = max(delay,
                            min(0.1 * 2.0 ** (errors - 1), 30.0))
            if self._stop.wait(delay):
                return

    # -- the state machine ----------------------------------------------------
    def step(self) -> str:
        """Advance the machine by at most one transition; returns the
        (possibly unchanged) state. Synchronous and deterministic given
        deterministic traffic/verdicts — the smoke's driver."""
        with self._lock:
            in_cycle = self.state != WATCHING
            links = ([self._cycle_ctx]
                     if (in_cycle and self._cycle_ctx is not None)
                     else None)
            with tracing.tracer.span("controller.step",
                                     model=self.model,
                                     state=self.state,
                                     links=links) as sp:
                handler = {
                    WATCHING: self._step_watching,
                    RETRAINING: self._step_retraining,
                    PUBLISHING: self._step_publishing,
                    CANARY: self._step_canary,
                    RAMPING: self._step_ramping,
                    BAKING: self._step_baking,
                    ROLLING_BACK: self._step_rolling_back,
                }[self.state]
                handler()
                ctx = tracing.context_of(sp)
            if self.state != WATCHING:
                # a cycle is (still) live: the NEXT step chains to this
                # one. The step that triggered it (watching → retraining)
                # mints the cycle trace — its trigger events ride along
                self._cycle_ctx = ctx
            elif not in_cycle or self._trigger is None:
                # back in watching with no cycle pending: the chain is
                # closed (the finishing step still linked to its
                # predecessor above)
                self._cycle_ctx = None
            return self.state

    def _transition(self, to: str, reason: str = "") -> None:
        frm = self.state
        self.state = to
        self.transitions.append({"from": frm, "to": to,
                                 "reason": reason, "cycle": self.cycle})
        self._group.counter("transitions",
                            labels={"model": self.model, "from": frm,
                                    "to": to})
        tracing.tracer.event(CONTROLLER_EVENT, kind="transition",
                             model=self.model, cycle=self.cycle,
                             reason=reason,
                             **{"from": frm, "to": to})

    def _finish_cycle(self, outcome: str, reason: str = "") -> None:
        self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
        self._group.counter("cycles", labels={"model": self.model,
                                              "outcome": outcome})
        if self._cycle_t0 is not None:
            self._group.histogram("cycleMs", labels={
                "model": self.model}).observe(
                (time.monotonic() - self._cycle_t0) * 1000.0)
        tracing.tracer.event(CONTROLLER_EVENT, kind="cycle",
                             model=self.model, cycle=self.cycle,
                             outcome=outcome, reason=reason)
        version = self._pending.get("version")
        if version is not None and outcome != "failed":
            # the rollout owns the version no longer: promoted versions
            # are the serving one, rejected/rolled-back ones are
            # remembered — either way the watcher guard can lift. A
            # "failed" cycle is different: its version may sit on disk
            # neither vetted nor condemned (e.g. the canary budget
            # exhausted on transient probe failures) — it STAYS held,
            # or the watcher would adopt un-ramped exactly the
            # candidate this controller declined to promote
            self.registry.release_version(version)
        self._pending = {}
        self._trigger = None
        self._cycle_t0 = None
        self._cooldown_until = time.monotonic() + self.config.cooldown_s
        self._transition(WATCHING, f"{outcome}: {reason}" if reason
                         else outcome)

    # -- watching -------------------------------------------------------------
    def _active_name(self) -> Optional[str]:
        active = self.registry.active
        if active is None:
            return None
        from flink_ml_tpu.servable.api import serving_name

        return serving_name(active)

    def _step_watching(self) -> None:
        if time.monotonic() < self._cooldown_until:
            return
        name = self._active_name()
        if name is None:
            return  # nothing serving yet — nothing to heal
        reasons = self._check_trigger(name)
        if not reasons:
            return
        self.cycle += 1
        self._cycle_t0 = time.monotonic()
        self._trigger = {"reasons": reasons, "servable": name,
                         "version": self.registry.version}
        tracing.tracer.event(CONTROLLER_EVENT, kind="trigger",
                             model=self.model, cycle=self.cycle,
                             servable=name, reasons=";".join(reasons))
        self._transition(RETRAINING, ";".join(reasons))

    def _check_trigger(self, name: str) -> List[str]:
        reasons: List[str] = []
        from flink_ml_tpu.observability import drift

        if drift.enabled():
            verdict = drift.evaluate(name)
            if verdict["drifted"]:
                reasons.append(
                    f"drift:{','.join(verdict['drifted'])}")
        if self.config.quality_gate:
            # the continuous-evaluation twin of the drift trigger:
            # joined ground truth says the ACTIVE version's live AUC
            # fell below the floor / under the published baseline —
            # concept drift the feature sketches cannot see
            from flink_ml_tpu.observability import evaluation

            if evaluation.enabled():
                q = evaluation.evaluate(name)
                if q["degraded"]:
                    reasons.append(
                        f"quality:{','.join(q['over'])}")
        if self.config.slos:
            from flink_ml_tpu.observability import slo as slo_mod

            for v in slo_mod.evaluate_slos(self.config.slos,
                                           emit=True):
                if not v["ok"]:
                    reasons.append(f"slo:{v['slo']}")
        return reasons

    # -- retraining / publishing ----------------------------------------------
    def _step_retraining(self) -> None:
        trigger = dict(self._trigger or {})

        def retrain_once():
            faults.inject("controller-retrain", model=self.model)
            return self._retrain_fn(trigger)

        try:
            with tracing.tracer.span("controller.retrain",
                                     model=self.model,
                                     cycle=self.cycle):
                t0 = time.monotonic()
                out = run_supervised(retrain_once,
                                     policy=self.config.policy)
                self._group.histogram("retrainMs", labels={
                    "model": self.model}).observe(
                    (time.monotonic() - t0) * 1000.0)
        except Exception as e:  # noqa: BLE001 — terminal taxonomy or
            # an exhausted budget: the cycle fails, the active version
            # keeps serving
            self._finish_cycle("failed",
                               f"retrain: {type(e).__name__}: {e}")
            return
        quality_baseline = None
        if isinstance(out, tuple) and len(out) == 3:
            leaves, baseline, quality_baseline = out
        elif isinstance(out, tuple) and len(out) == 2:
            leaves, baseline = out
        else:
            leaves, baseline = out, None
        self._group.counter("retrains", labels={"model": self.model})
        self._pending = {"leaves": leaves, "baseline": baseline,
                         "quality_baseline": quality_baseline}
        self._transition(PUBLISHING, "retrained")

    def _step_publishing(self) -> None:
        published = self.registry.published_versions()
        current = self.registry.version or 0
        version = max(published + [current]) + 1
        leaves = self._pending["leaves"]
        baseline = self._pending["baseline"]
        quality_baseline = self._pending.get("quality_baseline")
        # claim the version BEFORE it exists on disk: a running watcher
        # thread must never adopt the candidate directly and bypass the
        # canary/ramp/bake gates (released when the cycle finishes)
        self.registry.hold_version(version)
        self._pending["version"] = version

        def publish_once():
            faults.inject("controller-publish", model=self.model,
                          version=version)
            return publish_model(self.registry.watch_dir, leaves,
                                 version, baseline=baseline,
                                 quality_baseline=quality_baseline)

        try:
            with tracing.tracer.span("controller.publish",
                                     model=self.model, version=version):
                run_supervised(publish_once, policy=self.config.policy)
        except Exception as e:  # noqa: BLE001 — see _step_retraining
            self._finish_cycle("failed",
                               f"publish: {type(e).__name__}: {e}")
            return
        self._transition(CANARY, f"published v{version}")

    # -- canary / ramping / baking --------------------------------------------
    def _step_canary(self) -> None:
        version = self._pending["version"]

        def adopt_once():
            # the canary-probe chaos site fires inside the registry's
            # probe; injected faults surface retryable here
            return self.registry.load_candidate(version)

        try:
            with tracing.tracer.span("controller.canary",
                                     model=self.model, version=version):
                candidate = run_supervised(adopt_once,
                                           policy=self.config.policy)
        except CandidateRejected as e:
            # terminal bad candidate: remember it (the watcher must not
            # re-adopt), count the rejection, end the cycle — the
            # serving version was never replaced (rollback by
            # construction)
            self.registry.record_rejection(version, e.reason, str(e))
            self._finish_cycle("rejected", str(e))
            return
        except Exception as e:  # noqa: BLE001 — exhausted budget or an
            # unexpected terminal failure: same safety, the active
            # version keeps serving
            self._finish_cycle("failed",
                               f"canary: {type(e).__name__}: {e}")
            return
        self.registry.set_canary(candidate, version, fraction=0.0)
        self._pending["stage"] = 0
        self._transition(RAMPING, f"canary v{version} probed")

    def _counts_for(self, name: str,
                    snap: Optional[dict] = None) -> Dict[str, float]:
        if snap is None:
            snap = metrics.group(ML_GROUP, "serving").snapshot()
        counters = snap.get("counters", {})
        from flink_ml_tpu.observability.slo import _match_key

        def total(metric):
            return sum(int(v) for k, v in counters.items()
                       if _match_key(k, metric, {"servable": name}))

        return {"transforms": total("transforms"),
                "errors": total("errors")}

    def _canary_verdict(self, name: str, since: Dict[str, float],
                        min_requests: int,
                        deadline: float) -> Tuple[str, str]:
        """(status, detail): ``thin`` (insufficient evidence — wait),
        ``regressed`` or ``healthy``. Gauge order mirrors severity:
        non-finite predictions, error ratio, drift, quality (live AUC
        vs the published quality baseline), latency."""
        # ONE registry snapshot serves the counts and the gauge scan —
        # the verdict runs every step of a rollout
        snap = metrics.group(ML_GROUP, "serving").snapshot()
        now_counts = self._counts_for(name, snap)
        served = now_counts["transforms"] - since["transforms"]
        errors = now_counts["errors"] - since["errors"]
        if served + errors < min_requests:
            if time.monotonic() < deadline:
                return "thin", f"{int(served + errors)} request(s)"
            # starved of traffic: no evidence of regression is not
            # evidence of health, but wedging the rollout forever is
            # worse — proceed, loudly
            tracing.tracer.event(CONTROLLER_EVENT,
                                 kind="no-evidence-timeout",
                                 model=self.model, servable=name)
            return "healthy", "no-evidence-timeout"
        gauges = snap.get("gauges", {})
        # the registry probe's idiom: the PR 5 prediction-distribution
        # gauges, labeled by the versioned serving name
        label = f'servable="{name}"'
        for key, value in gauges.items():
            if "FiniteFraction" in key and label in key:
                try:
                    if float(value) < 1.0:
                        return "regressed", f"non-finite: {key}={value}"
                except (TypeError, ValueError):
                    continue
        total = served + errors
        ratio = errors / total if total else 0.0
        if ratio > self.config.max_error_ratio:
            return "regressed", (f"error-ratio {ratio:.4f} > "
                                 f"{self.config.max_error_ratio:g}")
        from flink_ml_tpu.observability import drift

        if drift.enabled():
            verdict = drift.evaluate(name)
            if verdict["drifted"]:
                return "regressed", (
                    f"drift: {','.join(verdict['drifted'])}")
            series = verdict.get("series", {})
            if verdict.get("source") == "baseline" and (
                    not series
                    or all(row.get("thin") for row in series.values())):
                # a baseline exists but the live window is below the
                # drift sample floor: "no drift" is absence of
                # evidence, not evidence of health — keep watching
                # (bounded by the same stage deadline)
                if time.monotonic() < deadline:
                    return "thin", "drift window below sample floor"
                tracing.tracer.event(CONTROLLER_EVENT,
                                     kind="no-evidence-timeout",
                                     model=self.model, servable=name)
        if self.config.quality_gate:
            from flink_ml_tpu.observability import evaluation

            if evaluation.enabled():
                q = evaluation.evaluate(name)
                if q["degraded"]:
                    base_auc = (q["baseline"] or {}).get("auc")
                    vs = (f" vs baseline {base_auc:.4f}"
                          if base_auc is not None
                          and math.isfinite(base_auc) else "")
                    return "regressed", (
                        f"quality: {','.join(q['over'])} (live auc "
                        f"{q['live']['auc']:.4f}{vs})")
                if q["source"] == "baseline" and q["thin"]:
                    # the drift precedent again: a published quality
                    # baseline with too few joined labels is absence of
                    # evidence — wait for feedback, bounded by the same
                    # stage deadline (labels are delayed by nature)
                    if time.monotonic() < deadline:
                        return "thin", "quality window below label floor"
                    tracing.tracer.event(CONTROLLER_EVENT,
                                         kind="no-evidence-timeout",
                                         model=self.model, servable=name)
        if self.config.latency_threshold_ms is not None:
            p = self._latency_quantile(name)
            if p is not None and p > self.config.latency_threshold_ms:
                return "regressed", (
                    f"latency p{self.config.latency_quantile * 100:g} "
                    f"{p:.1f}ms > "
                    f"{self.config.latency_threshold_ms:g}ms")
        return "healthy", f"{int(served)} request(s)"

    def _latency_quantile(self, name: str) -> Optional[float]:
        from flink_ml_tpu.common.metrics import histogram_quantile
        from flink_ml_tpu.observability.slo import _RegistrySource

        snap, _src = _RegistrySource(metrics).hist_window(
            f"{ML_GROUP}.serving", "transformMs",
            {"servable": name}, self.config.latency_window_s)
        if not snap or not snap.get("count"):
            return None
        value = histogram_quantile(snap, self.config.latency_quantile)
        return None if math.isnan(value) else value

    def _canary_name(self) -> str:
        return f"{self.model}@v{self._pending['version']}"

    def _step_ramping(self) -> None:
        stages = self.config.ramp_stages
        i = self._pending.get("stage", 0)
        name = self._canary_name()
        if i >= len(stages):
            # every stage passed (or none configured): promote — THE
            # committed swap, supervised (model-swap chaos site inside)
            version = self._pending["version"]
            try:
                with tracing.tracer.span("controller.swap",
                                         model=self.model,
                                         version=version):
                    run_supervised(self.registry.promote_canary,
                                   policy=self.config.policy)
            except Exception as e:  # noqa: BLE001 — could not commit:
                # demote the canary rather than leave it half-rolled
                self._transition(ROLLING_BACK,
                                 f"swap: {type(e).__name__}: {e}")
                return
            self._pending["bake_since"] = self._counts_for(name)
            self._pending["bake_deadline"] = (
                time.monotonic() + self.config.stage_timeout_s)
            self._transition(BAKING, f"v{version} promoted")
            return
        if self._pending.get("stage_set") != i:
            self.registry.set_canary_fraction(stages[i])
            self._pending["stage_set"] = i
            self._pending["stage_since"] = self._counts_for(name)
            self._pending["stage_deadline"] = (
                time.monotonic() + self.config.stage_timeout_s)
            return  # judge on a later step, once traffic flowed
        status, detail = self._canary_verdict(
            name, self._pending["stage_since"],
            self.config.stage_min_requests,
            self._pending["stage_deadline"])
        if status == "thin":
            return
        if status == "regressed":
            self._transition(ROLLING_BACK,
                             f"stage {stages[i]:g}: {detail}")
            return
        tracing.tracer.event(CONTROLLER_EVENT, kind="stage-pass",
                             model=self.model, fraction=stages[i],
                             detail=detail)
        self._pending["stage"] = i + 1

    def _step_baking(self) -> None:
        name = self._canary_name()
        status, detail = self._canary_verdict(
            name, self._pending["bake_since"],
            self.config.bake_min_requests,
            self._pending["bake_deadline"])
        if status == "thin":
            return
        if status == "regressed":
            self._transition(ROLLING_BACK, f"bake: {detail}")
            return
        self._finish_cycle("swapped",
                           f"v{self._pending['version']} healthy "
                           f"({detail})")

    # -- rolling back ---------------------------------------------------------
    @staticmethod
    def _short_reason(detail: str) -> str:
        """Fold a verdict detail into the small ``reason`` label set of
        ``rollbacks{model=,reason=}`` — labels must stay low-cardinality
        (common/metrics.py)."""
        for token in ("quality", "drift", "error-ratio", "non-finite",
                      "latency", "swap"):
            if token in detail:
                return token
        return "regression"

    def _step_rolling_back(self) -> None:
        detail = (self.transitions[-1]["reason"]
                  if self.transitions else "regression")
        reason = self._short_reason(detail)

        def rollback_once():
            # the model-rollback chaos site fires inside the registry
            return self.registry.rollback(reason=reason)

        try:
            with tracing.tracer.span("controller.rollback",
                                     model=self.model):
                restored = run_supervised(rollback_once,
                                          policy=self.config.policy)
        except RestartsExhausted:
            # a rollback MUST land: stay in this state and re-enter on
            # the next step rather than leaving a condemned version
            # serving
            self._group.counter("rollbackRetries",
                                labels={"model": self.model})
            return
        except Exception as e:  # noqa: BLE001 — truly terminal (e.g.
            # no prior version to restore): give the cycle up loudly
            self._finish_cycle("failed",
                               f"rollback: {type(e).__name__}: {e}")
            return
        self._finish_cycle("rolled-back",
                           f"restored v{restored} ({reason})")

    # -- live status ----------------------------------------------------------
    def status(self) -> dict:
        """Live state for the ``/controller`` route."""
        canary_version = self.registry.canary_version
        return {
            "model": self.model,
            "state": self.state,
            "cycle": self.cycle,
            "active_version": self.registry.version,
            "canary": (None if canary_version is None else
                       {"version": canary_version,
                        "fraction": self.registry.canary_fraction}),
            "trigger": self._trigger,
            "outcomes": dict(self._outcomes),
            "transitions": self.transitions[-20:],
            "running": self._thread is not None,
        }


# -- artifacts view / CLI -----------------------------------------------------

def controller_summary(spans: List[dict],
                       snapshot: Dict[str, dict]) -> dict:
    """Structured controller view from trace artifacts: the
    ``ml.controller`` event timeline + counters, per model."""
    events = []
    for sp in spans:
        for ev in sp.get("events", ()):
            if ev.get("name") == CONTROLLER_EVENT:
                events.append({"ts_us": ev.get("ts_us", 0),
                               **ev.get("attrs", {})})
    events.sort(key=lambda e: e["ts_us"])
    models: Dict[str, dict] = {}
    for ev in events:
        row = models.setdefault(ev.get("model", "?"), {
            "cycles": {}, "transitions": [], "triggers": 0,
            "last_state": None})
        kind = ev.get("kind")
        if kind == "transition":
            row["transitions"].append(ev)
            row["last_state"] = ev.get("to")
        elif kind == "cycle":
            outcome = ev.get("outcome", "?")
            row["cycles"][outcome] = row["cycles"].get(outcome, 0) + 1
        elif kind == "trigger":
            row["triggers"] += 1
    ctrl = snapshot.get(f"{ML_GROUP}.controller", {})
    serving = snapshot.get(f"{ML_GROUP}.serving", {})

    def counter_total(group: dict, prefix: str) -> int:
        return sum(int(v) for k, v in
                   group.get("counters", {}).items()
                   if k == prefix or k.startswith(prefix + "{"))

    return {
        "models": models,
        "events": len(events),
        "counters": {
            "transitions": counter_total(ctrl, "transitions"),
            "cycles": counter_total(ctrl, "cycles"),
            "retrains": counter_total(ctrl, "retrains"),
            "stepErrors": counter_total(ctrl, "stepErrors"),
            "rollbacks": counter_total(serving, "rollbacks"),
            "swapRejected": counter_total(serving, "swapRejected"),
            "watcherRestarts": counter_total(serving,
                                             "watcherRestarts"),
        },
    }


def render_controller(summary: dict) -> str:
    out = [f"{summary['events']} ml.controller event(s)"]
    c = summary["counters"]
    out.append(f"  retrains {c['retrains']}  rollbacks "
               f"{c['rollbacks']}  swap-rejected {c['swapRejected']}  "
               f"watcher-restarts {c['watcherRestarts']}  step-errors "
               f"{c['stepErrors']}")
    for model, row in sorted(summary["models"].items()):
        outcomes = ", ".join(f"{k}={v}" for k, v in
                             sorted(row["cycles"].items())) or "none"
        out.append("")
        out.append(f"model {model}: {row['triggers']} trigger(s), "
                   f"cycles: {outcomes}, last state: "
                   f"{row['last_state'] or '-'}")
        if row["transitions"]:
            t0 = row["transitions"][0]["ts_us"]
            for ev in row["transitions"]:
                reason = ev.get("reason", "")
                out.append(
                    f"  +{(ev['ts_us'] - t0) / 1000.0:>10.3f} ms  "
                    f"{ev.get('from', '?'):>12} -> "
                    f"{ev.get('to', '?'):<12} {reason}".rstrip())
    return "\n".join(out)


def check_verdict(summary: dict) -> List[str]:
    """Reasons the artifacts read unhealthy (empty = healthy): a cycle
    that ended ``failed``, or a controller whose LAST recorded state is
    not ``watching`` — the loop must always converge back to watching,
    whatever was injected along the way."""
    problems = []
    for model, row in sorted(summary["models"].items()):
        failed = row["cycles"].get("failed", 0)
        if failed:
            problems.append(f"{model}: {failed} failed cycle(s)")
        if row["last_state"] not in (None, WATCHING):
            problems.append(f"{model}: ended in state "
                            f"{row['last_state']!r} (not watching)")
    return problems


def main(argv=None) -> int:
    """``flink-ml-tpu-trace controller <dir>`` — render the controller
    timeline from trace artifacts; ``--check`` exits
    :data:`EXIT_UNHEALTHY` (4) when the loop did not end healthy,
    :data:`EXIT_INVALID` (2) on missing/broken artifacts."""
    import argparse
    import sys

    from flink_ml_tpu.observability.exporters import (
        pipe_guard,
        read_metrics,
        read_spans,
        resolve_trace_dir,
    )

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace controller",
        description="Ops-controller timeline and verdicts from a "
                    "FLINK_ML_TPU_TRACE_DIR's artifacts "
                    "(docs/ops.md).")
    parser.add_argument("trace_dir")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 4 unless every controller ended "
                             "healthy (no failed cycles, last state "
                             "watching), 2 on missing telemetry")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    args = parser.parse_args(argv)

    try:
        trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        spans = read_spans(trace_dir)
        snapshot = read_metrics(trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace controller: cannot read "
              f"{args.trace_dir}: {e}", file=sys.stderr)
        return EXIT_INVALID
    summary = controller_summary(spans, snapshot or {})
    if not summary["events"] and not summary["counters"]["transitions"]:
        print(f"flink-ml-tpu-trace controller: no controller "
              f"telemetry in {trace_dir}", file=sys.stderr)
        return EXIT_INVALID
    problems = check_verdict(summary)
    with pipe_guard():
        if args.json:
            print(json.dumps({"trace_dir": trace_dir,
                              "summary": summary,
                              "healthy": not problems,
                              "problems": problems}, indent=2,
                             default=str))
        else:
            print(render_controller(summary))
            if problems:
                print()
                print("UNHEALTHY: " + "; ".join(problems))
    if args.check and problems:
        print(f"flink-ml-tpu-trace controller: {'; '.join(problems)}",
              file=sys.stderr)
        return EXIT_UNHEALTHY
    return EXIT_OK


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
