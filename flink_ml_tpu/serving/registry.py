"""Versioned model registry: atomic hot-swap from checkpointed model
data, with integrity + health vetting and rollback.

The reference's signature capability is unbounded iteration — models
that keep training while serving (OnlineLogisticRegression's
model-version broadcast). This module is the serving half of that
handoff, in the "Just-in-Time Aggregation" shape (arXiv:2208.09740):
the trainer publishes model snapshots asynchronously, the server folds
each one in with no global barrier — requests never stop.

- **publish** (:func:`publish_model`, trainer side): model arrays land
  as iteration/checkpoint.py checkpoints — v2 manifests with per-leaf
  sha256 digests, fsync-before-atomic-rename — under a watch directory,
  one ``ckpt-<version>`` per model version.
- **watch** (:meth:`ModelRegistry.poll`, or the background watcher
  thread): the newest unseen version is validated against its manifest
  (:func:`~flink_ml_tpu.iteration.checkpoint.load_validated` — a
  bit-flipped snapshot is quarantined ``*.corrupt`` and never loaded),
  its leaves checked finite, loaded into a candidate servable, and
  **probed**: one synthetic transform whose PR 5
  prediction-distribution gauges (``ml.serving *FiniteFraction``) must
  read 1.0 — a NaN-producing candidate is rejected before it ever sees
  a request.
- **swap**: on pass, the candidate (labeled ``<model>@v<N>`` via
  ``serving_name``, so spans/histograms/SLOs split by version) becomes
  :attr:`ModelRegistry.active` in one atomic assignment. The
  micro-batcher resolves ``active`` once per tick, so in-flight batches
  complete on the version they were dispatched with. On ANY failure the
  registry **rolls back** by construction — the serving version was
  never replaced — records ``swapRejected{model=,reason=}`` +
  a ``serving.swap.rejected`` event, and remembers the version so a bad
  candidate is not re-probed every poll
  (:class:`~flink_ml_tpu.resilience.policy.CandidateRejected` is
  terminal: the same snapshot re-validates to the same verdict).

See docs/serving.md for the hot-swap state machine.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, List, Optional

import numpy as np

from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.iteration.checkpoint import (
    CheckpointManager,
    CorruptCheckpoint,
    list_checkpoint_names,
    load_validated,
    quarantine_checkpoint,
)
from flink_ml_tpu.observability import tracing
from flink_ml_tpu.resilience.policy import CandidateRejected
from flink_ml_tpu.servable.api import serving_name

__all__ = ["publish_model", "ModelRegistry"]


def publish_model(watch_dir: str, leaves, version: int,
                  keep: int = 8, baseline=None) -> str:
    """Trainer-side publish: write model ``leaves`` (a list/pytree of
    arrays) as checkpoint version ``version`` under ``watch_dir`` —
    v2 manifest, fsynced, atomically renamed — and return the published
    path. The serving registry's watcher picks it up on its next poll.

    ``baseline`` (a :class:`~flink_ml_tpu.observability.drift
    .DriftBaseline`, typically the fitted model's ``drift_baseline``
    captured by the traced-fit seam) is serialized as
    ``drift-baseline.json`` beside the manifest inside the same atomic
    rename, so the watcher installs the *matching* training-time
    distribution summary with every hot-swap; publishing without one is
    fine — drift evaluation then reports ``source: missing``."""
    manager = CheckpointManager(watch_dir, keep=keep)
    extras = None
    if baseline is not None:
        from flink_ml_tpu.observability import drift

        extras = {os.path.splitext(drift.BASELINE_FILENAME)[0]:
                  baseline.to_json()}
    return manager.save(leaves, int(version), extras=extras)


class ModelRegistry:
    """Watches a publish directory and hot-swaps validated, healthy
    model versions into :attr:`active`.

    ``loader(leaves, version)`` builds a servable from validated host
    arrays; ``probe`` (optional, a zero-arg factory of a small request
    DataFrame) gates every candidate behind one real transform plus the
    prediction-distribution finite check. ``health_check`` (optional,
    ``servable -> bool``) adds a custom gate — return falsy or raise to
    reject. ``mesh`` (optional) is asserted on every candidate before
    its probe, so a mesh-sharded dispatcher's candidates are probed
    through the same sharded executable they will serve with
    (docs/serving.md "Mesh-sharded dispatch")."""

    def __init__(self, watch_dir: str,
                 loader: Callable[[List[np.ndarray], int], object],
                 model: str = "model",
                 probe: Optional[Callable[[], object]] = None,
                 health_check: Optional[Callable[[object], bool]] = None,
                 poll_interval_s: float = 1.0,
                 mesh=None):
        self.watch_dir = watch_dir
        self.model = model
        self._loader = loader
        self._probe = probe
        self._health_check = health_check
        #: dispatch mesh asserted on every candidate BEFORE its probe
        #: (docs/serving.md "Mesh-sharded dispatch"): the probe
        #: transform must route through the same sharded executable the
        #: dispatcher will use, or it would compile — and serve — the
        #: single-device path the warmup never warmed
        self._mesh = mesh
        self.poll_interval_s = float(poll_interval_s)
        self._lock = threading.Lock()
        self._active = None
        self._version: Optional[int] = None
        self._rejected: set = set()
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._group = metrics.group(ML_GROUP, "serving")

    # -- the serving side ----------------------------------------------------
    @property
    def active(self):
        """The serving servable (None before the first successful
        swap). One atomic read — safe from any thread."""
        return self._active

    @property
    def version(self) -> Optional[int]:
        return self._version

    # -- candidate discovery -------------------------------------------------
    def _published_versions(self) -> List[int]:
        return [int(name[len("ckpt-"):])
                for name in list_checkpoint_names(self.watch_dir)]

    def poll(self) -> bool:
        """One watcher step: consider published versions newer than the
        serving one, newest first; adopt the first that validates and
        passes health checks. Returns True when a swap happened. Never
        raises on a bad candidate — rejection is recorded, the serving
        version keeps serving (rollback by construction)."""
        current = self._version
        fresh = [v for v in self._published_versions()
                 if (current is None or v > current)
                 and v not in self._rejected]
        for version in reversed(fresh):
            try:
                self._adopt(version)
                return True
            except CandidateRejected as e:
                reason, detail = e.reason, str(e)
            except Exception as e:  # noqa: BLE001 — the never-raises
                # contract: ANY failure between load and swap (a loader
                # returning a __slots__ object that rejects the
                # serving_name assignment, a gauge scan tripping on
                # junk) is a rejected candidate, recorded and
                # remembered — never a crashed watcher or a re-probe
                # loop
                reason = "internal-error"
                detail = f"{type(e).__name__}: {e}"
            self._rejected.add(version)
            self._group.counter(
                "swapRejected",
                labels={"model": self.model, "reason": reason})
            tracing.tracer.event("serving.swap.rejected",
                                 model=self.model, version=version,
                                 reason=reason, detail=detail)
        return False

    def _adopt(self, version: int) -> None:
        ckpt_dir = os.path.join(self.watch_dir, f"ckpt-{version:08d}")
        try:
            leaves, epoch = load_validated(ckpt_dir)
        except CorruptCheckpoint as e:
            # rename-to-*.corrupt keeps the evidence AND stops the
            # watcher from revalidating the same torn snapshot forever
            quarantine_checkpoint(ckpt_dir, str(e))
            raise CandidateRejected(self.model, version, "corrupt",
                                    str(e)) from e
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            if (np.issubdtype(arr.dtype, np.floating)
                    and not np.isfinite(arr).all()):
                raise CandidateRejected(
                    self.model, version, "non-finite",
                    f"leaf_{i} has non-finite values")
        try:
            candidate = self._loader(leaves, epoch)
        except Exception as e:  # noqa: BLE001 — a loader crash is a
            # rejected candidate, never a crashed server
            raise CandidateRejected(self.model, version, "load-error",
                                    f"{type(e).__name__}: {e}") from e
        if self._mesh is not None and hasattr(candidate, "set_mesh"):
            candidate.set_mesh(self._mesh)
        candidate.serving_name = f"{self.model}@v{version}"
        # install the baseline BEFORE the probe: the probe's transform
        # runs through the _served seam, which creates the candidate's
        # live drift window — it must be seeded with the baseline's bin
        # edges at creation, not auto-range its own
        self._install_baseline(candidate.serving_name, ckpt_dir,
                               version)
        try:
            self._probe_candidate(candidate, version)
        except Exception:
            # a rejected candidate's versioned name never serves —
            # drop its drift state so it cannot linger as "missing"
            self._forget_baseline(candidate.serving_name)
            raise
        with self._lock:
            previous = self._version
            self._active = candidate
            self._version = version
        self._group.gauge("modelVersion", version,
                          labels={"model": self.model})
        self._group.counter("swaps", labels={"model": self.model})
        tracing.tracer.event("serving.swap", model=self.model,
                             version=version,
                             previous=previous if previous is not None
                             else "none")

    def _install_baseline(self, serving_name: str, ckpt_dir: str,
                          version: int) -> None:
        """Install the drift baseline published beside this version's
        manifest (observability/drift.py), keyed by the VERSIONED
        serving name — so requests still in flight on the previous
        version keep comparing against the previous baseline. Runs
        BEFORE the candidate probe (whose transform creates the live
        window that must seed from these bin edges); a missing or
        unreadable baseline records ``source: missing`` / a
        ``baselineMissing`` counter and NEVER blocks the swap."""
        try:
            from flink_ml_tpu.observability import drift
        except ImportError:  # pragma: no cover — drift rides the pkg
            return
        baseline = None
        try:
            baseline = drift.load_baseline_file(
                os.path.join(ckpt_dir, drift.BASELINE_FILENAME))
        except ValueError as e:
            tracing.tracer.event("serving.baseline.invalid",
                                 model=self.model, version=version,
                                 detail=str(e))
        if baseline is not None:
            # the registry's published version is the authoritative one
            # (the fit-side capture may carry the trainer's own counter)
            baseline.version = int(version)
        try:
            drift.install_baseline(serving_name, baseline)
        except Exception:  # noqa: BLE001 — telemetry must never undo
            # a committed swap
            pass
        if baseline is None:
            self._group.counter("baselineMissing",
                                labels={"model": self.model})

    def _forget_baseline(self, serving_name: str) -> None:
        try:
            from flink_ml_tpu.observability import drift

            drift.forget_servable(serving_name)
        except Exception:  # noqa: BLE001 — cleanup only; the rejection
            # (the real verdict) must propagate unchanged
            pass

    def _probe_candidate(self, candidate, version: int) -> None:
        if self._probe is not None:
            try:
                candidate.transform(self._probe())
            except Exception as e:  # noqa: BLE001 — see _adopt
                raise CandidateRejected(
                    self.model, version, "probe-error",
                    f"{type(e).__name__}: {e}") from e
            # the probe transform just wrote this candidate's
            # prediction-distribution gauges (observability/health.py,
            # labeled by its serving_name) — the ready-made
            # accept/reject signal: anything non-finite rejects
            snap = self._group.snapshot().get("gauges", {})
            label = f'servable="{serving_name(candidate)}"'
            for key, value in snap.items():
                if "FiniteFraction" in key and label in key \
                        and float(value) < 1.0:
                    raise CandidateRejected(
                        self.model, version, "probe-non-finite",
                        f"{key} = {value}")
        if self._health_check is not None:
            try:
                verdict = self._health_check(candidate)
            except Exception as e:  # noqa: BLE001 — see _adopt
                raise CandidateRejected(
                    self.model, version, "health-check",
                    f"{type(e).__name__}: {e}") from e
            if not verdict:
                raise CandidateRejected(self.model, version,
                                        "health-check")

    # -- background watcher --------------------------------------------------
    def start_watcher(self) -> "ModelRegistry":
        if self._watcher is not None:
            return self
        self._stop.clear()
        self._watcher = threading.Thread(
            target=self._watch, name="flink-ml-tpu-model-watcher",
            daemon=True)
        self._watcher.start()
        return self

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — the watcher must outlive
                # any single bad poll (e.g. a transient listdir error)
                tracing.tracer.event("serving.watcher.error",
                                     model=self.model)

    def stop(self) -> None:
        if self._watcher is None:
            return
        self._stop.set()
        self._watcher.join(timeout=10.0)
        self._watcher = None

    def __enter__(self) -> "ModelRegistry":
        return self.start_watcher()

    def __exit__(self, *exc) -> None:
        self.stop()
