"""Versioned model registry: atomic hot-swap from checkpointed model
data, with integrity + health vetting and rollback.

The reference's signature capability is unbounded iteration — models
that keep training while serving (OnlineLogisticRegression's
model-version broadcast). This module is the serving half of that
handoff, in the "Just-in-Time Aggregation" shape (arXiv:2208.09740):
the trainer publishes model snapshots asynchronously, the server folds
each one in with no global barrier — requests never stop.

- **publish** (:func:`publish_model`, trainer side): model arrays land
  as iteration/checkpoint.py checkpoints — v2 manifests with per-leaf
  sha256 digests, fsync-before-atomic-rename — under a watch directory,
  one ``ckpt-<version>`` per model version.
- **watch** (:meth:`ModelRegistry.poll`, or the background watcher
  thread): the newest unseen version is validated against its manifest
  (:func:`~flink_ml_tpu.iteration.checkpoint.load_validated` — a
  bit-flipped snapshot is quarantined ``*.corrupt`` and never loaded),
  its leaves checked finite, loaded into a candidate servable, and
  **probed**: one synthetic transform whose PR 5
  prediction-distribution gauges (``ml.serving *FiniteFraction``) must
  read 1.0 — a NaN-producing candidate is rejected before it ever sees
  a request.
- **swap**: on pass, the candidate (labeled ``<model>@v<N>`` via
  ``serving_name``, so spans/histograms/SLOs split by version) becomes
  :attr:`ModelRegistry.active` in one atomic assignment. The
  micro-batcher resolves the provider once per tick, so in-flight
  batches complete on the version they were dispatched with. On ANY
  failure the registry **rolls back** by construction — the serving
  version was never replaced — records ``swapRejected{model=,reason=}``
  + a ``serving.swap.rejected`` event, and remembers the version so a
  bad candidate is not re-probed every poll
  (:class:`~flink_ml_tpu.resilience.policy.CandidateRejected` is
  terminal: the same snapshot re-validates to the same verdict).
- **canary** (:meth:`ModelRegistry.set_canary` /
  :meth:`~ModelRegistry.resolve`, the ops controller's rollout seam,
  serving/controller.py): a probed candidate can ride beside ``active``
  at a traffic fraction — :meth:`resolve` (what the micro-batcher calls
  each tick) returns the canary for that share of ticks — and is either
  **promoted** (:meth:`~ModelRegistry.promote_canary`, the committed
  swap) or dropped.
- **rollback** (:meth:`ModelRegistry.rollback`): first-class demotion —
  re-activates the prior adopted version from the in-process history
  WITHOUT re-probe (it already served healthily; re-validating it could
  only lose time while a bad version keeps serving), remembers the
  demoted version so the watcher never re-adopts it, records
  ``rollbacks{model=,reason=}`` + a ``serving.rollback`` event, and
  forgets the demoted version's live drift state
  (:func:`~flink_ml_tpu.observability.drift.forget_servable`) so a
  later re-canary of the same model seeds fresh windows instead of
  inheriting the stale violated ones.

The watcher thread is supervised: an exception escaping the poll loop
restarts it with exponential backoff (counted
``watcherRestarts{model=}``) instead of silently killing hot-swap for
the rest of the process.

See docs/serving.md for the hot-swap state machine and docs/ops.md for
the canary/rollback loop driving these seams.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from flink_ml_tpu.common.locks import (
    install_thread_excepthook,
    make_lock,
)
from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.iteration.checkpoint import (
    CheckpointManager,
    CorruptCheckpoint,
    list_checkpoint_names,
    load_validated,
    quarantine_checkpoint,
)
from flink_ml_tpu.observability import tracing
from flink_ml_tpu.resilience import faults
from flink_ml_tpu.resilience.policy import (
    CandidateRejected,
    RetryableFailure,
)
from flink_ml_tpu.servable.api import serving_name

__all__ = ["publish_model", "ModelRegistry"]

#: adopted (version, servable) pairs kept for :meth:`ModelRegistry
#: .rollback` — v(N-1) must be re-activatable without touching disk
HISTORY_KEEP = 4


def publish_model(watch_dir: str, leaves, version: int,
                  keep: int = 8, baseline=None,
                  quality_baseline=None) -> str:
    """Trainer-side publish: write model ``leaves`` (a list/pytree of
    arrays) as checkpoint version ``version`` under ``watch_dir`` —
    v2 manifest, fsynced, atomically renamed — and return the published
    path. The serving registry's watcher picks it up on its next poll.

    ``baseline`` (a :class:`~flink_ml_tpu.observability.drift
    .DriftBaseline`, typically the fitted model's ``drift_baseline``
    captured by the traced-fit seam) is serialized as
    ``drift-baseline.json`` beside the manifest inside the same atomic
    rename, so the watcher installs the *matching* training-time
    distribution summary with every hot-swap; publishing without one is
    fine — drift evaluation then reports ``source: missing``.

    ``quality_baseline`` (a :class:`~flink_ml_tpu.observability
    .evaluation.QualityBaseline`, the fitted model's
    ``quality_baseline`` captured at fit time from training-set scores
    vs labels) rides the same atomic rename as
    ``quality-baseline.json`` — the live-AUC reference the continuous
    evaluation plane judges this version against."""
    manager = CheckpointManager(watch_dir, keep=keep)
    extras = {}
    if baseline is not None:
        from flink_ml_tpu.observability import drift

        extras[os.path.splitext(drift.BASELINE_FILENAME)[0]] = \
            baseline.to_json()
    if quality_baseline is not None:
        from flink_ml_tpu.observability import evaluation

        extras[os.path.splitext(evaluation.BASELINE_FILENAME)[0]] = \
            quality_baseline.to_json()
    return manager.save(leaves, int(version), extras=extras or None)


class ModelRegistry:
    """Watches a publish directory and hot-swaps validated, healthy
    model versions into :attr:`active`.

    ``loader(leaves, version)`` builds a servable from validated host
    arrays; ``probe`` (optional, a zero-arg factory of a small request
    DataFrame) gates every candidate behind one real transform plus the
    prediction-distribution finite check. ``health_check`` (optional,
    ``servable -> bool``) adds a custom gate — return falsy or raise to
    reject. ``mesh`` (optional) is asserted on every candidate before
    its probe, so a mesh-sharded dispatcher's candidates are probed
    through the same sharded executable they will serve with
    (docs/serving.md "Mesh-sharded dispatch")."""

    def __init__(self, watch_dir: str,
                 loader: Callable[[List[np.ndarray], int], object],
                 model: str = "model",
                 probe: Optional[Callable[[], object]] = None,
                 health_check: Optional[Callable[[object], bool]] = None,
                 poll_interval_s: float = 1.0,
                 mesh=None):
        self.watch_dir = watch_dir
        self.model = model
        self._loader = loader
        self._probe = probe
        self._health_check = health_check
        #: dispatch mesh asserted on every candidate BEFORE its probe
        #: (docs/serving.md "Mesh-sharded dispatch"): the probe
        #: transform must route through the same sharded executable the
        #: dispatcher will use, or it would compile — and serve — the
        #: single-device path the warmup never warmed
        self._mesh = mesh
        self.poll_interval_s = float(poll_interval_s)
        self._lock = make_lock("serving.registry")
        self._active = None
        self._version: Optional[int] = None
        self._rejected: set = set()
        #: versions a rollout owner (the ops controller) has claimed:
        #: the watcher must not adopt them directly — they go through
        #: the staged canary path instead (docs/ops.md)
        self._held: set = set()
        #: adopted (version, servable) pairs, newest last — rollback's
        #: source of truth for "the prior version", capped HISTORY_KEEP
        self._history: List[Tuple[int, object]] = []
        #: (servable, version) riding beside active at _canary_fraction
        self._canary: Optional[Tuple[object, int]] = None
        self._canary_fraction = 0.0
        # seeded: a fixed seed makes the canary tick split reproducible
        # for tests; production cares only about the long-run fraction
        self._canary_rng = random.Random(0)
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._group = metrics.group(ML_GROUP, "serving")

    # -- the serving side ----------------------------------------------------
    @property
    def active(self):
        """The committed serving servable (None before the first
        successful swap). One atomic read — safe from any thread."""
        return self._active  # jaxlint: disable=unguarded-shared-state -- one atomic reference read; swaps replace the object under the lock

    @property
    def version(self) -> Optional[int]:
        return self._version  # jaxlint: disable=unguarded-shared-state -- one atomic int read; the serving path tolerates a stale version

    @property
    def canary_version(self) -> Optional[int]:
        canary = self._canary  # jaxlint: disable=unguarded-shared-state -- one atomic tuple read, unpacked from the local snapshot
        return canary[1] if canary is not None else None

    @property
    def canary_fraction(self) -> float:
        return self._canary_fraction if self._canary is not None else 0.0  # jaxlint: disable=unguarded-shared-state -- per-tick routing reads a snapshot; a stale fraction skews one tick

    def resolve(self):
        """The servable for ONE dispatch tick: the canary for
        ``canary_fraction`` of ticks, the committed ``active`` for the
        rest. THE provider seam the micro-batcher prefers over
        ``active`` — a staged rollout needs per-tick routing, and the
        batcher already resolves once per tick so in-flight batches
        complete on the version they were dispatched with."""
        canary = self._canary  # jaxlint: disable=unguarded-shared-state -- resolve snapshots the canary tuple once; ticks tolerate staleness
        if canary is not None:
            fraction = self._canary_fraction  # jaxlint: disable=unguarded-shared-state -- a stale fraction mis-routes at most the current tick
            if fraction >= 1.0 or (fraction > 0.0
                                   and self._canary_rng.random()
                                   < fraction):
                return canary[0]
        return self._active  # jaxlint: disable=unguarded-shared-state -- fallback is the same atomic read the active property makes

    # -- candidate discovery -------------------------------------------------
    def _published_versions(self) -> List[int]:
        return [int(name[len("ckpt-"):])
                for name in list_checkpoint_names(self.watch_dir)]

    def published_versions(self) -> List[int]:
        """Versions currently published under the watch dir — how the
        ops controller picks the next free version number."""
        return self._published_versions()

    def record_rejection(self, version: int, reason: str,
                         detail: str = "") -> None:
        """Remember ``version`` as rejected (the watcher never
        re-probes it) and record the ``swapRejected{model=,reason=}``
        counter + event — the one rejection bookkeeping path, shared by
        :meth:`poll` and callers driving :meth:`load_candidate`
        themselves (serving/controller.py)."""
        with self._lock:
            self._rejected.add(int(version))
        self._group.counter(
            "swapRejected",
            labels={"model": self.model, "reason": reason})
        tracing.tracer.event("serving.swap.rejected",
                             model=self.model, version=int(version),
                             reason=reason, detail=detail)

    def hold_version(self, version: int) -> None:
        """Claim ``version`` for a staged rollout: :meth:`poll` skips
        it, so a running watcher cannot adopt it directly while the
        ops controller canaries it. Released by :meth:`release_version`
        (and implicitly by rollback/drop, which condemn or free it)."""
        with self._lock:
            self._held.add(int(version))

    def release_version(self, version: int) -> None:
        with self._lock:
            self._held.discard(int(version))

    def poll(self) -> bool:
        """One watcher step: consider published versions newer than the
        serving one, newest first; adopt the first that validates and
        passes health checks. Returns True when a swap happened. Never
        raises on a bad candidate — rejection is recorded, the serving
        version keeps serving (rollback by construction). Versions
        held for a staged rollout (:meth:`hold_version`) or currently
        riding as the canary are skipped — adopting them here would
        bypass the ramp and bake gates."""
        # one consistent snapshot of the swap state; the dir scan and
        # the adopt work run lock-free on the copies
        with self._lock:
            current = self._version
            canary = self._canary
            rejected = set(self._rejected)
            held = set(self._held)
        canary_version = canary[1] if canary is not None else None
        fresh = [v for v in self._published_versions()
                 if (current is None or v > current)
                 and v not in rejected
                 and v not in held
                 and v != canary_version]
        for version in reversed(fresh):
            try:
                self._adopt(version)
                return True
            except CandidateRejected as e:
                reason, detail = e.reason, str(e)
            except RetryableFailure as e:
                # transient (an injected canary-probe/model-swap fault,
                # an I/O hiccup mid-load): the snapshot itself is not
                # condemned — do NOT remember it; the next poll sees the
                # same version as a fresh candidate and retries
                self._group.counter(
                    "swapRetried", labels={"model": self.model})
                tracing.tracer.event("serving.swap.retry",
                                     model=self.model, version=version,
                                     error=type(e).__name__,
                                     detail=str(e))
                return False
            except Exception as e:  # noqa: BLE001 — the never-raises
                # contract: ANY failure between load and swap (a loader
                # returning a __slots__ object that rejects the
                # serving_name assignment, a gauge scan tripping on
                # junk) is a rejected candidate, recorded and
                # remembered — never a crashed watcher or a re-probe
                # loop
                reason = "internal-error"
                detail = f"{type(e).__name__}: {e}"
            self.record_rejection(version, reason, detail)
        return False

    def _adopt(self, version: int) -> None:
        # the registry-adopt rung of the boot ladder (a no-op once the
        # process marked ready — steady-state adoptions are not boot)
        from flink_ml_tpu.observability import profiling

        with profiling.boot_phase("registry-adopt"):
            candidate = self.load_candidate(version)
            self._commit(candidate, version)

    def load_candidate(self, version: int):
        """Validate, load, baseline-install and probe published version
        ``version`` WITHOUT swapping it in — the canary entry point
        (serving/controller.py). Raises
        :class:`~flink_ml_tpu.resilience.policy.CandidateRejected`
        (terminal — the data is what it is) on a bad candidate, or a
        retryable failure (e.g. an injected ``canary-probe`` fault) the
        caller's policy may re-enter."""
        ckpt_dir = os.path.join(self.watch_dir, f"ckpt-{version:08d}")
        try:
            leaves, epoch = load_validated(ckpt_dir)
        except CorruptCheckpoint as e:
            # rename-to-*.corrupt keeps the evidence AND stops the
            # watcher from revalidating the same torn snapshot forever
            quarantine_checkpoint(ckpt_dir, str(e))
            raise CandidateRejected(self.model, version, "corrupt",
                                    str(e)) from e
        for i, leaf in enumerate(leaves):
            arr = np.asarray(leaf)
            if (np.issubdtype(arr.dtype, np.floating)
                    and not np.isfinite(arr).all()):
                raise CandidateRejected(
                    self.model, version, "non-finite",
                    f"leaf_{i} has non-finite values")
        try:
            candidate = self._loader(leaves, epoch)
        except Exception as e:  # noqa: BLE001 — a loader crash is a
            # rejected candidate, never a crashed server
            raise CandidateRejected(self.model, version, "load-error",
                                    f"{type(e).__name__}: {e}") from e
        if self._mesh is not None and hasattr(candidate, "set_mesh"):
            candidate.set_mesh(self._mesh)
        candidate.serving_name = f"{self.model}@v{version}"
        # install the baseline BEFORE the probe: the probe's transform
        # runs through the _served seam, which creates the candidate's
        # live drift window — it must be seeded with the baseline's bin
        # edges at creation, not auto-range its own
        self._install_baseline(candidate.serving_name, ckpt_dir,
                               version)
        try:
            self._probe_candidate(candidate, version)
        except CandidateRejected:
            # a rejected candidate's versioned name never serves —
            # drop its drift state so it cannot linger as "missing"
            self._forget_baseline(candidate.serving_name)
            raise
        except RetryableFailure:
            # transient: the baseline stays installed — the retry will
            # re-probe through the same seeded window
            raise
        except Exception:
            self._forget_baseline(candidate.serving_name)
            raise
        return candidate

    def _commit(self, candidate, version: int) -> None:
        """The committed swap: one atomic assignment, history recorded.
        The ``model-swap`` chaos site fires here — an injected fault is
        retryable (nothing was mutated yet; the caller or the next poll
        re-enters)."""
        faults.inject("model-swap", model=self.model, version=version)
        with self._lock:
            previous = self._version
            self._active = candidate
            self._version = version
            if self._canary is not None and self._canary[1] == version:
                # promoting the riding canary: it stops being a canary
                self._canary = None
                self._canary_fraction = 0.0
            if self._history and self._history[-1][0] == version:
                # re-commit of the newest version (a retried swap):
                # replace, never duplicate — rollback() pops exactly
                # one entry per demotion
                self._history[-1] = (version, candidate)
            else:
                self._history.append((version, candidate))
            del self._history[:-HISTORY_KEEP]
        self._group.gauge("modelVersion", version,
                          labels={"model": self.model})
        self._group.counter("swaps", labels={"model": self.model})
        tracing.tracer.event("serving.swap", model=self.model,
                             version=version,
                             previous=previous if previous is not None
                             else "none")

    # -- canary rollout (the ops controller's seams) --------------------------
    def set_canary(self, candidate, version: int,
                   fraction: float = 0.0) -> None:
        """Install a probed candidate as the canary at ``fraction`` of
        dispatch ticks (:meth:`resolve`); ``active`` keeps serving the
        rest. Promote with :meth:`promote_canary`, demote with
        :meth:`rollback` (or :meth:`drop_canary` without condemning the
        version)."""
        if not 0.0 <= float(fraction) <= 1.0:
            raise ValueError("canary fraction must be in [0, 1]")
        with self._lock:
            self._canary = (candidate, int(version))
            self._canary_fraction = float(fraction)
        self._group.gauge("canaryVersion", int(version),
                          labels={"model": self.model})
        self._group.gauge("canaryFraction", float(fraction),
                          labels={"model": self.model})
        tracing.tracer.event("serving.canary", model=self.model,
                             version=int(version),
                             fraction=float(fraction))

    def set_canary_fraction(self, fraction: float) -> None:
        """Ramp the live canary's traffic share (a stage boundary)."""
        if not 0.0 <= float(fraction) <= 1.0:
            raise ValueError("canary fraction must be in [0, 1]")
        with self._lock:
            if self._canary is None:
                raise ValueError("no canary to ramp")
            self._canary_fraction = float(fraction)
            version = self._canary[1]
        self._group.gauge("canaryFraction", float(fraction),
                          labels={"model": self.model})
        tracing.tracer.event("serving.canary.ramp", model=self.model,
                             version=version,
                             fraction=float(fraction))

    def promote_canary(self) -> int:
        """Commit the canary as the serving version (THE swap of a
        staged rollout); returns the promoted version. Retryable on an
        injected ``model-swap`` fault — nothing is mutated until the
        commit."""
        canary = self._canary  # jaxlint: disable=unguarded-shared-state -- snapshot-then-commit: _commit takes the lock before mutating
        if canary is None:
            raise ValueError("no canary to promote")
        candidate, version = canary
        self._commit(candidate, version)
        self._group.gauge("canaryFraction", 0.0,
                          labels={"model": self.model})
        self._group.gauge("canaryVersion", 0,
                          labels={"model": self.model})
        return version

    def drop_canary(self, reason: str = "dropped") -> Optional[int]:
        """Remove the canary WITHOUT condemning its version (e.g. the
        controller shutting down mid-ramp); returns the dropped version
        (None when no canary was live). The version stays adoptable —
        use :meth:`rollback` to also remember it as bad."""
        with self._lock:
            canary, self._canary = self._canary, None
            self._canary_fraction = 0.0
            if canary is not None:
                # a dropped canary's version is free again — including
                # for the watcher, which the hold/canary guards kept
                # away from it
                self._held.discard(canary[1])
        if canary is None:
            return None
        self._group.gauge("canaryFraction", 0.0,
                          labels={"model": self.model})
        self._group.gauge("canaryVersion", 0,  # 0 = none (v start at 1)
                          labels={"model": self.model})
        tracing.tracer.event("serving.canary.drop", model=self.model,
                             version=canary[1], reason=reason)
        return canary[1]

    def rollback(self, reason: str = "regression") -> Optional[int]:
        """First-class rollback: demote the newest adopted (or canary)
        version and re-activate the prior one from the in-process
        history WITHOUT re-probe — it already served healthily, and a
        re-probe would only keep a bad version serving longer. The
        demoted version is remembered (never re-adopted by the
        watcher), its live drift state is forgotten
        (:func:`~flink_ml_tpu.observability.drift.forget_servable`) so
        a later re-canary seeds fresh windows, and the demotion is
        recorded ``rollbacks{model=,reason=}`` + a ``serving.rollback``
        event. Returns the version now serving.

        Raises ValueError (terminal) when there is no prior version to
        re-activate; retryable on an injected ``model-rollback`` fault
        (nothing is mutated before the injection point)."""
        faults.inject("model-rollback", model=self.model, reason=reason)
        with self._lock:
            if self._canary is not None:
                # mid-ramp demotion: active was never replaced — the
                # prior version IS the serving one; drop + condemn
                bad_version = self._canary[1]
                self._canary = None
                self._canary_fraction = 0.0
                restored = self._version
            else:
                if len(self._history) < 2:
                    raise ValueError(
                        f"no prior {self.model} version to roll back "
                        f"to (history: "
                        f"{[v for v, _ in self._history]})")
                bad_version = self._history[-1][0]
                self._history.pop()
                restored, self._active = self._history[-1]
                self._version = restored
            self._rejected.add(bad_version)
            self._held.discard(bad_version)
        self._group.counter(
            "rollbacks", labels={"model": self.model, "reason": reason})
        if restored is not None:
            self._group.gauge("modelVersion", restored,
                              labels={"model": self.model})
        self._group.gauge("canaryFraction", 0.0,
                          labels={"model": self.model})
        self._group.gauge("canaryVersion", 0,
                          labels={"model": self.model})
        tracing.tracer.event("serving.rollback", model=self.model,
                             demoted=bad_version,
                             restored=(restored if restored is not None
                                       else "none"),
                             reason=reason)
        try:
            # a rollback IS an incident: the evidence that condemned
            # the demoted version is in the span ring / windowed
            # metrics RIGHT NOW and rotates away — freeze it
            # (observability/flightrecorder.py; debounced, capped,
            # no-op without an armed trace dir)
            from flink_ml_tpu.observability import flightrecorder

            flightrecorder.record_incident(
                "rollback", model=self.model, demoted=bad_version,
                restored=restored, reason=reason)
        except Exception:  # noqa: BLE001 — recording must never undo
            # the rollback that just protected serving
            pass
        # a demoted version's windows hold exactly the violated samples
        # that condemned it — a later re-canary of the same model must
        # seed fresh ones, not inherit the stale verdict
        self._forget_baseline(f"{self.model}@v{bad_version}")
        return restored

    def _install_baseline(self, serving_name: str, ckpt_dir: str,
                          version: int) -> None:
        """Install the drift baseline published beside this version's
        manifest (observability/drift.py), keyed by the VERSIONED
        serving name — so requests still in flight on the previous
        version keep comparing against the previous baseline. Runs
        BEFORE the candidate probe (whose transform creates the live
        window that must seed from these bin edges); a missing or
        unreadable baseline records ``source: missing`` / a
        ``baselineMissing`` counter and NEVER blocks the swap."""
        try:
            from flink_ml_tpu.observability import drift
        except ImportError:  # pragma: no cover — drift rides the pkg
            return
        baseline = None
        try:
            baseline = drift.load_baseline_file(
                os.path.join(ckpt_dir, drift.BASELINE_FILENAME))
        except ValueError as e:
            tracing.tracer.event("serving.baseline.invalid",
                                 model=self.model, version=version,
                                 detail=str(e))
        if baseline is not None:
            # the registry's published version is the authoritative one
            # (the fit-side capture may carry the trainer's own counter)
            baseline.version = int(version)
        try:
            drift.install_baseline(serving_name, baseline)
        except Exception:  # noqa: BLE001 — telemetry must never undo
            # a committed swap
            pass
        if baseline is None:
            self._group.counter("baselineMissing",
                                labels={"model": self.model})
        self._install_quality_baseline(serving_name, ckpt_dir, version)

    def _install_quality_baseline(self, serving_name: str,
                                  ckpt_dir: str, version: int) -> None:
        """Same contract as :meth:`_install_baseline`, for the quality
        baseline (``quality-baseline.json``, observability/
        evaluation.py) — the training-set AUC reference the canary
        verdict's quality stage compares live AUC against. Missing is
        fine (evaluation reports ``source: missing``); never blocks."""
        try:
            from flink_ml_tpu.observability import evaluation
        except ImportError:  # pragma: no cover — rides the pkg
            return
        baseline = None
        try:
            baseline = evaluation.load_baseline_file(
                os.path.join(ckpt_dir, evaluation.BASELINE_FILENAME))
        except ValueError as e:
            tracing.tracer.event("serving.quality_baseline.invalid",
                                 model=self.model, version=version,
                                 detail=str(e))
        if baseline is not None:
            baseline.version = int(version)
        try:
            evaluation.install_baseline(serving_name, baseline)
        except Exception:  # noqa: BLE001 — telemetry must never undo
            # a committed swap
            pass
        if baseline is None:
            self._group.counter("qualityBaselineMissing",
                                labels={"model": self.model})

    def _forget_baseline(self, serving_name: str) -> None:
        try:
            from flink_ml_tpu.observability import drift

            drift.forget_servable(serving_name)
        except Exception:  # noqa: BLE001 — cleanup only; the rejection
            # (the real verdict) must propagate unchanged
            pass
        try:
            from flink_ml_tpu.observability import evaluation

            evaluation.forget_servable(serving_name)
        except Exception:  # noqa: BLE001 — see above
            pass

    def _probe_candidate(self, candidate, version: int) -> None:
        # the chaos site fires OUTSIDE the rejection-conversion blocks:
        # an injected probe fault is transient infrastructure
        # (retryable), not a verdict on the candidate's data
        faults.inject("canary-probe", model=self.model, version=version)
        if self._probe is not None:
            try:
                candidate.transform(self._probe())
            except Exception as e:  # noqa: BLE001 — see _adopt
                raise CandidateRejected(
                    self.model, version, "probe-error",
                    f"{type(e).__name__}: {e}") from e
            # the probe transform just wrote this candidate's
            # prediction-distribution gauges (observability/health.py,
            # labeled by its serving_name) — the ready-made
            # accept/reject signal: anything non-finite rejects
            snap = self._group.snapshot().get("gauges", {})
            label = f'servable="{serving_name(candidate)}"'
            for key, value in snap.items():
                if "FiniteFraction" in key and label in key \
                        and float(value) < 1.0:
                    raise CandidateRejected(
                        self.model, version, "probe-non-finite",
                        f"{key} = {value}")
        if self._health_check is not None:
            try:
                verdict = self._health_check(candidate)
            except Exception as e:  # noqa: BLE001 — see _adopt
                raise CandidateRejected(
                    self.model, version, "health-check",
                    f"{type(e).__name__}: {e}") from e
            if not verdict:
                raise CandidateRejected(self.model, version,
                                        "health-check")

    # -- background watcher --------------------------------------------------
    def start_watcher(self) -> "ModelRegistry":
        if self._watcher is not None:
            return self
        # a crashing watcher must surface in telemetry, not die mute
        install_thread_excepthook()
        self._stop.clear()
        self._watcher = threading.Thread(
            target=self._watch_supervised,
            name="flink-ml-tpu-model-watcher", daemon=True)
        self._watcher.start()
        return self

    def _watch_supervised(self) -> None:
        """The watcher thread's real target: re-enter the poll loop
        with exponential backoff when an exception escapes it. Without
        this, one transient failure (a listdir ENOENT while the publish
        dir is being recreated, an event sink hiccup) would kill
        hot-swap silently for the rest of the process — the server keeps
        serving, new versions just never arrive."""
        restarts = 0
        while not self._stop.is_set():
            entered = time.monotonic()
            try:
                self._watch()
                return  # _stop was set: clean shutdown
            except Exception as e:  # noqa: BLE001 — ANY escape restarts
                if time.monotonic() - entered >= 60.0:
                    # a healthy stretch forgives the burst: unrelated
                    # one-off blips days apart must not escalate the
                    # backoff to the 30s cap for the process lifetime
                    restarts = 0
                restarts += 1
                self._group.counter("watcherRestarts",
                                    labels={"model": self.model})
                tracing.tracer.event("serving.watcher.restart",
                                     model=self.model,
                                     restarts=restarts,
                                     error=type(e).__name__,
                                     detail=str(e))
                # backoff from the poll cadence, capped at 30s — the
                # RetryPolicy curve without importing a fit-scoped
                # budget (the watcher must retry forever)
                delay = min(
                    max(self.poll_interval_s, 0.05)
                    * min(2.0 ** (restarts - 1), 64.0), 30.0)
                if self._stop.wait(delay):
                    return

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self.poll()

    def stop(self) -> None:
        if self._watcher is None:
            return
        self._stop.set()
        self._watcher.join(timeout=10.0)
        self._watcher = None

    def __enter__(self) -> "ModelRegistry":
        return self.start_watcher()

    def __exit__(self, *exc) -> None:
        self.stop()
