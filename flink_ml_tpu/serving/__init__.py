"""Production serving runtime over the engine-free servables.

The servable tier (flink_ml_tpu/servable/) answers ONE caller's
``transform``; this package turns it into a server (docs/serving.md):

- :mod:`batcher` — async micro-batching: admission-controlled queueing
  with deadlines, padding/bucketing to a fixed batch-shape table (so
  steady-state serving never recompiles), one device dispatch per tick
  — pipelined (a pad stage overlapping a device stage) and, given a
  mesh, sharded over its devices per tick;
- :mod:`warmup` — AOT-compile every bucket shape (x the dispatch mesh)
  at start and gate ``/healthz`` readiness on completion;
- :mod:`registry` — versioned model hot-swap from checkpointed model
  data: manifest-validated, health-probed, atomic, rolled back on any
  failure — the online-learning (FTRL) → serving handoff — plus canary
  fraction routing and first-class rollback to v(N-1);
- :mod:`controller` — the self-healing ops loop (docs/ops.md):
  drift/SLO violation → warm-start retrain → publish with a fresh
  baseline → canary → staged ramp → swap, with automatic rollback when
  the canary's error/drift/latency gauges regress;
- :mod:`loadgen` — closed/open-loop load generation with exact latency
  percentiles, the one request-driving path for benchmarks, smokes and
  tests.

Ref parity: the reference stops at the synchronous servable interface
(TransformerServable.transform); the runtime around it — Flink's job
graph there — is this package here.
"""

from flink_ml_tpu.serving.batcher import (  # noqa: F401
    BUCKETS_ENV,
    DEADLINE_ENV,
    DEFAULT_BUCKET_ROWS,
    PIPELINE_ENV,
    QUEUE_ENV,
    WINDOW_ENV,
    BatcherConfig,
    MicroBatcher,
)
from flink_ml_tpu.serving.controller import (  # noqa: F401
    ControllerConfig,
    OpsController,
)
from flink_ml_tpu.serving.loadgen import (  # noqa: F401
    LoadGenConfig,
    percentiles,
    run_loadgen,
)
from flink_ml_tpu.serving.registry import (  # noqa: F401
    ModelRegistry,
    publish_model,
)
from flink_ml_tpu.serving.warmup import (  # noqa: F401
    WARMUP_GATE,
    compile_count,
    warm,
)

__all__ = [
    "BUCKETS_ENV",
    "DEADLINE_ENV",
    "DEFAULT_BUCKET_ROWS",
    "PIPELINE_ENV",
    "QUEUE_ENV",
    "WINDOW_ENV",
    "BatcherConfig",
    "MicroBatcher",
    "ControllerConfig",
    "OpsController",
    "LoadGenConfig",
    "percentiles",
    "run_loadgen",
    "ModelRegistry",
    "publish_model",
    "WARMUP_GATE",
    "compile_count",
    "warm",
]
