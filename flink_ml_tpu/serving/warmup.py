"""AOT warmup: pre-compile every serving bucket shape before traffic.

The micro-batcher (serving/batcher.py) guarantees steady-state serving
presents XLA with a closed set of batch shapes; this module pays the
compile bill for that whole set at server start, so the FIRST request
into each bucket is already a compile-cache hit instead of a
multi-hundred-ms stall. Each bucket warms through the servable's own
jitted predict path — ``aot_warm(rows)`` when the servable exposes one
(servable/lr.py routes it through
:func:`~flink_ml_tpu.observability.compilestats.instrumented_jit`, so
every warm compile is counted ``ml.compile compiles{fn=...}`` and the
post-warmup steady count is assertable), else one synthetic
``transform`` per bucket via the caller's ``frame_factory``.

Readiness: :func:`warm` registers the ``serving-warmup`` gate with the
live endpoint (observability/server.py) before compiling and releases
it after — ``/healthz`` answers 503 with the gate's reason until every
bucket is warm, the readiness/liveness split a load balancer needs to
keep traffic off a cold compile cache. See docs/serving.md.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import profiling, tracing
from flink_ml_tpu.observability.compilestats import compile_totals_split

__all__ = ["WARMUP_GATE", "compile_count", "warm"]

#: the readiness gate name ``/healthz`` reports while warming
WARMUP_GATE = "serving-warmup"


def compile_count() -> int:
    """Total per-function compiles recorded so far (the
    ``ml.compile compileMs{fn=...}`` series) — the before/after probe
    for the steady-state zero-compile assertion: read once after
    :func:`warm`, again after a load run, and the delta is the number
    of compiles real traffic paid."""
    return int(compile_totals_split()["perfn"]["count"])


def warm(target,
         frame_factory: Optional[Callable[[int], "object"]] = None,
         buckets: Optional[Sequence[int]] = None,
         gate: bool = True, mesh=None) -> dict:
    """Warm every bucket shape; returns a report dict.

    ``target`` is a :class:`~flink_ml_tpu.serving.batcher.MicroBatcher`
    (buckets, servable AND dispatch mesh are taken from it) or a
    servable (pass ``buckets`` — and ``mesh`` for sharded dispatch —
    explicitly). Per bucket the servable's ``aot_warm`` is preferred;
    ``frame_factory(rows)`` (a synthetic request frame of that many
    rows) is the generic fallback — pure-host servables warm trivially
    through it.

    With a mesh, the warm matrix is every bucket x THIS mesh shape:
    the mesh is asserted on the servable first (``set_mesh``), so each
    ``aot_warm(rows)`` compiles exactly the executable the dispatcher
    will route that bucket to — the row-sharded twin for buckets the
    shard count divides, the single-device kernel for the rest — and
    steady state still compiles zero times (the PR 8 probe,
    :func:`compile_count`, keeps gating it).

    With ``gate`` (default) the ``serving-warmup`` readiness gate is
    held closed while compiling and released on success; a warmup
    failure leaves the gate closed with the failure as its reason and
    re-raises — a server that could not warm must not report ready.
    """
    from flink_ml_tpu.observability import server
    from flink_ml_tpu.serving.batcher import MicroBatcher

    if isinstance(target, MicroBatcher):
        servable = target._provider()
        if buckets is None:
            buckets = target.config.buckets
        if mesh is None:
            mesh = target._mesh
    else:
        servable = target
    if servable is None:
        raise ValueError("cannot warm: no active servable "
                         "(publish a model to the registry first)")
    if mesh is not None and hasattr(servable, "set_mesh"):
        servable.set_mesh(mesh)
    bucket_list = [int(b) for b in (buckets or (1,))]
    if gate:
        server.set_gate(WARMUP_GATE, False,
                        f"warming {len(bucket_list)} bucket shape(s)")
    n_devices = int(mesh.devices.size) if mesh is not None else 1
    # the DATA-shard count decides which buckets route sharded (the
    # servable's own rule) — on a (data, model) mesh the raw device
    # count would mispredict the matrix
    n_shards = 1
    if mesh is not None:
        from flink_ml_tpu.parallel.mesh import data_shard_count

        n_shards = data_shard_count(mesh)
    report = {"buckets": {}, "total_ms": 0.0, "compiles": 0,
              "mesh_devices": n_devices,
              "sharded_buckets": [b for b in bucket_list
                                  if n_shards > 1
                                  and b % n_shards == 0]}
    before = compile_count()
    t_start = time.perf_counter()
    try:
        # the warmup-compile rung of the boot ladder (ml.boot
        # phaseMs{phase="warmup-compile"}, observability/profiling.py)
        with profiling.boot_phase("warmup-compile"):
            for rows in bucket_list:
                t0 = time.perf_counter()
                if hasattr(servable, "aot_warm"):
                    servable.aot_warm(rows)
                elif frame_factory is not None:
                    servable.transform(frame_factory(rows))
                else:
                    raise ValueError(
                        f"servable {type(servable).__name__} has no "
                        f"aot_warm and no frame_factory was given")
                report["buckets"][rows] = round(
                    (time.perf_counter() - t0) * 1000.0, 3)
    except Exception as e:
        if gate:
            server.set_gate(WARMUP_GATE, False,
                            f"warmup failed: {type(e).__name__}: {e}")
        raise
    report["total_ms"] = round((time.perf_counter() - t_start) * 1000.0,
                               3)
    report["compiles"] = compile_count() - before
    grp = metrics.group(ML_GROUP, "serving")
    grp.gauge("warmupMs", report["total_ms"])
    grp.gauge("warmupCompiles", report["compiles"])
    tracing.tracer.event("serving.warmup",
                         buckets=",".join(str(b) for b in bucket_list),
                         ms=report["total_ms"],
                         compiles=report["compiles"],
                         mesh_devices=n_devices)
    if gate:
        # gate-open closes the boot ladder: the process is ready for
        # traffic — latch bootToReadyMs for the fleet beacon
        with profiling.boot_phase("gate-open"):
            server.set_gate(WARMUP_GATE, True)
        profiling.mark_ready()
    return report
