"""Fleet telemetry plane: live cross-process aggregation + membership.

Every process in a runtime (training worker, serving replica,
controller) periodically writes an atomic **beacon** —
``fleet-p<k>-<pid>.json`` — into a shared fleet directory: a liveness
stamp, its role, windowed histogram/counter snapshot slices in the
mergeable bucket format that :func:`check_histogram_snapshot` /
``MetricsRegistry.merge`` already validate, key load gauges
(queueDepth, inFlight, model version / canary, participation) and the
most recent ``elastic.*`` / ``ml.controller`` trace events.  Because
the carried slices are plain cumulative-bucket snapshots, fleet-level
aggregation is bin-exact by construction: summing member counts arrays
gives the same histogram a single process would have recorded — the
same fold-exactly discipline the DrJAX-style reducers apply on device
(arXiv:2403.07128), host-side, with JiT-aggregation-style staleness
bookkeeping for members that stop reporting (arXiv:2208.09740).

:class:`FleetView` (driver- or CLI-side) merges live beacons into
fleet-level windowed quantiles ("fleet p99 over the last 60 s"), a
membership table with staleness classification (alive / stale / dead
by beacon age vs the announced interval) and per-replica load rows.
``observability/slo.py`` evaluates ``scope: fleet`` objectives through
it, and the elastic watchdog's ``beat()`` / ``stale_processes()``
(parallel/elastic.py) are thin views over the same beacon stamps — ONE
liveness mechanism, so the watchdog and ``mltrace fleet`` can never
disagree about who is dead.

CLI: ``flink-ml-tpu-trace fleet <dir> [--json|--check|--watch]``
(exit 4 on a dead member or a violated fleet-scope SLO under
``--check``, 2 without fleet telemetry).  Live route: ``/fleet`` on
the telemetry endpoint (observability/server.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from flink_ml_tpu.common import locks
from flink_ml_tpu.common.metrics import (
    WindowedHistogram,
    check_histogram_snapshot,
    histogram_quantile,
    metrics,
)

#: shared fleet directory (writer side); falls back to the elastic
#: heartbeat dir, then to ``<trace_dir>/fleet`` when tracing is armed
FLEET_DIR_ENV = "FLINK_ML_TPU_FLEET_DIR"
#: seconds between beacon writes (default 2.0)
BEACON_S_ENV = "FLINK_ML_TPU_FLEET_BEACON_S"
#: beacon age beyond which a member is *stale*; *dead* past twice this
#: (default: 2x the beacon interval)
STALE_S_ENV = "FLINK_ML_TPU_FLEET_STALE_S"

BEACON_GLOB = "fleet-*.json"
BEACON_SCHEMA = 1
DEFAULT_BEACON_S = 2.0
#: window slices every beacon carries, seconds (smallest >= the asked
#: window is picked at read time)
FLEET_WINDOWS = (60.0, 300.0)

EXIT_OK = 0
EXIT_INVALID = 2
EXIT_VIOLATION = 4

#: trace-event names a beacon carries (membership/ops context)
_EVENT_NAMES = ("elastic.", "ml.controller")
_EVENT_LIMIT = 20

__all__ = [
    "FLEET_DIR_ENV", "BEACON_S_ENV", "STALE_S_ENV", "BEACON_GLOB",
    "BEACON_SCHEMA", "FLEET_WINDOWS", "EXIT_OK", "EXIT_INVALID",
    "EXIT_VIOLATION", "beacon_interval_s", "stale_after_s", "fleet_dir",
    "find_fleet_dir", "write_beacon", "start_beacon", "stop_beacon",
    "read_beacons", "member_key", "FleetView", "fold_snapshots",
    "stale_member_indices", "provenance", "main",
]


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        return default
    return val if val > 0.0 else default


def beacon_interval_s() -> float:
    """Seconds between beacon writes (``FLINK_ML_TPU_FLEET_BEACON_S``,
    default 2.0; non-positive or junk values fall back)."""
    return _env_float(BEACON_S_ENV, DEFAULT_BEACON_S)


def stale_after_s() -> float:
    """Beacon age past which a member classifies *stale*
    (``FLINK_ML_TPU_FLEET_STALE_S``, default 2x the beacon interval).
    *Dead* starts at twice this again — a member gets one full missed
    interval of grace before 'stale' and a second before 'dead'."""
    return _env_float(STALE_S_ENV, 2.0 * beacon_interval_s())


def fleet_dir() -> Optional[str]:
    """The directory this process's beacons go to, or None (disarmed):
    ``FLINK_ML_TPU_FLEET_DIR``, else the elastic heartbeat dir (one
    liveness plane — parallel/elastic.py), else ``<trace_dir>/fleet``
    when tracing is armed."""
    explicit = os.environ.get(FLEET_DIR_ENV)
    if explicit:
        return explicit
    try:
        from flink_ml_tpu.parallel.elastic import HEARTBEAT_DIR_ENV

        hb = os.environ.get(HEARTBEAT_DIR_ENV)
    except Exception:
        hb = None
    if hb:
        return hb
    try:
        from flink_ml_tpu.observability.tracing import tracer

        trace_dir = tracer.trace_dir
    except Exception:
        trace_dir = None
    if trace_dir:
        return os.path.join(trace_dir, "fleet")
    return None


def find_fleet_dir(path: str) -> Optional[str]:
    """Reader-side resolution: ``path`` itself if it holds beacons,
    else its ``fleet/`` subdir (how a trace dir nests them), else
    None."""
    for cand in (path, os.path.join(path, "fleet")):
        if glob.glob(os.path.join(cand, BEACON_GLOB)):
            return cand
    return None


# -- beacon writing ----------------------------------------------------------

_seq_lock = locks.make_lock("observability.fleet")
_seq = 0
# singleton periodic writer: token -> role, in registration order
_beacon_tokens: Dict[int, str] = {}
_beacon_thread: Optional[threading.Thread] = None
_beacon_stop: Optional[threading.Event] = None
_beacon_dir: Optional[str] = None
_next_token = 1


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def _windows_payload(registry) -> dict:
    """Per-group windowed slices: for every :class:`WindowedHistogram`
    a cumulative-bucket snapshot per fleet window, for every windowed
    counter its per-window delta.  Keys are stringified whole seconds
    ("60", "300") so JSON round-trips exactly."""
    out: dict = {}
    for gname, group in registry.group_items():
        hists: dict = {}
        for key, hist in group.histogram_items():
            if not isinstance(hist, WindowedHistogram):
                continue
            per_window = {}
            for window_s in FLEET_WINDOWS:
                snap = hist.window_snapshot(window_s)
                per_window[str(int(window_s))] = snap
            hists[key] = per_window
        counters: dict = {}
        for key, wc in group.windowed_counter_items():
            counters[key] = {str(int(w)): int(wc.window_delta(w))
                             for w in FLEET_WINDOWS}
        if hists or counters:
            entry: dict = {}
            if hists:
                entry["histograms"] = hists
            if counters:
                entry["counters"] = counters
            out[gname] = entry
    return out


def _gauges_payload(registry) -> dict:
    out: dict = {}
    for gname, group in registry.group_items():
        if not gname.startswith("ml."):
            continue
        snap = group.snapshot()
        if snap.get("gauges"):
            out[gname] = dict(snap["gauges"])
    return out


def _load_payload() -> dict:
    """Point-in-time load row: serving status (when a batcher runs
    here) + elastic participation.  Every probe is best-effort — a
    beacon must never sink the workload it describes."""
    load: dict = {}
    try:
        from flink_ml_tpu.observability.server import get_serving_status

        provider = get_serving_status()
        if provider is not None:
            st = provider() or {}
            queue = st.get("queue") or {}
            load["servable"] = st.get("servable")
            load["queueDepth"] = queue.get("rows")
            load["inFlight"] = st.get("pipeline_depth")
            load["modelVersion"] = st.get("model_version")
            load["canary"] = st.get("canary")
    except Exception:
        pass
    try:
        from flink_ml_tpu.parallel import elastic

        prov = elastic.provenance()
        load["participation"] = prov.get("participationMin")
        load["elasticEvents"] = prov.get("elasticEvents")
    except Exception:
        pass
    try:
        from flink_ml_tpu.observability import profiling

        ready_ms = profiling.boot_to_ready_ms()
        if ready_ms is not None:
            load["bootToReadyMs"] = round(ready_ms, 3)
    except Exception:
        pass
    try:
        # continuous-evaluation quality (observability/evaluation.py):
        # the worst fresh live AUC + feedback coverage, so a half-fleet
        # quality collapse is visible from one `mltrace fleet` call
        from flink_ml_tpu.observability import evaluation

        prov = evaluation.provenance()
        if prov.get("aucLive") is not None:
            load["aucLive"] = prov["aucLive"]
        if prov.get("feedbackCoverage") is not None:
            load["feedbackCoverage"] = prov["feedbackCoverage"]
        if prov.get("labelLagP99Ms") is not None:
            load["labelLagP99Ms"] = prov["labelLagP99Ms"]
    except Exception:
        pass
    return load


def _events_payload() -> list:
    """The last ``elastic.*`` / ``ml.controller`` events from the
    tracer's recent-span ring, oldest first."""
    try:
        from flink_ml_tpu.observability.tracing import tracer

        records = list(tracer.recent)
    except Exception:
        return []
    picked = []
    for record in records:
        for ev in record.get("events", ()):
            name = ev.get("name", "")
            if name.startswith(_EVENT_NAMES[0]) or name == _EVENT_NAMES[1]:
                picked.append({"name": name, "ts_us": ev.get("ts_us"),
                               "attrs": ev.get("attrs", {})})
    return picked[-_EVENT_LIMIT:]


def beacon_payload(role: str = "process", registry=None,
                   epoch: Optional[int] = None,
                   now: Optional[float] = None) -> dict:
    """The beacon dict :func:`write_beacon` persists — exposed so tests
    and the live ``/fleet`` route can inspect it without disk."""
    if registry is None:
        registry = metrics
    if now is None:
        now = time.time()
    try:
        from flink_ml_tpu.observability.exporters import safe_process_label

        proc = safe_process_label()
    except Exception:
        proc = None
    try:
        from flink_ml_tpu.parallel.distributed import process_index

        index = int(process_index())
    except Exception:
        index = 0
    payload = {
        "schema": BEACON_SCHEMA,
        "time": float(now),
        "seq": _next_seq(),
        "pid": os.getpid(),
        "process": proc,
        "processIndex": index,
        "role": role,
        "interval_s": beacon_interval_s(),
    }
    if epoch is not None:
        payload["epoch"] = int(epoch)
    try:
        payload["windows"] = _windows_payload(registry)
    except Exception:
        payload["windows"] = {}
    try:
        payload["gauges"] = _gauges_payload(registry)
    except Exception:
        payload["gauges"] = {}
    payload["load"] = _load_payload()
    payload["events"] = _events_payload()
    return payload


def write_beacon(base_dir: Optional[str] = None, role: str = "process",
                 registry=None, epoch: Optional[int] = None,
                 now: Optional[float] = None) -> Optional[str]:
    """Atomically write this process's beacon into ``base_dir`` (or the
    :func:`fleet_dir` resolution when None).  Returns the path, or None
    when disarmed or on any write failure — liveness reporting must
    never raise into the workload (the elastic ``beat()`` contract)."""
    resolved = base_dir if base_dir is not None else fleet_dir()
    if not resolved:
        return None
    try:
        from flink_ml_tpu.observability.exporters import artifact_suffix

        suffix = artifact_suffix()
    except Exception:
        suffix = str(os.getpid())
    path = os.path.join(resolved, f"fleet-{suffix}.json")
    try:
        payload = beacon_payload(role=role, registry=registry,
                                 epoch=epoch, now=now)
        os.makedirs(resolved, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
        return path
    except (OSError, ValueError, TypeError):
        return None


def _beacon_loop(stop: threading.Event) -> None:
    # wait-first: start_beacon already wrote the initial beacon, and an
    # eager write here would race a second start_beacon's joined-role
    # write landing between thread start and the first tick
    while not stop.wait(beacon_interval_s()):
        with _seq_lock:
            base, roles = _beacon_dir, list(_beacon_tokens.values())
        if roles:
            role = "+".join(dict.fromkeys(roles))
            write_beacon(base, role=role)


def start_beacon(role: str = "process",
                 base_dir: Optional[str] = None) -> Optional[int]:
    """Start (or join) the singleton periodic beacon writer under
    ``role``; returns a token for :func:`stop_beacon`, or None when no
    fleet dir resolves (disarmed runtime — nothing to write into).
    Multiple components sharing a process (batcher + controller)
    stack roles: the beacon reports them joined with '+'."""
    global _beacon_thread, _beacon_stop, _beacon_dir, _next_token
    resolved = base_dir if base_dir is not None else fleet_dir()
    if not resolved:
        return None
    with _seq_lock:
        token = _next_token
        _next_token += 1
        _beacon_tokens[token] = role
        _beacon_dir = resolved
        roles = list(_beacon_tokens.values())
        started = _beacon_thread is not None and _beacon_thread.is_alive()
        if not started:
            _beacon_stop = threading.Event()
            _beacon_thread = threading.Thread(
                target=_beacon_loop, args=(_beacon_stop,),
                name="fleet-beacon", daemon=True)
    # first write + thread start outside the lock: never IO under it
    write_beacon(resolved, role="+".join(dict.fromkeys(roles)))
    if not started:
        _beacon_thread.start()
    return token


def stop_beacon(token: Optional[int]) -> None:
    """Release a :func:`start_beacon` registration; the last release
    stops the writer thread after one final beacon (so the stamp a
    clean shutdown leaves behind is as fresh as possible)."""
    if token is None:
        return
    global _beacon_thread, _beacon_stop, _beacon_dir
    with _seq_lock:
        _beacon_tokens.pop(token, None)
        if _beacon_tokens:
            return
        stop, thread = _beacon_stop, _beacon_thread
        base = _beacon_dir
        _beacon_stop = _beacon_thread = None
        _beacon_dir = None
    if stop is not None:
        stop.set()
    if thread is not None and thread.is_alive():
        thread.join(timeout=2.0 * beacon_interval_s())
    write_beacon(base, role="stopped")


# -- beacon reading ----------------------------------------------------------

def _validate_beacon(raw: dict) -> None:
    """All-or-nothing admission: a beacon either parses whole — schema,
    stamp, and every carried window snapshot bucket-valid — or it is
    rejected entirely.  A torn write must never fold partially into a
    fleet aggregate (the ``MetricsRegistry.merge`` discipline)."""
    if not isinstance(raw, dict):
        raise ValueError("beacon is not an object")
    if raw.get("schema") != BEACON_SCHEMA:
        raise ValueError(f"unknown beacon schema {raw.get('schema')!r}")
    float(raw["time"])
    int(raw["pid"])
    int(raw.get("processIndex", 0))
    windows = raw.get("windows", {})
    if not isinstance(windows, dict):
        raise ValueError("beacon windows is not an object")
    for gname, entry in windows.items():
        if not isinstance(entry, dict):
            raise ValueError(f"beacon group {gname!r} is not an object")
        for key, per_window in entry.get("histograms", {}).items():
            if not isinstance(per_window, dict):
                raise ValueError(
                    f"beacon histogram {key!r} windows not an object")
            for snap in per_window.values():
                check_histogram_snapshot(key, snap)
        for key, per_window in entry.get("counters", {}).items():
            if not isinstance(per_window, dict):
                raise ValueError(
                    f"beacon counter {key!r} windows not an object")
            for val in per_window.values():
                int(val)


def member_key(raw: dict) -> str:
    """Stable member identity across relaunches: ``p<index>`` when the
    runtime hands out process labels (a relaunched replica with a new
    pid supersedes its predecessor), else ``pid-<pid>``."""
    proc = raw.get("process")
    if proc is not None:
        return f"p{proc}"
    return f"pid-{raw.get('pid')}"


def read_beacons(base_dir: str) -> Tuple[List[dict], int]:
    """``(beacons, invalid_count)`` from ``base_dir`` — one entry per
    member (newest stamp wins when a relaunch left an older file
    behind), torn/partial/malformed beacons counted but never folded."""
    members: Dict[str, dict] = {}
    invalid = 0
    for path in sorted(glob.glob(os.path.join(base_dir, BEACON_GLOB))):
        try:
            with open(path, encoding="utf-8") as fh:
                raw = json.load(fh)
            _validate_beacon(raw)
        except (OSError, ValueError, TypeError, KeyError):
            invalid += 1
            continue
        key = member_key(raw)
        prev = members.get(key)
        if prev is None or float(raw["time"]) >= float(prev["time"]):
            members[key] = raw
    return list(members.values()), invalid


def fold_snapshots(snaps: List[dict]) -> Optional[dict]:
    """Sum cumulative-bucket snapshots bin-exactly.  Bucket layouts
    must match across members (they do by construction — every process
    runs the same code registering the same buckets); a mismatch raises
    rather than aggregating apples with oranges."""
    folded: Optional[dict] = None
    for snap in snaps:
        if folded is None:
            folded = {"buckets": [float(b) for b in snap["buckets"]],
                      "counts": [int(c) for c in snap["counts"]],
                      "sum": float(snap.get("sum", 0.0)),
                      "count": int(snap.get("count", 0))}
            continue
        check_histogram_snapshot(None, snap, folded["buckets"])
        folded["counts"] = [a + int(b) for a, b
                            in zip(folded["counts"], snap["counts"])]
        folded["sum"] += float(snap.get("sum", 0.0))
        folded["count"] += int(snap.get("count", 0))
    return folded


def _key_matches(key: str, name: str,
                 labels: Optional[Dict[str, str]]) -> bool:
    """Base-name + label-subset match (the slo.py rule: extra labels on
    the series — ``servable=``, ``process=`` — never block a match).
    Lazy imports keep the exporters/health edges one-directional at
    module load."""
    base, _, rest = key.partition("{")
    if base != name:
        return False
    if not labels:
        return True
    from flink_ml_tpu.observability.health import _parse_labels

    got = _parse_labels(rest[:-1] if rest else "")
    return all(got.get(k) == str(v) for k, v in labels.items())


def _pick_window(per_window: Dict[str, object], window_s: float):
    """The carried slice answering a ``window_s`` ask: smallest carried
    window >= the ask (never undercounts), else the largest carried."""
    parsed = sorted((float(w), snap) for w, snap in per_window.items())
    if not parsed:
        return None
    for w, snap in parsed:
        if w >= window_s:
            return snap
    return parsed[-1][1]


class FleetView:
    """Aggregated live view over a fleet directory's beacons:
    membership with staleness classification, bin-exact fleet-level
    windowed quantiles, per-replica load rows.  ``clock`` is injectable
    for tests; classification clamps negative ages to zero so a
    clock-skewed (future-stamped) beacon reads as fresh, never as
    negative-age weirdness."""

    def __init__(self, base_dir: str, stale_s: Optional[float] = None,
                 clock=time.time):
        self.base_dir = base_dir
        self.stale_s = float(stale_s) if stale_s is not None \
            else stale_after_s()
        self.clock = clock
        self.members: List[dict] = []
        self.invalid = 0
        self.refresh()

    def refresh(self) -> None:
        self.members, self.invalid = read_beacons(self.base_dir)

    def _age(self, raw: dict, now: float) -> float:
        return max(0.0, now - float(raw["time"]))

    def classify(self, age_s: float) -> str:
        if age_s <= self.stale_s:
            return "alive"
        if age_s <= 2.0 * self.stale_s:
            return "stale"
        return "dead"

    def membership(self) -> List[dict]:
        """One row per member: identity, role, state, beacon age."""
        now = self.clock()
        rows = []
        for raw in sorted(self.members, key=member_key):
            age = self._age(raw, now)
            rows.append({
                "member": member_key(raw),
                "process": raw.get("process"),
                "processIndex": raw.get("processIndex"),
                "pid": raw.get("pid"),
                "role": raw.get("role"),
                "state": self.classify(age),
                "age_s": round(age, 3),
                "seq": raw.get("seq"),
                "epoch": raw.get("epoch"),
                "interval_s": raw.get("interval_s"),
            })
        return rows

    def alive_members(self) -> List[dict]:
        now = self.clock()
        return [raw for raw in self.members
                if self.classify(self._age(raw, now)) == "alive"]

    def members_missing(self) -> List[str]:
        """Member ids currently stale or dead — the 'half-dead fleet'
        bookkeeping fleet-scope SLO verdicts must surface."""
        now = self.clock()
        return sorted(member_key(raw) for raw in self.members
                      if self.classify(self._age(raw, now)) != "alive")

    # -- SLO source protocol (alive members only) ------------------------
    def hist_window(self, group: str, name: str,
                    labels: Optional[Dict[str, str]],
                    window_s: float) -> Tuple[Optional[dict], str]:
        snaps = []
        contributing = 0
        for raw in self.alive_members():
            entry = raw.get("windows", {}).get(group, {})
            member_snaps = [
                _pick_window(per_window, window_s)
                for key, per_window in entry.get("histograms", {}).items()
                if _key_matches(key, name, labels)]
            member_snaps = [s for s in member_snaps if s is not None]
            if member_snaps:
                contributing += 1
                snaps.extend(member_snaps)
        folded = fold_snapshots(snaps)
        return folded, f"fleet[{contributing}]:{int(window_s)}s"

    def counter_window(self, group: str, name: str,
                       labels: Optional[Dict[str, str]],
                       window_s: float) -> Tuple[float, str]:
        total = 0
        contributing = 0
        for raw in self.alive_members():
            entry = raw.get("windows", {}).get(group, {})
            hit = False
            for key, per_window in entry.get("counters", {}).items():
                if not _key_matches(key, name, labels):
                    continue
                delta = _pick_window(per_window, window_s)
                if delta is not None:
                    total += int(delta)
                    hit = True
            if hit:
                contributing += 1
        return float(total), f"fleet[{contributing}]:{int(window_s)}s"

    def gauge_values(self, group: str, name: str,
                     labels: Optional[Dict[str, str]] = None) -> List[tuple]:
        out = []
        for raw in self.alive_members():
            for key, val in raw.get("gauges", {}).get(group, {}).items():
                if not _key_matches(key, name, labels):
                    continue
                try:
                    out.append((f"{key}@{member_key(raw)}", float(val)))
                except (TypeError, ValueError):
                    continue  # non-numeric gauge: not comparable
        return out

    # -- per-member detail -----------------------------------------------
    def per_member_quantile(self, group: str, name: str,
                            labels: Optional[Dict[str, str]],
                            window_s: float, q: float) -> Dict[str, float]:
        """Member id -> quantile over its OWN carried window — the
        per-replica load signal beside the fleet aggregate."""
        out: Dict[str, float] = {}
        for raw in self.alive_members():
            entry = raw.get("windows", {}).get(group, {})
            snaps = [
                _pick_window(per_window, window_s)
                for key, per_window in entry.get("histograms", {}).items()
                if _key_matches(key, name, labels)]
            folded = fold_snapshots([s for s in snaps if s is not None])
            if folded is not None and folded.get("count", 0) > 0:
                out[member_key(raw)] = histogram_quantile(folded, q)
        return out

    def aggregates(self, window_s: float) -> Dict[str, dict]:
        """Fleet-level p50/p99/count for every windowed histogram any
        alive member carries, keyed ``<group>/<series>`` — the signal
        table load-aware routing will read."""
        by_key: Dict[str, List[dict]] = {}
        for raw in self.alive_members():
            for gname, entry in raw.get("windows", {}).items():
                for key, per_window in entry.get("histograms", {}).items():
                    snap = _pick_window(per_window, window_s)
                    if snap is not None:
                        by_key.setdefault(f"{gname}/{key}", []).append(snap)
        out: Dict[str, dict] = {}
        for full_key, snaps in sorted(by_key.items()):
            try:
                folded = fold_snapshots(snaps)
            except ValueError:
                continue  # drifted layout across members: skip the series
            if folded is None or folded.get("count", 0) <= 0:
                continue
            out[full_key] = {
                "p50": histogram_quantile(folded, 0.50),
                "p99": histogram_quantile(folded, 0.99),
                "count": folded["count"],
                "sum": folded["sum"],
                "members": len(snaps),
            }
        return out

    def load_rows(self) -> List[dict]:
        rows = []
        for raw in sorted(self.members, key=member_key):
            load = raw.get("load", {}) or {}
            rows.append({"member": member_key(raw),
                         "role": raw.get("role"), **load})
        return rows

    def report(self, window_s: float = 60.0) -> dict:
        """The full fleet report the CLI and ``/fleet`` route render."""
        membership = self.membership()
        states = [row["state"] for row in membership]
        return {
            "fleetDir": self.base_dir,
            "time": self.clock(),
            "windowS": window_s,
            "staleS": self.stale_s,
            "members": membership,
            "counts": {"alive": states.count("alive"),
                       "stale": states.count("stale"),
                       "dead": states.count("dead"),
                       "invalid": self.invalid},
            "membersMissing": self.members_missing(),
            "aggregates": self.aggregates(window_s),
            "load": self.load_rows(),
        }


# -- elastic liveness view ---------------------------------------------------

def stale_member_indices(base_dir: str, timeout_s: float,
                         num_processes: Optional[int] = None,
                         now: Optional[float] = None) -> List[int]:
    """Process indices whose beacon stamp is older than ``timeout_s``
    (or missing entirely) — the elastic watchdog's
    ``stale_processes()`` view over the fleet plane.  A member that
    never wrote a beacon is stale by definition: silence IS the
    signal."""
    beacons, _ = read_beacons(base_dir)
    if now is None:
        now = time.time()
    fresh = set()
    seen = set()
    for raw in beacons:
        idx = int(raw.get("processIndex", 0))
        seen.add(idx)
        if max(0.0, now - float(raw["time"])) <= timeout_s:
            fresh.add(idx)
    n = num_processes if num_processes is not None else \
        (max(seen) + 1 if seen else 0)
    return [i for i in range(n) if i not in fresh]


# -- provenance --------------------------------------------------------------

def provenance() -> dict:
    """The fleet fields benchmark rows carry: ``fleetMembers`` (beacon
    count in the resolved fleet dir) and ``fleetP99Ms`` (fleet queueMs
    p99 over 60 s, falling back to transformMs then batchMs).  Both
    None on single-process / disarmed benches — never raises (the
    benchmark provenance contract)."""
    out = {"fleetMembers": None, "fleetP99Ms": None}
    try:
        base = fleet_dir()
        if not base:
            return out
        view = FleetView(base)
        if not view.members:
            return out
        out["fleetMembers"] = len(view.members)
        for series in ("queueMs", "transformMs", "batchMs"):
            snap, _src = view.hist_window("ml.serving", series, None, 60.0)
            if snap is not None and snap.get("count", 0) > 0:
                out["fleetP99Ms"] = histogram_quantile(snap, 0.99)
                break
    except Exception:
        pass
    return out


# -- CLI ---------------------------------------------------------------------

def _fmt_ms(val) -> str:
    if val is None or val != val:  # NaN
        return "-"
    return f"{val:.2f}ms"


def render_report(report: dict) -> str:
    counts = report["counts"]
    lines = [f"fleet {report['fleetDir']} — "
             f"{len(report['members'])} member(s): "
             f"{counts['alive']} alive, {counts['stale']} stale, "
             f"{counts['dead']} dead"
             + (f", {counts['invalid']} invalid beacon(s)"
                if counts["invalid"] else "")]
    if report["members"]:
        lines.append(f"  {'member':<8} {'role':<18} {'state':<6} "
                     f"{'age':>7} {'pid':>7} {'seq':>5}  epoch")
        for row in report["members"]:
            epoch = row.get("epoch")
            lines.append(
                f"  {row['member']:<8} {str(row.get('role')):<18} "
                f"{row['state']:<6} {row['age_s']:>6.1f}s "
                f"{str(row.get('pid')):>7} {str(row.get('seq')):>5}  "
                f"{epoch if epoch is not None else '-'}")
    if report["membersMissing"]:
        lines.append("  missing: " + ", ".join(report["membersMissing"]))
    if report["aggregates"]:
        lines.append(f"windows ({int(report['windowS'])}s, "
                     "alive members, bin-exact fold):")
        for key, agg in report["aggregates"].items():
            lines.append(
                f"  {key:<40} p50={_fmt_ms(agg['p50'])} "
                f"p99={_fmt_ms(agg['p99'])} n={agg['count']} "
                f"members={agg['members']}")
    loaded = [row for row in report["load"]
              if any(row.get(k) is not None for k in
                     ("queueDepth", "inFlight", "servable",
                      "bootToReadyMs", "aucLive"))]
    if loaded:
        lines.append("load:")
        for row in loaded:
            boot = row.get("bootToReadyMs")
            auc = row.get("aucLive")
            cov = row.get("feedbackCoverage")
            lines.append(
                f"  {row['member']:<8} queueDepth="
                f"{row.get('queueDepth')} inFlight={row.get('inFlight')} "
                f"servable={row.get('servable')} "
                f"version={row.get('modelVersion')} "
                f"canary={row.get('canary')}"
                + (f" bootToReadyMs={boot:.0f}" if boot is not None
                   else "")
                + (f" aucLive={auc:.4f}" if auc is not None else "")
                + (f" coverage={cov:.2f}" if cov is not None else ""))
        # the half-fleet collapse view: one line naming the member
        # whose live AUC is worst across the fleet
        quality = [(row["member"], row["aucLive"]) for row in loaded
                   if row.get("aucLive") is not None]
        if quality:
            worst_member, worst_auc = min(quality, key=lambda mv: mv[1])
            lines.append(f"quality: worst live AUC {worst_auc:.4f} "
                         f"({worst_member}, {len(quality)} member(s) "
                         f"reporting)")
    return "\n".join(lines)


def _eval_fleet_slos(view: "FleetView", spec_path: Optional[str]):
    """Fleet-scope SLO verdicts over this view (lazy import — slo.py
    imports fleet for its own fleet-source, this is the reverse edge
    kept function-local)."""
    from flink_ml_tpu.observability import slo as slo_mod

    if spec_path:
        slos = slo_mod.load_specs(spec_path)
    else:
        slos = slo_mod.default_slos()
    # quality rides too: its gauges travel in every beacon's ml.quality
    # group, so a fleet-scope AUC floor evaluates from beacons alone
    slos = [s for s in slos
            if s.kind in ("latency", "error-rate", "quality")]
    for s in slos:
        s.scope = "fleet"
    return slo_mod.evaluate_slos(slos, fleet_view=view)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace fleet",
        description="Live fleet membership + bin-exact windowed "
                    "aggregates from beacon files.")
    parser.add_argument("dir", help="fleet dir (or a trace dir/root "
                                    "holding a fleet/ subdir)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the report as JSON")
    parser.add_argument("--check", action="store_true",
                        help="exit 4 on a dead member or a violated "
                             "fleet-scope SLO")
    parser.add_argument("--watch", action="store_true",
                        help="re-render every beacon interval until ^C")
    parser.add_argument("--window", type=float, default=60.0,
                        help="aggregation window seconds (default 60)")
    parser.add_argument("--stale-s", type=float, default=None,
                        help="override the staleness threshold")
    parser.add_argument("--spec", default=None,
                        help="JSON SLO spec file evaluated at fleet "
                             "scope under --check")
    parser.add_argument("--latest", action="store_true",
                        help="treat DIR as a root; use its newest "
                             "trace dir")
    args = parser.parse_args(argv)

    try:
        from flink_ml_tpu.observability.exporters import resolve_trace_dir

        root = resolve_trace_dir(args.dir, args.latest)
    except OSError as exc:
        print(f"fleet: {exc}", file=sys.stderr)
        return EXIT_INVALID

    while True:
        base = find_fleet_dir(root)
        if base is None:
            print(f"fleet: no fleet telemetry under {root} "
                  f"(no {BEACON_GLOB} beacons)", file=sys.stderr)
            return EXIT_INVALID
        view = FleetView(base, stale_s=args.stale_s)
        report = view.report(window_s=args.window)
        rc = EXIT_OK
        verdicts = []
        if args.check:
            if report["counts"]["dead"]:
                rc = EXIT_VIOLATION
            try:
                verdicts = _eval_fleet_slos(view, args.spec)
            except (OSError, ValueError) as exc:
                print(f"fleet: bad SLO spec: {exc}", file=sys.stderr)
                return EXIT_INVALID
            if any(not v["ok"] for v in verdicts):
                rc = EXIT_VIOLATION
        if args.as_json:
            if verdicts:
                report = dict(report, slo=verdicts)
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            print(render_report(report))
            if verdicts:
                from flink_ml_tpu.observability.slo import render_verdicts

                print(render_verdicts(verdicts))
        if not args.watch:
            return rc
        try:
            time.sleep(beacon_interval_s())
        except KeyboardInterrupt:
            return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
