"""Mesh telemetry: topology snapshots, per-shard metrics, skew detection.

The distributed runtime (shard_map fits over the ``parallel/`` mesh) was
the one layer the observability stack could not see: a trace told you an
epoch took 40 ms but not how many devices ran it, whether the batch was
spread evenly over them, or which replica a NaN came from. This module
adds the missing mesh dimension (docs/observability.md "Distributed
telemetry"), DrJAX-style (arXiv:2403.07128): per-replica quantities are
first-class outputs of the jitted program or host-side shard math —
never per-element device probes.

Four surfaces, all JL107-clean (recording happens at host boundaries;
anything device-side is folded to per-shard scalars inside the program):

- **Topology**: :func:`ensure_mesh_recorded` — called from the
  ``parallel.shardmap`` build seam — writes the mesh snapshot (device
  count, axis layout, platform, per-device ids) once per mesh as
  ``ml.mesh`` gauges, root-span attributes and a ``mesh.json`` trace
  artifact, so every later reader knows whether a trace is a 1-device
  cpu fallback or a real mesh.
- **Per-shard labels**: ``ml.shard`` gauges/histograms carry
  ``shard=``/``device=`` labels — ``shard`` is the dim-0 block index in
  the mesh's row-major device order, ``device`` the JAX device id — so
  registry merges (host-pool fork, multi-process traces) keep replicas
  apart.
- **Skew/straggler detection**: :func:`detect_skew` gauges the
  max/median spread of any per-shard series (ready-time, row counts)
  and emits an ``ml.skew`` event when it exceeds
  ``FLINK_ML_TPU_SKEW_FACTOR`` (default 4.0×) past an absolute floor.
- **Per-shard health**: :func:`record_input_health` runs one tiny
  shard_mapped reduction returning per-shard non-finite counts, so bad
  input data is attributable to a replica before the fit consumes it.

Inspect with ``flink-ml-tpu-trace shards <dir>``.
"""

from __future__ import annotations

import functools
import json
import math
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import tracing

__all__ = [
    "MESH_FILE",
    "SKEW_EVENT",
    "SKEW_FACTOR_ENV",
    "SKEW_FLOOR_MS_ENV",
    "detect_skew",
    "ensure_mesh_recorded",
    "mesh_snapshot",
    "observe_shard_ready",
    "read_mesh",
    "record_input_health",
    "record_shard_rows",
    "skew_factor",
]

#: the mesh-topology artifact in a trace dir (one file, every mesh the
#: traced processes built, newest-last)
MESH_FILE = "mesh.json"

#: instant-event name for a detected straggler/imbalance
SKEW_EVENT = "ml.skew"

#: max/median ratio above which a per-shard spread is skew (default 4.0)
SKEW_FACTOR_ENV = "FLINK_ML_TPU_SKEW_FACTOR"

#: absolute ready-time spread floor (ms) below which the ratio never
#: fires — a simulated CPU mesh has ~0 medians, and 0.2 ms vs 0.05 ms is
#: not a straggler (default 50 ms)
SKEW_FLOOR_MS_ENV = "FLINK_ML_TPU_SKEW_FLOOR_MS"

#: meshes already recorded by THIS process (pid in the key: a forked
#: host-pool child must re-record into its own artifacts)
_recorded: set = set()


def _shard_group():
    return metrics.group(ML_GROUP, "shard")


def _mesh_group():
    return metrics.group(ML_GROUP, "mesh")


def skew_factor() -> float:
    try:
        return float(os.environ.get(SKEW_FACTOR_ENV, "4.0"))
    except ValueError:
        return 4.0


def _skew_floor_ms() -> float:
    try:
        return float(os.environ.get(SKEW_FLOOR_MS_ENV, "50.0"))
    except ValueError:
        return 50.0


# -- topology -----------------------------------------------------------------

def mesh_snapshot(mesh) -> dict:
    """The JSON-ready topology of one mesh: what a reader needs to tell
    a 1-device cpu fallback from an 8-way data mesh from a (2, 4)
    dcn×data hybrid, and to resolve ``shard`` indices to devices."""
    devices = list(mesh.devices.flat)
    return {
        "device_count": len(devices),
        "axis_names": list(mesh.axis_names),
        "shape": {name: int(mesh.shape[name]) for name in mesh.axis_names},
        "platform": devices[0].platform if devices else None,
        "devices": [{"id": int(d.id),
                     "process": int(getattr(d, "process_index", 0)),
                     "platform": d.platform} for d in devices],
    }


def _mesh_key(mesh):
    return (os.getpid(), tuple(mesh.axis_names),
            tuple(int(d.id) for d in mesh.devices.flat),
            mesh.devices.shape)


def ensure_mesh_recorded(mesh) -> None:
    """Record one mesh's topology — gauges, root-span attrs, mesh.json —
    exactly once per (process, mesh). No-op when the tracer is disarmed:
    topology without a trace dir has nowhere to land."""
    tracer = tracing.tracer
    if mesh is None or not tracer.enabled:
        return
    key = _mesh_key(mesh)
    if key in _recorded:
        return
    _recorded.add(key)
    snap = mesh_snapshot(mesh)
    group = _mesh_group()
    group.gauge("deviceCount", snap["device_count"])
    for name, size in snap["shape"].items():
        group.gauge("axisSize", size, labels={"axis": name})
    root = tracer.root()
    if root is not None:
        root.set_attribute("mesh_devices", snap["device_count"])
        root.set_attribute("mesh_axes", ",".join(
            f"{k}={v}" for k, v in snap["shape"].items()))
        if snap["platform"]:
            root.set_attribute("mesh_platform", snap["platform"])
    _append_mesh_file(tracer.trace_dir, snap)


def _append_mesh_file(trace_dir: str, snap: dict) -> None:
    """Append ``snap`` to the dir's ``mesh.json`` (read-modify-replace:
    concurrent traced processes at worst drop a duplicate topology, never
    tear the file)."""
    path = os.path.join(trace_dir, MESH_FILE)
    doc = {"meshes": []}
    try:
        with open(path, "r", encoding="utf-8") as f:
            existing = json.load(f)
        if isinstance(existing, dict) and \
                isinstance(existing.get("meshes"), list):
            doc = existing
    except (OSError, json.JSONDecodeError):
        pass
    if snap in doc["meshes"]:
        return
    doc["meshes"].append(snap)
    os.makedirs(trace_dir, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)


def read_mesh(trace_dir: str) -> Optional[dict]:
    """The newest mesh snapshot from a trace dir's ``mesh.json`` (the
    one the run actually fitted on), or None when the artifact is
    absent/unreadable."""
    path = os.path.join(trace_dir, MESH_FILE)
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        meshes = doc.get("meshes") or []
        return meshes[-1] if meshes else None
    except (OSError, json.JSONDecodeError, AttributeError):
        return None


# -- skew/straggler detection -------------------------------------------------

def detect_skew(kind: str, values: Sequence[float],
                floor: float = 0.0, **attrs) -> Optional[float]:
    """Gauge the max/median spread of a per-shard series and emit an
    ``ml.skew`` event when it exceeds the configured factor.

    Returns the spread (max/median), or None for an empty/degenerate
    series. The event only fires when the absolute max-median gap also
    clears ``floor`` — ratios over near-zero medians (a simulated CPU
    mesh's ready times) are noise, not stragglers."""
    vals = [float(v) for v in values if math.isfinite(float(v))]
    if len(vals) < 2:
        return None
    med = float(np.median(vals))
    mx = max(vals)
    if med <= 0.0:
        spread = math.inf if mx > 0 else 1.0
    else:
        spread = mx / med
    group = _shard_group()
    group.gauge("skew", spread if math.isfinite(spread) else -1.0,
                labels={"kind": kind})
    factor = skew_factor()
    if spread > factor and (mx - med) > floor:
        group.counter("skewEvents", labels={"kind": kind})
        tracing.tracer.event(
            SKEW_EVENT, kind=kind, spread=round(spread, 2)
            if math.isfinite(spread) else "inf",
            max=round(mx, 3), median=round(med, 3),
            shard=int(np.argmax(vals)), factor=factor, **attrs)
    return spread


# -- per-shard series ---------------------------------------------------------

def shard_row_counts(mesh, n: int, axis_name=None,
                     local_n: Optional[int] = None) -> List[int]:
    """Valid (un-padded) rows each dim-0 shard holds after
    ``shard_batch``'s zero-padding — pure host math from the scalar
    ``n``, in the mesh's row-major shard order. ``local_n`` overrides
    the per-shard slice size for callers whose padded length is NOT the
    ceil multiple — the serving micro-batcher pads to a bucket, so each
    shard owns ``bucket / N`` rows and the real rows fill from shard 0."""
    from flink_ml_tpu.parallel.mesh import data_shard_count

    shards = data_shard_count(mesh) if axis_name is None else None
    if shards is None:
        axes = ((axis_name,) if isinstance(axis_name, str)
                else tuple(axis_name))
        shards = int(np.prod([mesh.shape[a] for a in axes]))
    if local_n is None:
        local_n = -(-n // shards)  # ceil: padded rows land on the tail
    return [int(min(max(n - i * local_n, 0), local_n))
            for i in range(shards)]


def record_shard_rows(mesh, n: int, axis_name=None,
                      local_n: Optional[int] = None,
                      skew: bool = True) -> List[int]:
    """Per-shard row-count gauges (``ml.shard rows{shard=,device=}``) +
    the row-imbalance skew check. Returns the per-shard counts.
    ``skew=False`` records the series without the straggler detector —
    the serving dispatcher's partially-filled buckets are *expected* to
    load shard 0 first, so a per-tick skew event would be noise, not a
    straggler signal (the serving view is ``ml.serving shardRows``)."""
    counts = shard_row_counts(mesh, n, axis_name, local_n=local_n)
    devices = list(mesh.devices.flat)
    group = _shard_group()
    for i, rows in enumerate(counts):
        dev = devices[i] if i < len(devices) else None
        group.gauge("rows", rows, labels={
            "shard": str(i),
            "device": str(int(dev.id)) if dev is not None else "?"})
    if skew:
        detect_skew("rows", counts)
    return counts


def _global_shard_ordinal(shard, local_i: int) -> int:
    """The GLOBAL dim-0 shard index of one addressable shard: on a
    multi-process mesh each process enumerates only its own shards, so
    the local ordinal would collide across processes in a merged trace
    (process 0's shard "1" vs process 1's shard "1" are different
    replicas). Derived from the shard's global slice start / chunk
    length; falls back to the local ordinal for replicated leaves."""
    try:
        sl = shard.index[0]
        chunk = shard.data.shape[0]
        if sl.start is not None and chunk:
            return int(sl.start) // int(chunk)
    except Exception:
        pass
    return local_i


def observe_shard_ready(tree, span=None, phase: str = "epoch"
                        ) -> Optional[List[float]]:
    """Per-shard time-to-ready of the first sharded device array in
    ``tree``: each addressable shard's ``block_until_ready`` is timed in
    device order, so after an async dispatch the waits approximate each
    replica's remaining work — the straggler surface of the epoch.
    Records ``ml.shard readyMs{shard=,device=,phase=}`` histograms, the
    ready-time skew check, and (optionally) the spread onto ``span``.
    Returns the per-shard times (ms), or None when ``tree`` holds no
    multi-shard device array."""
    import jax

    arr = None
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and \
                len(getattr(leaf, "addressable_shards", ())) > 1:
            arr = leaf
            break
    if arr is None:
        return None
    group = _shard_group()
    times = []
    for i, shard in enumerate(arr.addressable_shards):
        t0 = time.perf_counter()
        shard.data.block_until_ready()
        ms = (time.perf_counter() - t0) * 1000.0
        times.append(ms)
        group.histogram("readyMs", labels={
            "shard": str(_global_shard_ordinal(shard, i)),
            "device": str(int(shard.device.id)),
            "phase": phase}).observe(ms)
    spread = detect_skew("readyMs", times, floor=_skew_floor_ms(),
                         phase=phase)
    if span is not None:
        span.set_attribute("shard_ready_ms",
                           [round(t, 3) for t in times])
        if spread is not None and math.isfinite(spread):
            span.set_attribute("shard_skew", round(spread, 2))
    return times


# -- per-shard health ---------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _nonfinite_program(mesh, ndim: int):
    """Per-shard non-finite element counts of a dim-0-sharded array as
    ONE ``(n_shards,)`` output — the count folds inside the shard_map
    body (JL107-clean), then all-gathers so the tiny vector comes back
    REPLICATED: on a multi-process mesh the host can only materialize
    fully-replicated outputs (a P(data)-sharded result would strand
    other processes' shards), and single-process the gather of one
    scalar per shard costs nothing."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu.parallel import mapreduce as mr
    from flink_ml_tpu.parallel.mesh import data_axes, data_pspec

    spec0 = data_pspec(mesh)
    axes = data_axes(mesh)
    ax = axes[0] if len(axes) == 1 else axes

    def per_shard(xl):
        bad = jnp.sum(jnp.logical_not(jnp.isfinite(xl)))
        return mr.all_gather(bad.astype(jnp.int32)[None], ax)

    return mr.map_shards(
        per_shard, mesh,
        in_specs=P(spec0, *([None] * (ndim - 1))),
        out_specs=P())


def record_input_health(algo: str, mesh, array) -> Optional[List[int]]:
    """Per-shard non-finite counts of a mesh-resident input
    (``ml.shard nonFinite{algo=,shard=,device=}`` gauges) so corrupt
    data is attributable to a replica before the fit consumes it.
    Returns the counts, or None when the array is not multi-sharded."""
    import jax

    if not isinstance(array, jax.Array) or \
            len(getattr(array, "addressable_shards", ())) < 2:
        return None
    counts = np.asarray(_nonfinite_program(mesh, array.ndim)(array))
    devices = list(mesh.devices.flat)
    group = _shard_group()
    for i, bad in enumerate(counts):
        dev = devices[i] if i < len(devices) else None
        group.gauge("nonFinite", int(bad), labels={
            "algo": algo, "shard": str(i),
            "device": str(int(dev.id)) if dev is not None else "?"})
    if counts.any():
        tracing.tracer.event(
            "ml.health", algo=algo, kind="non-finite-input",
            shards=",".join(str(i) for i in np.nonzero(counts)[0]),
            total=int(counts.sum()))
    return [int(c) for c in counts]
