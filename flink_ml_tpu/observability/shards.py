"""``flink-ml-tpu-trace shards``: the per-device view of a trace dir.

Renders the mesh-telemetry artifacts (observability/meshstats.py,
docs/observability.md "Distributed telemetry") the way ``health``
renders model health — from the artifacts alone, no live process:

- the mesh topology (``mesh.json``): device count, axis layout,
  platform — is this trace a 1-device cpu fallback or a real mesh?
- one row per device: valid rows held, non-finite input elements,
  time-to-ready quantiles (the straggler surface), bytes reduced per
  collective round, and whether this shard was flagged by an
  ``ml.skew`` event;
- the collective program structure: per (op, axis, devices) traced-site
  counts + payload quantiles, and the host-boundary placement timings;
- the skew event timeline.

``--check`` exits 2 when the dir holds no mesh/shard telemetry at all —
the CI smoke gate proving a "multi-device" run really ran multi-device.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from flink_ml_tpu.common.metrics import histogram_quantile

#: gates --check: a multi-device trace must have recorded a mesh of at
#: least this many devices or per-shard series for them
MIN_DEVICES = 2


def _labeled(entries: Dict[str, object], name: str):
    """``(labels_dict, value)`` for every key of metric ``name``."""
    from flink_ml_tpu.observability.health import _parse_labels

    for key, value in entries.items():
        base, _, rest = key.partition("{")
        if base == name:
            yield _parse_labels(rest[:-1] if rest else ""), value


def shards_summary(spans: List[dict], snapshot: Dict[str, dict],
                   mesh: Optional[dict]) -> dict:
    """Structured per-device summary (the CLI's JSON output)."""
    shard_group = snapshot.get("ml.shard", {}) or {}
    coll_group = snapshot.get("ml.collective", {}) or {}

    rows: Dict[str, dict] = {}

    def row(shard: str, device: str) -> dict:
        return rows.setdefault(shard, {"shard": int(shard),
                                       "device": device})

    for labels, value in _labeled(shard_group.get("gauges", {}), "rows"):
        if "shard" in labels:
            row(labels["shard"], labels.get("device", "?"))["rows"] = \
                int(value)
    for labels, value in _labeled(shard_group.get("gauges", {}),
                                  "nonFinite"):
        if "shard" in labels:
            r = row(labels["shard"], labels.get("device", "?"))
            r["nonFinite"] = r.get("nonFinite", 0) + int(value)
    for labels, hist in _labeled(shard_group.get("histograms", {}),
                                 "readyMs"):
        if "shard" not in labels or not hist.get("count"):
            continue
        r = row(labels["shard"], labels.get("device", "?"))
        r["readyCount"] = r.get("readyCount", 0) + int(hist["count"])
        p50 = histogram_quantile(hist, 0.5)
        mx = histogram_quantile(hist, 1.0)
        r["readyMs_p50"] = max(r.get("readyMs_p50", 0.0),
                               0.0 if math.isnan(p50) else round(p50, 3))
        r["readyMs_max"] = max(r.get("readyMs_max", 0.0),
                               0.0 if math.isnan(mx) else round(mx, 3))

    # skew: per-kind spread gauges + the event timeline; flag the shard
    # each event blamed
    skew = {}
    for labels, value in _labeled(shard_group.get("gauges", {}), "skew"):
        skew[labels.get("kind", "?")] = value
    events = []
    for sp in spans:
        for ev in sp.get("events", ()):
            if ev.get("name") == "ml.skew":
                events.append({"ts_us": ev.get("ts_us", 0),
                               "attrs": ev.get("attrs", {})})
    events.sort(key=lambda e: e["ts_us"])
    for ev in events:
        shard = str(ev["attrs"].get("shard", ""))
        if shard in rows:
            rows[shard]["skewFlagged"] = True

    # collective program structure: traced sites + host-boundary timing
    collectives = []
    payload = {key: hist for key, hist
               in coll_group.get("histograms", {}).items()}
    for labels, count in _labeled(coll_group.get("counters", {}),
                                  "tracedOps"):
        from flink_ml_tpu.common.metrics import metric_key

        hist = payload.get(metric_key("payloadBytes", labels))
        entry = {"op": labels.get("op", "?"),
                 "axis": labels.get("axis", "?"),
                 "devices": labels.get("devices", "?"),
                 "tracedSites": int(count)}
        if hist and hist.get("count"):
            entry["payloadBytes_p50"] = round(
                histogram_quantile(hist, 0.5), 1)
            entry["payloadBytes_total"] = int(hist.get("sum", 0))
        collectives.append(entry)
    collectives.sort(key=lambda e: (e["op"], e["axis"]))

    host_ops = []
    for labels, hist in _labeled(coll_group.get("histograms", {}),
                                 "opMs"):
        if not hist.get("count"):
            continue
        host_ops.append({"op": labels.get("op", "?"),
                         "devices": labels.get("devices", "?"),
                         "count": int(hist["count"]),
                         "ms_p50": round(histogram_quantile(hist, 0.5), 3),
                         "ms_p99": round(histogram_quantile(hist, 0.99),
                                         3)})
    host_ops.sort(key=lambda e: e["op"])

    # bytes reduced per device: the sum of traced reduction-site
    # payloads (per-shard shapes). SPMD collectives move the same
    # per-shard volume through every device, so this column is identical
    # across rows BY CONSTRUCTION — it says how much each device
    # contributes to a reduction pass, not a per-device differential
    reduce_bytes = sum(
        e.get("payloadBytes_total", 0) for e in collectives
        if e["op"] in ("psum", "pmean", "pmax", "broadcast",
                       "termination_vote"))
    # process attribution: mesh.json records each device's owning
    # process (meshstats.mesh_snapshot), so a merged multi-process trace
    # resolves every shard row to the host that ran it — same-pid
    # artifact collisions across hosts are prevented by the file naming
    # (exporters.artifact_suffix); this is the read-side half
    dev_proc = {str(d.get("id")): int(d.get("process", 0))
                for d in (mesh or {}).get("devices", [])}
    n_procs = len(set(dev_proc.values())) if dev_proc else 1

    shard_rows = sorted(rows.values(), key=lambda r: r["shard"])
    for r in shard_rows:
        r.setdefault("rows", None)
        r.setdefault("nonFinite", 0)
        r["bytesReduced"] = reduce_bytes
        r.setdefault("skewFlagged", False)
        r["process"] = dev_proc.get(str(r.get("device")), 0)

    return {"mesh": mesh, "shards": shard_rows, "skew": skew,
            "skew_events": events, "collectives": collectives,
            "host_ops": host_ops, "process_count": n_procs}


def render_shards(summary: dict) -> str:
    out = []
    mesh = summary["mesh"]
    multiproc = summary.get("process_count", 1) > 1
    if mesh:
        axes = ",".join(f"{k}={v}" for k, v in mesh["shape"].items())
        procs = (f" processes={summary['process_count']}"
                 if multiproc else "")
        out.append(f"mesh: {mesh['device_count']} device(s) "
                   f"[{axes}] platform={mesh.get('platform')}{procs}")
    else:
        out.append("mesh: no mesh.json artifact (single-device run, or "
                   "trace predates mesh telemetry)")

    if summary["shards"]:
        out.append("")
        proc_hdr = f" {'proc':>5}" if multiproc else ""
        out.append(f"  {'shard':>5} {'device':>6}{proc_hdr} {'rows':>10} "
                   f"{'non-finite':>10} {'ready p50':>10} "
                   f"{'ready max':>10} {'bytes reduced':>13} {'skew':>5}")
        for r in summary["shards"]:
            proc_col = f" {r.get('process', 0):>5}" if multiproc else ""
            out.append(
                f"  {r['shard']:>5} {r['device']:>6}{proc_col} "
                f"{('-' if r['rows'] is None else r['rows']):>10} "
                f"{r['nonFinite']:>10} "
                f"{r.get('readyMs_p50', '-'):>10} "
                f"{r.get('readyMs_max', '-'):>10} "
                f"{r['bytesReduced']:>13} "
                f"{'!' if r['skewFlagged'] else '':>5}")

    if summary["skew"]:
        out.append("")
        out.append("skew (max/median per series):")
        for kind, value in sorted(summary["skew"].items()):
            out.append(f"  {kind}: "
                       f"{'inf' if value == -1.0 else round(value, 2)}")

    if summary["collectives"]:
        out.append("")
        out.append("collective sites (trace-time program structure):")
        for e in summary["collectives"]:
            extra = ""
            if "payloadBytes_p50" in e:
                extra = (f"  payload p50 {e['payloadBytes_p50']} B, "
                         f"total {e['payloadBytes_total']} B")
            out.append(f"  {e['op']} over {e['axis']} "
                       f"({e['devices']} devices): {e['tracedSites']} "
                       f"traced site(s){extra}")

    if summary["host_ops"]:
        out.append("")
        out.append("host-boundary collective ops:")
        for e in summary["host_ops"]:
            out.append(f"  {e['op']} ({e['devices']} devices): "
                       f"{e['count']}x  p50 {e['ms_p50']} ms  "
                       f"p99 {e['ms_p99']} ms")

    if summary["skew_events"]:
        out.append("")
        out.append("skew event timeline:")
        t0 = summary["skew_events"][0]["ts_us"]
        for ev in summary["skew_events"]:
            attrs = " ".join(f"{k}={v}" for k, v in ev["attrs"].items())
            out.append(f"  +{(ev['ts_us'] - t0) / 1000.0:>10.3f} ms  "
                       f"ml.skew  {attrs}")
    return "\n".join(out)


def main(argv=None) -> int:
    """``flink-ml-tpu-trace shards <dir>`` — per-device table + mesh
    topology + collective structure. ``--check`` exits 2 when the trace
    recorded no multi-device telemetry (mesh of ≥2 devices or per-shard
    series)."""
    import argparse
    import json
    import sys

    from flink_ml_tpu.observability.exporters import (
        pipe_guard,
        read_metrics,
        read_spans,
        resolve_trace_dir,
    )
    from flink_ml_tpu.observability.meshstats import read_mesh

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace shards",
        description="Per-device/per-shard view of a FLINK_ML_TPU_TRACE_"
                    "DIR: mesh topology, row/ready/skew table, "
                    "collective structure.")
    parser.add_argument("trace_dir")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 2 unless the trace recorded a "
                             "multi-device mesh or per-shard series")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    args = parser.parse_args(argv)

    try:
        args.trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        spans = read_spans(args.trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace shards: cannot read "
              f"{args.trace_dir}: {e}", file=sys.stderr)
        return 2
    snapshot = read_metrics(args.trace_dir)
    mesh = read_mesh(args.trace_dir)
    summary = shards_summary(spans, snapshot, mesh)

    if args.check:
        # a 1-device fallback run still records shard=0 series, so the
        # per-shard row count must ALSO clear the multi-device bar
        multi = ((mesh or {}).get("device_count", 0) >= MIN_DEVICES
                 or len(summary["shards"]) >= MIN_DEVICES)
        if not multi:
            print(f"flink-ml-tpu-trace shards: no multi-device telemetry "
                  f"in {args.trace_dir} (mesh: "
                  f"{(mesh or {}).get('device_count', 'absent')} "
                  f"device(s), {len(summary['shards'])} per-shard "
                  "series)", file=sys.stderr)
            return 2

    with pipe_guard():
        if args.json:
            print(json.dumps(summary, indent=2, default=str))
        else:
            print(render_shards(summary))
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
