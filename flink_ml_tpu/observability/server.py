"""Embedded live-telemetry HTTP endpoint: scrape a *running* process.

Every artifact so far (spans, metrics snapshots) is read post-mortem
from a trace dir; this module serves the live half — a stdlib
``http.server`` daemon thread, env-armed by
``FLINK_ML_TPU_METRICS_PORT`` (``0`` binds an ephemeral port; read it
back from :attr:`TelemetryServer.port`), started lazily by the first
instrumented seam that runs (api/stage.py fit/transform, the servable
``_served`` wrapper).

THE route table (also :data:`ROUTE_TABLE` — the dispatch map, the 404
body and this doc all render from one definition, so they cannot
drift):

================  ==========================================  =============================
route             serves                                      response with no data
================  ==========================================  =============================
``/metrics``      process registry, Prometheus text           empty exposition (0 families)
                  exposition (cumulative histograms — any
                  scraper computes its own windows)
``/healthz``      liveness + readiness JSON (status, pid,     200 ``{"status": "ok"}`` —
                  uptime); 503 + per-gate reasons while any   no gates registered means
                  readiness gate is unready (serving          ready
                  warmup registers one, serving/warmup.py)
``/slo``          live SLO verdicts (observability/slo.py)    200, verdicts evaluate over
                  over the registry's *windowed* metrics;     empty windows (every
                  violations emit events/counters on every    objective ``ok`` with 0
                  evaluation — scraping doubles as the        samples)
                  burn-rate alerter
``/serving``      the serving runtime's live status (queue    200 ``{"serving": null}`` —
                  depth, bucket table, active model version)  no runtime registered a
                  from the registered provider                provider (serving/batcher.py)
                  (serving/batcher.py)
``/drift``        live drift verdicts                         200 with an empty
                  (observability/drift.py): PSI/JS/KS per     ``servables`` map — nothing
                  servable series vs the installed            sketched yet; a servable
                  training-time baselines; evaluating emits   without a baseline reports
                  the events/gauges, so scraping doubles as   ``source: "missing"``
                  the drift alerter
``/quality``      live continuous-evaluation verdicts         200 with an empty
                  (observability/evaluation.py): AUC/logloss/ ``servables`` map — no
                  calibration from feedback-joined windows    feedback joined yet; a thin
                  vs the installed quality baselines;         window is insufficient
                  evaluating emits the events/gauges, so      evidence; no baseline →
                  scraping doubles as the quality alerter     ``source: "missing"``
``/controller``   the ops controller's live state             200 ``{"controller": null}``
                  (serving/controller.py): state machine      — no controller registered
                  position, cycle, canary version/fraction,   a provider
                  cycle outcomes, recent transitions
``/incidents``    the flight recorder's incident bundles      200 with an empty
                  (observability/flightrecorder.py) under     ``incidents`` list — nothing
                  the armed trace dir, plus the span-ring     recorded, or no trace dir
                  ``dropped_spans`` truncation count          armed
``/spans/recent`` the tracer's in-memory ring of recently     200 ``{"spans": []}``
                  closed spans (tracing.RECENT_SPANS;
                  arming the endpoint flips
                  ``tracer.keep_recent`` so request-scoped
                  spans exist even without a trace dir)
``/fleet``        the live fleet report                        200 ``{"fleet": null}`` —
                  (observability/fleet.py): membership with    no fleet dir resolves, or
                  alive/stale/dead classification, bin-exact   no member wrote a beacon
                  windowed fleet quantiles folded across       yet
                  member beacons, per-replica load rows
``/profilez``     on-demand bounded device profile             409 — capture killed
                  (observability/profiling.py): ``?ms=250``    (``FLINK_ML_TPU_PROFILE_``
                  captures a window (clamped to                ``CAPTURE=0``), another
                  ``FLINK_ML_TPU_PROFILEZ_MAX_MS``), answers   trace already active, or
                  with the parsed per-op/per-fn attribution;   not the driver process
                  one at a time, driver only
================  ==========================================  =============================

Any other path: 404 JSON naming the known routes.

**Driver-only.** Host-pool children (common/hostpool.py) never listen:
:func:`maybe_start` refuses in any pid other than the one that imported
this module, and the fork reseed (:func:`reseed_child`) closes the
inherited listener fd and pins the module shut — children keep shipping
metric snapshots through the existing merge path instead. Binding
failures are logged once and latch the module off; telemetry must never
take the serving process down.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import metrics
from flink_ml_tpu.observability import tracing

__all__ = ["METRICS_PORT_ENV", "METRICS_HOST_ENV", "ROUTE_TABLE",
           "ROUTES", "TelemetryServer",
           "maybe_start", "stop", "reseed_child", "set_gate",
           "clear_gate", "readiness", "set_serving_status",
           "get_serving_status", "clear_serving_status",
           "set_controller_status", "get_controller_status",
           "clear_controller_status"]

#: env var holding the port to serve on; unset → no endpoint, ``0`` →
#: an ephemeral port (tests, the serve smoke)
METRICS_PORT_ENV = "FLINK_ML_TPU_METRICS_PORT"
#: bind address (default loopback — a sidecar scraper; widen explicitly)
METRICS_HOST_ENV = "FLINK_ML_TPU_METRICS_HOST"

#: route → (handler method name on _Handler, no-data response note) —
#: the ONE definition the dispatch, the 404 body and the module
#: docstring's table derive from
ROUTE_TABLE = {
    "/metrics": ("_route_metrics",
                 "empty Prometheus exposition (0 families)"),
    "/healthz": ("_route_healthz",
                 '200 {"status": "ok"} — no gates registered'),
    "/slo": ("_route_slo",
             "200, every objective ok with 0 samples"),
    "/serving": ("_route_serving",
                 '200 {"serving": null} — no runtime provider'),
    "/drift": ("_route_drift",
               '200 with an empty "servables" map; no baseline → '
               'source: "missing"'),
    "/quality": ("_route_quality",
                 '200 with an empty "servables" map; no joined '
                 'feedback → thin; no baseline → source: "missing"'),
    "/controller": ("_route_controller",
                    '200 {"controller": null} — no ops controller '
                    'registered a provider (serving/controller.py)'),
    "/incidents": ("_route_incidents",
                   '200 with an empty "incidents" list — the flight '
                   'recorder (observability/flightrecorder.py) has '
                   'dumped no bundle, or no trace dir is armed'),
    "/spans/recent": ("_route_spans_recent", '200 {"spans": []}'),
    "/fleet": ("_route_fleet",
               '200 {"fleet": null} — no fleet dir resolves '
               '(observability/fleet.py) or no beacons written yet'),
    "/profilez": ("_route_profilez",
                  "409 — capture killed, another trace active, or not "
                  "the driver process (observability/profiling.py)"),
}

ROUTES = tuple(ROUTE_TABLE)

_PROM_CTYPE = "text/plain; version=0.0.4; charset=utf-8"
_JSON_CTYPE = "application/json"

_log = logging.getLogger(__name__)

_lock = make_lock("observability.server")
_FAILED = object()   # latched off: bad port / bind failure / forked child
_server = None       # None | TelemetryServer | _FAILED
_owner_pid = os.getpid()
_t0 = time.monotonic()

# -- readiness gates (liveness vs readiness split) ----------------------------
# ``/healthz`` stays the liveness probe (the process answers); readiness
# is gated: a registered gate that is not yet ready flips /healthz to
# 503 with a JSON reason — how serving warmup (serving/warmup.py) keeps
# a load balancer from routing traffic at a cold compile cache. With no
# gates registered (every plain fit/serve process) /healthz is 200, as
# before.
_gates: dict = {}
_gates_lock = make_lock("observability.server.gates")

# ``/serving`` status provider: the serving runtime (serving/batcher.py)
# registers a zero-arg callable returning its live status dict (queue
# depth, bucket table, active model version); None → route answers with
# ``{"serving": null}``.
_serving_status = None

# ``/controller`` status provider: the ops controller
# (serving/controller.py) registers a zero-arg callable returning its
# live state dict (state machine position, cycle, canary, outcomes);
# None → route answers with ``{"controller": null}``.
_controller_status = None


def set_gate(name: str, ready: bool, reason: str = "") -> None:
    """Register/update a readiness gate. ``/healthz`` reports 503 until
    every registered gate is ready."""
    with _gates_lock:
        _gates[name] = (bool(ready), str(reason))


def clear_gate(name: str) -> None:
    with _gates_lock:
        _gates.pop(name, None)


def readiness() -> tuple:
    """(ready, {gate: reason}) — the unready gates and their reasons."""
    with _gates_lock:
        blocked = {n: reason for n, (ok, reason) in _gates.items()
                   if not ok}
    return (not blocked, blocked)


def set_serving_status(provider) -> None:
    """Register the ``/serving`` route's status provider (a zero-arg
    callable returning a JSON-serializable dict), or None to unregister."""
    global _serving_status
    _serving_status = provider


def get_serving_status():
    """The currently registered ``/serving`` provider (or None) — a
    runtime snapshots it at start so its stop can restore it."""
    return _serving_status


def clear_serving_status(provider=None, restore=None) -> None:
    """Unregister the ``/serving`` provider — with ``provider`` given,
    only if it is still the registered one (a runtime stopping must not
    clobber a later runtime's registration), re-installing ``restore``
    (the provider that was registered when ``provider`` took over, so a
    short-lived runtime hands the route back)."""
    global _serving_status
    if provider is None or _serving_status == provider:
        _serving_status = restore


def set_controller_status(provider) -> None:
    """Register the ``/controller`` route's status provider (a zero-arg
    callable returning a JSON-serializable dict), or None to
    unregister."""
    global _controller_status
    _controller_status = provider


def get_controller_status():
    """The currently registered ``/controller`` provider (or None)."""
    return _controller_status


def clear_controller_status(provider=None) -> None:
    """Unregister the ``/controller`` provider — with ``provider``
    given, only if it is still the registered one (the /serving
    contract: a stopping controller must not clobber a later one)."""
    global _controller_status
    if provider is None or _controller_status == provider:
        _controller_status = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "flink-ml-tpu-telemetry"

    def log_message(self, fmt, *args):  # stdout silence: debug log only
        _log.debug("telemetry: " + fmt, *args)

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # -- one method per ROUTE_TABLE row --------------------------------------
    def _route_metrics(self) -> None:
        from flink_ml_tpu.observability.exporters import (
            prometheus_text,
        )

        self._send(200, prometheus_text(metrics.snapshot()),
                   _PROM_CTYPE)

    def _route_healthz(self) -> None:
        ready, blocked = readiness()
        body = {"status": "ok" if ready else "unready",
                "pid": os.getpid(),
                "uptime_s": round(time.monotonic() - _t0, 3),
                "tracing": tracing.tracer.enabled}
        if not ready:
            # 503: the readiness half of the probe — alive but not yet
            # fit to take traffic (e.g. serving warmup still compiling
            # bucket shapes)
            body["reasons"] = blocked
        self._send(200 if ready else 503, json.dumps(body),
                   _JSON_CTYPE)

    def _route_slo(self) -> None:
        from flink_ml_tpu.observability import slo

        verdicts = slo.evaluate_slos(slo.active_slos(), emit=True)
        self._send(200, json.dumps(
            {"source": "windowed", "verdicts": verdicts,
             "violated": [v["slo"] for v in verdicts
                          if not v["ok"]]},
            default=str), _JSON_CTYPE)

    def _route_serving(self) -> None:
        provider = _serving_status
        status = provider() if provider is not None else None
        self._send(200, json.dumps({"serving": status},
                                   default=str), _JSON_CTYPE)

    def _route_drift(self) -> None:
        from flink_ml_tpu.observability import drift
        from flink_ml_tpu.observability.health import _json_safe

        # emit=True: scraping doubles as the drift alerter, exactly
        # like /slo — the verdict gauges/events land on every scrape.
        # _json_safe: never-observed series carry NaN stats, and the
        # bare NaN token is unparseable strict JSON
        self._send(200, json.dumps(
            _json_safe(drift.drift_report(emit=True)),
            default=str), _JSON_CTYPE)

    def _route_quality(self) -> None:
        from flink_ml_tpu.observability import evaluation
        from flink_ml_tpu.observability.health import _json_safe

        # emit=True: scraping doubles as the quality alerter, exactly
        # like /drift — verdict gauges/events land on every scrape.
        # _json_safe: an empty joined window carries NaN AUC, and the
        # bare NaN token is unparseable strict JSON
        self._send(200, json.dumps(
            _json_safe(evaluation.quality_report(emit=True)),
            default=str), _JSON_CTYPE)

    def _route_controller(self) -> None:
        from flink_ml_tpu.observability.health import _json_safe

        provider = _controller_status
        status = provider() if provider is not None else None
        self._send(200, json.dumps(_json_safe({"controller": status}),
                                   default=str), _JSON_CTYPE)

    def _route_incidents(self) -> None:
        from flink_ml_tpu.observability import flightrecorder

        trace_dir = tracing.tracer.trace_dir
        # include_spans=False: a polling monitor must not re-parse
        # every bundle's span evidence per scrape; the meta's own
        # "spans" count says how much each bundle holds
        rows = (flightrecorder.read_incidents(trace_dir,
                                              include_spans=False)
                if trace_dir else [])
        slim = [{k: v for k, v in r.items() if k != "recent_spans"}
                for r in rows]
        self._send(200, json.dumps(
            {"trace_dir": trace_dir, "incidents": slim,
             "dropped_spans": tracing.tracer.mirror_dropped()},
            default=str), _JSON_CTYPE)

    def _route_spans_recent(self) -> None:
        # deque.append is thread-safe but ITERATION is not: serving
        # threads ring spans concurrently, and a mid-iteration append
        # raises RuntimeError — retry
        spans = []
        for _ in range(8):
            try:
                spans = list(tracing.tracer.recent)
                break
            except RuntimeError:
                continue
        self._send(200, json.dumps({"spans": spans},
                                   default=str), _JSON_CTYPE)

    def _route_fleet(self) -> None:
        from flink_ml_tpu.observability import fleet
        from flink_ml_tpu.observability.health import _json_safe

        base = fleet.fleet_dir()
        resolved = fleet.find_fleet_dir(base) if base else None
        if resolved is None:
            self._send(200, json.dumps({"fleet": None,
                                        "fleetDir": base}),
                       _JSON_CTYPE)
            return
        view = fleet.FleetView(resolved)
        self._send(200, json.dumps(
            _json_safe({"fleet": view.report()}), default=str),
            _JSON_CTYPE)

    def _route_profilez(self) -> None:
        # on-demand device profile: /profilez?ms=250 captures a bounded
        # window (clamped to FLINK_ML_TPU_PROFILEZ_MAX_MS) and answers
        # with the parsed attribution. One at a time, driver only —
        # profiling.capture_now refuses (→ 409) rather than queue: a
        # scraper must never stack blocking capture windows.
        from urllib.parse import parse_qs, urlsplit

        from flink_ml_tpu.observability import profiling

        query = parse_qs(urlsplit(self.path).query)
        try:
            ms = int(query.get("ms", ["200"])[0])
            if ms <= 0:
                raise ValueError(ms)
        except (TypeError, ValueError):
            self._send(400, json.dumps(
                {"error": "ms must be a positive integer",
                 "example": "/profilez?ms=250"}), _JSON_CTYPE)
            return
        result = profiling.capture_now(ms)
        if result is None:
            self._send(409, json.dumps(
                {"error": "capture refused: disabled "
                          f"({profiling.CAPTURE_ENV}=0), another trace "
                          "active, or not the driver process"}),
                _JSON_CTYPE)
            return
        self._send(200, json.dumps(result, default=str), _JSON_CTYPE)

    def do_GET(self):  # noqa: N802 — http.server's casing
        path = self.path.split("?", 1)[0]
        if path != "/" and path.endswith("/"):
            path = path.rstrip("/")
        try:
            row = ROUTE_TABLE.get(path)
            if row is not None:
                getattr(self, row[0])()
            else:
                self._send(404, json.dumps(
                    {"error": f"no route {path!r}",
                     "routes": list(ROUTES)}), _JSON_CTYPE)
        except (BrokenPipeError, ConnectionError):
            pass  # scraper went away mid-write: not our problem
        except Exception as e:  # noqa: BLE001 — a route bug must never
            # take the serving process down; report it to the scraper
            _log.warning("telemetry route %s failed", path,
                         exc_info=True)
            try:
                self._send(500, json.dumps({"error": repr(e)}),
                           _JSON_CTYPE)
            except OSError:
                pass


class TelemetryServer:
    """The endpoint: a ThreadingHTTPServer on a daemon thread. Port 0
    resolves to the bound ephemeral port."""

    def __init__(self, port: int, host: Optional[str] = None):
        if host is None:
            host = os.environ.get(METRICS_HOST_ENV, "127.0.0.1")
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="flink-ml-tpu-telemetry", daemon=True)

    def start(self) -> None:
        self.thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def maybe_start(port: Optional[int] = None) -> Optional[TelemetryServer]:
    """Start the endpoint once per driver process when armed; return it
    (or None when unarmed/latched off). ``port=None`` reads
    ``FLINK_ML_TPU_METRICS_PORT``; instrumented seams call this on
    every entry, so the unarmed fast path is one dict lookup."""
    global _server
    if _server is not None:
        return _server if isinstance(_server, TelemetryServer) else None
    if port is None:
        raw = os.environ.get(METRICS_PORT_ENV)
        if not raw:
            return None
        try:
            port = int(raw)
        except ValueError:
            _log.warning("invalid %s=%r: telemetry endpoint disabled",
                         METRICS_PORT_ENV, raw)
            with _lock:
                if _server is None:
                    _server = _FAILED
            return None
    if os.getpid() != _owner_pid:
        return None  # forked child: driver-only by contract
    with _lock:
        if _server is None:
            try:
                srv = TelemetryServer(int(port))
                srv.start()
            except (OSError, OverflowError, ValueError) as e:
                # OverflowError: port outside 0-65535; the seams call
                # maybe_start unguarded, so ANY failure must latch the
                # endpoint off instead of re-raising on every fit
                _log.warning("telemetry endpoint failed to bind port "
                             "%s: %s", port, e)
                _server = _FAILED
                return None
            # request-scoped spans must exist for /spans/recent even
            # when no trace dir is armed
            tracing.tracer.keep_recent = True
            _server = srv
            _log.info("telemetry endpoint listening on %s:%d",
                      srv.host, srv.port)
    return _server if isinstance(_server, TelemetryServer) else None


def stop() -> None:
    """Shut the endpoint down and disarm the span ring (tests; also
    un-latches a failed start so a new port can be tried). Readiness
    gates and the /serving provider reset too — they belong to the
    runtime that registered them, which is gone."""
    global _server, _serving_status, _controller_status
    with _lock:
        srv, _server = _server, None
    if isinstance(srv, TelemetryServer):
        srv.stop()
    tracing.tracer.keep_recent = False
    with _gates_lock:
        _gates.clear()
    _serving_status = None
    _controller_status = None


def reseed_child() -> None:
    """Called in a freshly forked host-pool child: close the inherited
    listener fd (the parent keeps serving on its own copy) and latch
    this process's endpoint shut — children never listen."""
    global _server, _owner_pid
    _owner_pid = -1
    srv, _server = _server, _FAILED
    if isinstance(srv, TelemetryServer):
        try:
            srv.httpd.socket.close()
        except OSError:
            pass
