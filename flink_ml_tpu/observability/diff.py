"""``mltrace diff``: compare two trace dirs (or metrics snapshots) and
gate perf regressions from artifacts alone.

A trace dir is the ``FLINK_ML_TPU_TRACE_DIR`` artifact set
(``spans-*.jsonl`` + ``metrics-*.json``); a side may also be a single
registry-snapshot JSON file (``observability.dump_metrics`` output, or a
benchmark results file reduced to a snapshot). The diff reports:

- **per-span-name self-time deltas** (span duration minus direct
  children, aggregated by name — where work actually happened),
- **histogram-quantile deltas** (q50/q90/q99 of every registry
  histogram, labeled series kept apart),
- **compile-count deltas** (the ``ml.compile`` counters, plus the
  backend_compile total `compilestats` aggregates),
- **per-phase compile-TIME deltas** (the ``ml.compile
  phaseMs{phase=...}`` histograms: count and summed ms per monitoring
  phase), so a gate trip distinguishes "B compiles MORE" from "B's
  compiles got SLOWER" — two different regressions with two different
  fixes,
- **per-fn efficiency rows** (when a ``profile.json`` device-profile
  artifact sits beside a side's artifacts —
  observability/profiling.py): measured device ms and roofline
  utilization per jitted fn, so "slower because lower utilization"
  reads apart from "slower because more work". Reported, not gated —
  the efficiency floor lives in ``mltrace efficiency --check``.

``--budget <pct>`` turns the report into a regression gate: exit
:data:`EXIT_BUDGET` (4) when side B regresses side A beyond the budget.
Gated: per-span-name self-time (with a ``--min-ms`` absolute noise
floor, default 5 ms — wall clocks jitter, sub-floor deltas never gate)
and the total compile count (floor: +2 compiles). Histogram quantiles
are reported but not gated — two honest runs jitter there by design.
Exit codes: 0 within budget / no budget given, 2 unreadable or empty
side, 4 budget exceeded — distinct so CI and the unattended TPU sweep
can tell "regressed" from "broken artifacts".
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional

from flink_ml_tpu.common.metrics import histogram_quantile
from flink_ml_tpu.observability.compilestats import (
    compile_totals_from_snapshot,
)
from flink_ml_tpu.observability.exporters import read_metrics, read_spans

EXIT_OK = 0
EXIT_INVALID = 2
#: the documented budget exit code (docs/observability.md)
EXIT_BUDGET = 4

QUANTILES = (0.5, 0.9, 0.99)

#: default absolute self-time noise floor (ms) under which no span-level
#: delta can gate, whatever its percentage
DEFAULT_MIN_MS = 5.0

#: compile-count gate floor: B must add at least this many compiles over
#: A before the percentage budget can fire (one stray compile is noise)
COMPILE_COUNT_FLOOR = 2


# -- span aggregation (shared with cli.summarize) -----------------------------
def aggregate_self_time(spans: List[dict]) -> Dict[str, dict]:
    """``name → {count, total_us, self_us}`` where self-time is a span's
    duration minus its direct children's — the quantity worth diffing
    (total time double-counts every level of nesting)."""
    by_id = {sp["id"]: sp for sp in spans if sp.get("id")}
    child_dur: Dict[str, int] = {}
    for sp in spans:
        parent = sp.get("parent")
        if parent in by_id:
            child_dur[parent] = (child_dur.get(parent, 0)
                                 + (sp.get("dur_us") or 0))
    agg: Dict[str, dict] = {}
    for sp in spans:
        dur = sp.get("dur_us") or 0
        row = agg.setdefault(sp.get("name", "?"),
                             {"count": 0, "total_us": 0, "self_us": 0})
        row["count"] += 1
        row["total_us"] += dur
        row["self_us"] += max(0, dur - child_dur.get(sp.get("id"), 0))
    return agg


# -- side loading -------------------------------------------------------------
def load_side(path: str) -> dict:
    """One diff side: a trace directory, or a metrics-snapshot JSON
    file. Raises ValueError when the side holds no readable artifact —
    an empty side must be EXIT_INVALID, never a vacuous 'no regression'."""
    if os.path.isdir(path):
        spans = read_spans(path)
        snap = read_metrics(path)
        if not spans and not snap:
            raise ValueError(
                f"{path}: no spans-*.jsonl or metrics-*.json artifacts")
        # per-fn efficiency rides along when a profile.json sits beside
        # the artifacts (observability/profiling.py) — so the diff can
        # tell "slower because lower utilization" from "slower because
        # more work". Best-effort: most sides have no profile
        eff: Dict[str, dict] = {}
        try:
            from flink_ml_tpu.observability import profiling

            report = profiling.efficiency_report(path, snapshot=snap)
            eff = {row["fn"]: row for row in report["fns"]}
        except Exception:  # noqa: BLE001 — optional evidence
            pass
        return {"spans": aggregate_self_time(spans), "metrics": snap,
                "efficiency": eff}
    with open(path, "r", encoding="utf-8") as f:
        snap = json.load(f)
    if not isinstance(snap, dict) or not snap:
        raise ValueError(f"{path}: not a metrics snapshot")
    return {"spans": {}, "metrics": snap, "efficiency": {}}


# -- delta computation --------------------------------------------------------
_PHASE_KEY = re.compile(r'^phaseMs\{phase="((?:[^"\\]|\\.)*)"\}$')


def _phase_totals(snap: Optional[dict]) -> Dict[str, dict]:
    """``phase → {count, ms}`` from a snapshot's ``ml.compile``
    ``phaseMs{phase="..."}`` histograms (count + summed ms — the
    jax.monitoring per-phase channels compilestats subscribes to)."""
    out: Dict[str, dict] = {}
    hists = ((snap or {}).get("ml.compile") or {}).get("histograms", {})
    for key, hist in hists.items():
        m = _PHASE_KEY.match(key)
        if not m:
            continue
        out[m.group(1)] = {"count": int(hist.get("count", 0)),
                           "ms": float(hist.get("sum", 0.0))}
    return out


def _pct(a: float, b: float) -> Optional[float]:
    if a <= 0:
        return None if b <= 0 else math.inf
    return (b - a) / a * 100.0


def diff_profiles(a: dict, b: dict) -> dict:
    """Structured deltas between two loaded sides (B relative to A)."""
    span_rows = []
    for name in sorted(set(a["spans"]) | set(b["spans"])):
        empty = {"count": 0, "total_us": 0, "self_us": 0}
        ra = a["spans"].get(name, empty)
        rb = b["spans"].get(name, empty)
        a_ms = ra["self_us"] / 1000.0
        b_ms = rb["self_us"] / 1000.0
        span_rows.append({"name": name,
                          "a_count": ra["count"], "b_count": rb["count"],
                          "a_self_ms": round(a_ms, 3),
                          "b_self_ms": round(b_ms, 3),
                          "delta_ms": round(b_ms - a_ms, 3),
                          "delta_pct": _pct(a_ms, b_ms)})
    span_rows.sort(key=lambda r: -abs(r["delta_ms"]))

    hist_rows = []
    ma, mb = a["metrics"] or {}, b["metrics"] or {}
    for group in sorted(set(ma) | set(mb)):
        ha = (ma.get(group) or {}).get("histograms", {})
        hb = (mb.get(group) or {}).get("histograms", {})
        for key in sorted(set(ha) | set(hb)):
            sa, sb = ha.get(key), hb.get(key)
            row = {"group": group, "key": key,
                   "a_count": int((sa or {}).get("count", 0)),
                   "b_count": int((sb or {}).get("count", 0)),
                   "quantiles": {}}
            for q in QUANTILES:
                qa = histogram_quantile(sa, q) if sa else float("nan")
                qb = histogram_quantile(sb, q) if sb else float("nan")
                row["quantiles"][f"q{int(q * 100)}"] = {
                    "a": None if math.isnan(qa) else round(qa, 3),
                    "b": None if math.isnan(qb) else round(qb, 3),
                    "delta_pct": (None if math.isnan(qa) or math.isnan(qb)
                                  else _pct(qa, qb))}
            hist_rows.append(row)

    compile_rows = []
    ca = (ma.get("ml.compile") or {}).get("counters", {})
    cb = (mb.get("ml.compile") or {}).get("counters", {})
    for key in sorted(set(ca) | set(cb)):
        va, vb = int(ca.get(key, 0)), int(cb.get(key, 0))
        compile_rows.append({"key": key, "a": va, "b": vb,
                             "delta": vb - va})
    totals_a = compile_totals_from_snapshot(ma)
    totals_b = compile_totals_from_snapshot(mb)

    # per-phase compile-time deltas (ml.compile phaseMs{phase=...}):
    # count AND summed ms per monitoring phase, so "more compiles" and
    # "slower compiles" read as distinct findings
    pa, pb = _phase_totals(ma), _phase_totals(mb)
    phase_rows = []
    for phase in sorted(set(pa) | set(pb)):
        ra = pa.get(phase, {"count": 0, "ms": 0.0})
        rb = pb.get(phase, {"count": 0, "ms": 0.0})
        phase_rows.append({
            "phase": phase,
            "a_count": ra["count"], "b_count": rb["count"],
            "a_ms": round(ra["ms"], 3), "b_ms": round(rb["ms"], 3),
            "delta_ms": round(rb["ms"] - ra["ms"], 3),
            "delta_pct": _pct(ra["ms"], rb["ms"])})
    phase_rows.sort(key=lambda r: -abs(r["delta_ms"]))

    # per-fn efficiency deltas (profile.json sides only): measured
    # device ms + roofline utilization — reported, never gated (the
    # efficiency gate is `mltrace efficiency --check`, with real floors)
    ea, eb = a.get("efficiency") or {}, b.get("efficiency") or {}
    eff_rows = []
    for fn in sorted(set(ea) | set(eb)):
        ra, rb = ea.get(fn) or {}, eb.get(fn) or {}
        eff_rows.append({
            "fn": fn,
            "a_device_ms": ra.get("deviceMs"),
            "b_device_ms": rb.get("deviceMs"),
            "a_utilization": ra.get("utilization"),
            "b_utilization": rb.get("utilization"),
            "a_achieved_flops": ra.get("achievedFlops"),
            "b_achieved_flops": rb.get("achievedFlops"),
            "bound": rb.get("bound") or ra.get("bound")})

    return {"spans": span_rows, "histograms": hist_rows,
            "compile": compile_rows,
            "compile_phases": phase_rows,
            "efficiency": eff_rows,
            "compile_totals": {"a": totals_a, "b": totals_b},
            # span gating needs span data on BOTH sides: against a
            # metrics-only side (a snapshot file, or a dir that captured
            # no spans) every B span would read as an infinite-percent
            # regression and the budget would always fire
            "spans_comparable": bool(a["spans"]) and bool(b["spans"])}


def violations(diff: dict, budget_pct: float,
               min_ms: float = DEFAULT_MIN_MS) -> List[dict]:
    """The gated regressions in ``diff`` exceeding ``budget_pct``."""
    out = []
    for row in diff["spans"] if diff.get("spans_comparable") else ():
        regress_ms = row["b_self_ms"] - row["a_self_ms"]
        if regress_ms < min_ms:
            continue
        pct = row["delta_pct"]
        if pct is not None and pct > budget_pct:
            out.append({"kind": "span-self-time", "name": row["name"],
                        "a_ms": row["a_self_ms"], "b_ms": row["b_self_ms"],
                        "delta_pct": (None if math.isinf(pct)
                                      else round(pct, 1))})
    ta = diff["compile_totals"]["a"]["count"]
    tb = diff["compile_totals"]["b"]["count"]
    if tb - ta >= COMPILE_COUNT_FLOOR:
        pct = _pct(float(ta), float(tb))
        if pct is not None and pct > budget_pct:
            out.append({"kind": "compile-count", "name": "backend compiles",
                        "a": ta, "b": tb,
                        "delta_pct": (None if math.isinf(pct)
                                      else round(pct, 1))})
    return out


# -- rendering ----------------------------------------------------------------
def _fmt_pct(pct: Optional[float]) -> str:
    if pct is None:
        return "  —   "
    if math.isinf(pct):
        return "  new "
    return f"{pct:+7.1f}%"


def render_diff(diff: dict, viol: List[dict], top_n: int = 15) -> str:
    out = ["span self-time deltas (B vs A):",
           f"  {'name':<32} {'A ms':>10} {'B ms':>10} {'delta':>10} "
           f"{'pct':>8}"]
    for row in diff["spans"][:top_n]:
        out.append(f"  {row['name']:<32} {row['a_self_ms']:>10.3f} "
                   f"{row['b_self_ms']:>10.3f} {row['delta_ms']:>+10.3f} "
                   f"{_fmt_pct(row['delta_pct'])}")
    if not diff["spans"]:
        out.append("  (no spans on either side)")
    elif not diff.get("spans_comparable"):
        out.append("  (one side has no span data — self-time deltas "
                   "reported but not gated)")

    hists = [r for r in diff["histograms"]
             if r["a_count"] or r["b_count"]]
    if hists:
        out.append("")
        out.append("histogram quantile deltas (reported, not gated):")
        for row in hists[:top_n]:
            qs = "  ".join(
                f"{q}: {v['a']}→{v['b']}"
                for q, v in row["quantiles"].items()
                if v["a"] is not None or v["b"] is not None)
            out.append(f"  {row['group']}:{row['key']}  "
                       f"count {row['a_count']}→{row['b_count']}  {qs}")

    ct = diff["compile_totals"]
    out.append("")
    out.append(f"compile totals: count {ct['a']['count']}→"
               f"{ct['b']['count']}, time "
               f"{ct['a']['timeMs']:.1f}→{ct['b']['timeMs']:.1f} ms")
    for row in diff["compile"][:top_n]:
        if row["delta"]:
            out.append(f"  {row['key']}: {row['a']}→{row['b']} "
                       f"({row['delta']:+d})")
    phases = [r for r in diff.get("compile_phases", ())
              if r["a_count"] or r["b_count"]]
    if phases:
        out.append("per-phase compile time (count / ms — 'more compiles'"
                   " vs 'slower compiles'):")
        for row in phases[:top_n]:
            out.append(
                f"  {row['phase']}: {row['a_count']}→{row['b_count']} "
                f"compiles, {row['a_ms']:.1f}→{row['b_ms']:.1f} ms "
                f"({row['delta_ms']:+.1f} ms, "
                f"{_fmt_pct(row['delta_pct']).strip()})")

    effs = diff.get("efficiency") or ()
    if effs:
        out.append("")
        out.append("per-fn efficiency (measured device ms / roofline "
                   "utilization — reported, not gated):")
        for row in effs[:top_n]:
            ua, ub = row["a_utilization"], row["b_utilization"]
            out.append(
                "  {}: deviceMs {}→{}  util {}→{}  bound={}".format(
                    row["fn"],
                    "—" if row["a_device_ms"] is None
                    else f"{row['a_device_ms']:.3f}",
                    "—" if row["b_device_ms"] is None
                    else f"{row['b_device_ms']:.3f}",
                    "—" if ua is None else f"{ua * 100.0:.1f}%",
                    "—" if ub is None else f"{ub * 100.0:.1f}%",
                    row["bound"] or "—"))

    if viol:
        out.append("")
        out.append("BUDGET EXCEEDED:")
        for v in viol:
            out.append(f"  {v['kind']}: {v['name']}  "
                       + " ".join(f"{k}={val}" for k, val in v.items()
                                  if k not in ("kind", "name")))
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace diff",
        description="Diff two trace dirs / metrics snapshots; with "
                    "--budget, gate regressions (exit 4).")
    parser.add_argument("a", help="baseline: trace dir or metrics JSON")
    parser.add_argument("b", help="candidate: trace dir or metrics JSON")
    parser.add_argument("--budget", type=float, default=None, metavar="PCT",
                        help="fail (exit 4) when B regresses A beyond "
                             "PCT%% on a gated quantity")
    parser.add_argument("--min-ms", type=float, default=DEFAULT_MIN_MS,
                        help="absolute span self-time delta (ms) below "
                             "which the budget never fires "
                             f"(default {DEFAULT_MIN_MS})")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--top", type=int, default=15,
                        help="rows per section in text output")
    parser.add_argument("--latest", action="store_true",
                        help="treat each directory side as a root and "
                             "pick the newest trace dir under it "
                             "(snapshot-file sides pass through)")
    args = parser.parse_args(argv)

    try:
        from flink_ml_tpu.observability.exporters import (
            resolve_trace_dir,
        )

        if args.latest:
            if os.path.isdir(args.a):
                args.a = resolve_trace_dir(args.a, True)
            if os.path.isdir(args.b):
                args.b = resolve_trace_dir(args.b, True)
        side_a = load_side(args.a)
        side_b = load_side(args.b)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"mltrace diff: {e}", file=sys.stderr)
        return EXIT_INVALID

    diff = diff_profiles(side_a, side_b)
    viol = (violations(diff, args.budget, args.min_ms)
            if args.budget is not None else [])

    from flink_ml_tpu.observability.exporters import pipe_guard

    with pipe_guard():  # a closed `| head` pipe must not mask the gate
        if args.format == "json":
            print(json.dumps({"diff": diff, "violations": viol,
                              "budget_pct": args.budget}, indent=2,
                             default=str))
        else:
            print(render_diff(diff, viol, top_n=args.top))
    return EXIT_BUDGET if viol else EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
