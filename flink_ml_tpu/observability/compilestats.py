"""Compile & device telemetry: XLA compile visibility + HBM/FLOP accounting.

Two quantities govern TPU performance that the span tracer cannot see:
how often and how long XLA compiles (and why it recompiles), and how
hard the compiled programs drive the device (FLOPs, bytes, HBM
watermarks). This module makes both first-class registry metrics and
tracer events, so they land in the same trace-dir artifacts as spans and
epoch histograms (docs/observability.md) and survive unattended runs:

- **Compile telemetry.** :func:`install` subscribes to the
  ``jax.monitoring`` duration/event channels when this jax build exposes
  them, recording per-phase compile-time histograms
  (``ml.compile phaseMs{phase="backend_compile"|...}``) and channel
  counters. The monitoring channels carry no function identity, so
  :func:`instrumented_jit` / :func:`aot_compile` add the per-function
  view: compile counts and compile-time histograms labeled by function
  name, plus a **recompile-storm detector** — one function compiled for
  more than N distinct abstract signatures within one fit window fires a
  ``compile.storm`` event and counter, the dynamic complement of
  jaxlint's static recompile-hazard rule.

- **Device telemetry.** :func:`capture_cost` records
  ``compiled.cost_analysis()`` FLOPs / bytes-accessed on first compile
  (``ml.device programFlops{fn=...}``); :func:`sample_memory` samples
  ``device.memory_stats()`` watermarks at epoch boundaries and root-span
  close. On CPU ``memory_stats()`` returns ``None`` — sampling degrades
  silently to a no-op (and remembers, so a traced CPU fit pays one probe
  total, not one per epoch). It also never *initializes* a backend: a
  pure-host fit must not open the TPU tunnel just for telemetry.

``mltrace diff`` (observability/diff.py) joins these artifacts with span
durations to report compile-count deltas and gate perf regressions from
artifacts alone.
"""

from __future__ import annotations

import contextlib
import functools
import os
import sys
import threading
import time
from typing import Dict, Optional, Set

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import ML_GROUP, MetricsRegistry, metrics
from flink_ml_tpu.observability import tracing

#: registry subgroup names: ml.compile / ml.device
COMPILE_GROUP = "compile"
DEVICE_GROUP = "device"

#: env var: distinct abstract signatures one function may compile for
#: within one fit window before the recompile-storm detector fires
STORM_ENV = "FLINK_ML_TPU_COMPILE_STORM_N"
DEFAULT_STORM_THRESHOLD = 8

#: compile-time histogram buckets (ms) — compiles are slower-tailed than
#: the latency-shaped DEFAULT_BUCKETS (a cold TPU compile can take minutes)
COMPILE_BUCKETS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0, 2500.0, 5000.0, 15000.0, 60000.0, 300000.0)


def storm_threshold() -> int:
    try:
        return int(os.environ.get(STORM_ENV, DEFAULT_STORM_THRESHOLD))
    except ValueError:
        return DEFAULT_STORM_THRESHOLD


def _channel_tail(channel: str) -> str:
    """``/jax/core/compile/backend_compile_duration`` → ``backend_compile``."""
    tail = channel.rstrip("/").rsplit("/", 1)[-1]
    if tail.endswith("_duration"):
        tail = tail[: -len("_duration")]
    return tail


def _backend_ready() -> bool:
    """True when jax is imported AND a backend is already live — the
    guard that keeps telemetry from *initializing* a backend (on a
    wedged relay tunnel, backend init can hang for minutes; bench.py's
    orchestrator is built around never triggering it)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge
    except ImportError:
        return True  # cannot tell on this jax: assume the caller knows
    backends = getattr(xla_bridge, "_backends", None)
    if backends is None:
        return True
    return bool(backends)


class CompileStats:
    """Process-wide compile/device telemetry state (see module doc).

    Thread-safe; survives the host-pool fork like the tracer does — the
    monitoring listeners registered pre-fork keep firing in the child
    and write into the child's re-seeded registry, which ships its
    snapshot back to the driver (common/hostpool.py)."""

    def __init__(self, registry: MetricsRegistry = metrics):
        self._registry = registry
        self._lock = make_lock("observability.compilestats")
        self._installed = False
        self._enabled = False
        self._sigs: Dict[str, Set] = {}
        self._window_base: Dict[str, int] = {}
        self._window_depth = 0
        self._storm_fired: Set[str] = set()
        self._memory_unavailable = False

    # -- jax.monitoring subscription -----------------------------------------
    def install(self) -> bool:
        """Subscribe to the jax.monitoring compile channels (idempotent —
        every traced fit calls this). Returns True when the channels are
        available and subscribed; False on jax builds without them (the
        per-function instrumentation still works there)."""
        with self._lock:
            self._enabled = True
            if self._installed:
                return True
            try:
                from jax import monitoring
                register_dur = monitoring.register_event_duration_secs_listener
                register_ev = monitoring.register_event_listener
            except (ImportError, AttributeError):
                return False
            register_dur(self._on_duration)
            register_ev(self._on_event)
            self._installed = True
            return True

    def uninstall(self) -> None:
        """Disarm the monitoring listeners. jax has no public
        unregister, so they stay subscribed but become no-ops."""
        with self._lock:
            self._enabled = False

    def _on_duration(self, event: str, duration_secs: float, **kw) -> None:
        if not self._enabled:  # jaxlint: disable=unguarded-shared-state -- lock-free bool fast path on the per-compile listener; a stale read delays disarm by one event
            return
        try:
            phase = _channel_tail(event)
            ms = float(duration_secs) * 1000.0
            grp = self._registry.group(ML_GROUP, COMPILE_GROUP)
            grp.histogram("phaseMs", buckets=COMPILE_BUCKETS,
                          labels={"phase": phase}).observe(ms)
            grp.counter("phases", labels={"phase": phase})
            if phase == "backend_compile":
                tracing.tracer.event("compile.backend", ms=round(ms, 3))
        except Exception:  # a telemetry listener must never sink a compile
            pass

    def _on_event(self, event: str, **kw) -> None:
        if not self._enabled:  # jaxlint: disable=unguarded-shared-state -- lock-free bool fast path on the per-compile listener; a stale read delays disarm by one event
            return
        try:
            channel = event.removeprefix("/jax/")
            self._registry.group(ML_GROUP, COMPILE_GROUP).counter(
                "events", labels={"channel": channel})
        except Exception:
            pass

    # -- per-function compile accounting -------------------------------------
    def note_compile(self, name: str, ms: float, sig=None,
                     approx: bool = False) -> None:
        """Record one compile of ``name``: counter + compile-time
        histogram labeled by function name, a tracer instant event, and
        (when ``sig`` is given) a distinct-signature sample for the
        storm detector. ``approx`` marks a first-call wall time standing
        in for an exact lower+compile measurement."""
        grp = self._registry.group(ML_GROUP, COMPILE_GROUP)
        grp.counter("compiles", labels={"fn": name})
        grp.histogram("compileMs", buckets=COMPILE_BUCKETS,
                      labels={"fn": name}).observe(ms)
        attrs = {"fn": name, "ms": round(ms, 3)}
        if approx:
            attrs["approx"] = "call"
        tracing.tracer.event("compile", **attrs)
        if sig is not None:
            self._note_signature(name, sig)

    def _note_signature(self, name: str, sig) -> None:
        with self._lock:
            seen = self._sigs.setdefault(name, set())
            if sig in seen:
                return
            seen.add(sig)
            distinct = len(seen) - self._window_base.get(name, 0)
            threshold = storm_threshold()
            storm = distinct > threshold and name not in self._storm_fired
            if storm:
                self._storm_fired.add(name)
        if storm:
            self._registry.group(ML_GROUP, COMPILE_GROUP).counter(
                "storms", labels={"fn": name})
            tracing.tracer.event("compile.storm", fn=name,
                                 signatures=distinct, threshold=threshold)

    @contextlib.contextmanager
    def fit_window(self):
        """Scope for the recompile-storm detector: distinct-signature
        counts rebase at the OUTERMOST window (one fit), so a long-lived
        process doesn't accumulate a slow drip of shapes into a false
        storm. With no window open, the window is the process lifetime.
        Re-entrant — nested stages (a Pipeline's members) share the
        outer fit's window."""
        with self._lock:
            self._window_depth += 1
            if self._window_depth == 1:
                self._window_base = {n: len(s)
                                     for n, s in self._sigs.items()}
                self._storm_fired = set()
        try:
            yield self
        finally:
            with self._lock:
                self._window_depth -= 1

    # -- test/embedding hook -------------------------------------------------
    def reset(self) -> None:
        """Forget signature history, fired storms, and the memory-probe
        verdict (tests; embedding across backend changes)."""
        with self._lock:
            self._sigs = {}
            self._window_base = {}
            self._storm_fired = set()
            self._memory_unavailable = False


#: default process-wide telemetry state
compile_stats = CompileStats()


def install() -> bool:
    """Module-level convenience: :meth:`CompileStats.install`."""
    return compile_stats.install()


def uninstall() -> None:
    compile_stats.uninstall()


def fit_window():
    """Module-level convenience: :meth:`CompileStats.fit_window`."""
    return compile_stats.fit_window()


# -- abstract signatures ------------------------------------------------------
def _sig_leaf(x):
    aval = getattr(x, "aval", None)
    if aval is not None:
        return str(aval)
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    if x is None or isinstance(x, str):
        return ("static", repr(x))
    # python scalars of one type (bools included) share one weak-typed
    # executable under jit — value-sensitive signatures here would pay a
    # duplicate XLA compile per value and report phantom recompiles.
    # type() (not isinstance) keeps bool from collapsing into int.
    if isinstance(x, (bool, int, float, complex)):
        return ("py", type(x).__name__)
    return ("static", repr(x))


def abstract_signature(args, kwargs=None):
    """Hashable abstract signature of a call: tree structure + per-leaf
    (shape, dtype) — two calls with equal signatures hit one compiled
    executable; a new signature means a compile."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    return (str(treedef),) + tuple(_sig_leaf(leaf) for leaf in leaves)


# -- instrumented jit ---------------------------------------------------------
def instrumented_jit(fn=None, *, name: Optional[str] = None,
                     stats: Optional[CompileStats] = None, **jit_kwargs):
    """``jax.jit`` with compile telemetry: per-function compile counts +
    compile-time histograms (``ml.compile compiles/compileMs{fn=...}``),
    :func:`capture_cost` on each compile, tracer instant events, and
    recompile-storm detection.

    Keeps its own signature→executable AOT cache: a new abstract
    signature compiles through ``.lower().compile()`` (timed exactly, so
    the compile never hides inside a first-call wall time); repeat
    signatures dispatch the cached executable directly. Signatures the
    AOT path can't lower fall back to the plain jitted call — the first
    call's wall time (which includes the compile) is recorded instead,
    flagged ``approx="call"`` on the tracer event."""
    if fn is None:
        return functools.partial(instrumented_jit, name=name, stats=stats,
                                 **jit_kwargs)
    import jax

    st = stats or compile_stats
    label = name or getattr(fn, "__name__", None) or "jit"
    jitted = jax.jit(fn, **jit_kwargs)
    cache: Dict = {}
    cache_lock = make_lock("observability.compilestats.aot")

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        sig = abstract_signature(args, kwargs)
        with cache_lock:
            target = cache.get(sig)
        if target is not None:
            return target(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            compiled = jitted.lower(*args, **kwargs).compile()
        except Exception:
            out = jitted(*args, **kwargs)
            st.note_compile(label, (time.perf_counter() - t0) * 1000.0,
                            sig=sig, approx=True)
            with cache_lock:
                cache[sig] = jitted
            return out
        st.note_compile(label, (time.perf_counter() - t0) * 1000.0, sig=sig)
        capture_cost(compiled, label, registry=st._registry)
        try:
            out = compiled(*args, **kwargs)
            target = compiled
        except TypeError:
            # a Compiled from static_argnums takes only the dynamic args;
            # rather than re-split the argument list here, dispatch such
            # signatures through the jitted callable (its C++ cache is
            # warm — .compile() populated it)
            out = jitted(*args, **kwargs)
            target = jitted
        with cache_lock:
            cache[sig] = target
        return out

    wrapper._instrumented_jit = True
    wrapper._jitted = jitted
    return wrapper


def aot_compile(fn, *args, name: Optional[str] = None,
                stats: Optional[CompileStats] = None, **kwargs):
    """Lower+compile ``fn`` for ``args`` now, recording compile time,
    per-function counters, cost analysis and a tracer event; returns the
    ``jax.stages.Compiled`` executable. The shared API for scripts that
    used to hand-time ``.lower().compile()`` (scripts/tpu_profile_*)."""
    import jax

    st = stats or compile_stats
    label = name or getattr(fn, "__name__", None) or "aot"
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    t0 = time.perf_counter()
    compiled = jitted.lower(*args, **kwargs).compile()
    st.note_compile(label, (time.perf_counter() - t0) * 1000.0,
                    sig=abstract_signature(args, kwargs))
    capture_cost(compiled, label, registry=st._registry)
    return compiled


# -- device telemetry ---------------------------------------------------------
def capture_cost(compiled, name: str,
                 registry: MetricsRegistry = metrics) -> Optional[dict]:
    """Record ``compiled.cost_analysis()`` FLOPs / bytes-accessed as
    ``ml.device programFlops/programBytes{fn=...}`` gauges plus a
    ``compile.cost`` tracer event — the per-program FLOP/byte accounting
    that feeds achieved-FLOP/s reporting and sharding decisions. Returns
    ``{'flops', 'bytes'}``, or None when the backend exposes no
    analysis (never raises: telemetry must not sink the compile)."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    flops = float(analysis.get("flops", 0.0) or 0.0)
    nbytes = float(analysis.get("bytes accessed", 0.0) or 0.0)
    grp = registry.group(ML_GROUP, DEVICE_GROUP)
    grp.gauge("programFlops", flops, labels={"fn": name})
    grp.gauge("programBytes", nbytes, labels={"fn": name})
    tracing.tracer.event("compile.cost", fn=name, flops=flops, bytes=nbytes)
    return {"flops": flops, "bytes": nbytes}


def sample_memory(site: str, span=None,
                  registry: MetricsRegistry = metrics) -> dict:
    """Sample per-device ``memory_stats()`` watermarks into ``ml.device``
    gauges and (optionally) attributes on ``span``. Returns
    ``{'bytes_in_use', 'peak_bytes_in_use'}`` (host-wide sum / max), or
    ``{}`` where the platform exposes no stats.

    CPU degradation: ``memory_stats()`` returns None there — the first
    empty sample latches :attr:`CompileStats._memory_unavailable` so a
    traced CPU fit pays one probe total, not one per epoch. Never
    initializes a backend (see :func:`_backend_ready`)."""
    st = compile_stats
    if st._memory_unavailable or not _backend_ready():
        return {}
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return {}
    grp = registry.group(ML_GROUP, DEVICE_GROUP)
    in_use = peak = 0
    found = False
    for dev in devices:
        try:
            dev_stats = dev.memory_stats()
        except Exception:
            dev_stats = None
        if not dev_stats:
            continue
        found = True
        dev_in_use = int(dev_stats.get("bytes_in_use", 0))
        dev_peak = int(dev_stats.get("peak_bytes_in_use", dev_in_use))
        in_use += dev_in_use
        peak = max(peak, dev_peak)
        label = {"device": str(getattr(dev, "id", "?"))}
        grp.gauge("hbmBytesInUse", dev_in_use, labels=label)
        grp.gauge("hbmPeakBytes", dev_peak, labels=label)
    if not found:
        st._memory_unavailable = True
        return {}
    grp.gauge("hbmBytesInUseTotal", in_use, labels={"site": site})
    grp.gauge("hbmPeakBytesMax", peak, labels={"site": site})
    if span is not None:
        span.set_attribute("hbm_bytes_in_use", in_use)
        span.set_attribute("hbm_peak_bytes", peak)
    return {"bytes_in_use": in_use, "peak_bytes_in_use": peak}


# -- aggregates for the benchmark split and mltrace diff ----------------------
def compile_totals_split(
        snapshot: Optional[Dict[str, dict]] = None,
        registry: MetricsRegistry = metrics) -> Dict[str, dict]:
    """Compile totals per source: ``{'phase': {count, timeMs},
    'perfn': {count, timeMs}}`` — the monitoring ``backend_compile``
    channel vs the per-function ``compileMs`` series. Kept apart because
    a before/after delta must subtract within ONE source: an
    instrumented compile fires both, compiles outside instrumented
    functions fire only the monitoring channel, and mixing sources
    across a delta can go negative."""
    if snapshot is None:
        snapshot = registry.snapshot()
    gsnap = (snapshot or {}).get(f"{ML_GROUP}.{COMPILE_GROUP}", {})
    phase = {"count": 0, "timeMs": 0.0}
    perfn = {"count": 0, "timeMs": 0.0}
    for key, hist in gsnap.get("histograms", {}).items():
        if key.startswith("phaseMs") and 'phase="backend_compile"' in key:
            phase["count"] += int(hist.get("count", 0))
            phase["timeMs"] += float(hist.get("sum", 0.0))
        elif key.startswith("compileMs"):
            perfn["count"] += int(hist.get("count", 0))
            perfn["timeMs"] += float(hist.get("sum", 0.0))
    return {"phase": phase, "perfn": perfn}


def compile_totals_from_snapshot(snapshot: Optional[Dict[str, dict]]) -> dict:
    """``{'count', 'timeMs'}`` of ALL compile work in one registry
    snapshot. Prefers the monitoring ``backend_compile`` channel (it
    sees every compile); falls back to the per-function ``compileMs``
    series on jax builds without monitoring. The two are never summed —
    an instrumented compile fires both, and double counting would halve
    every 'compile share of wall time' readout. For before/after deltas
    use :func:`compile_totals_split` and subtract within one source."""
    totals = compile_totals_split(snapshot)
    src = totals["phase"] if totals["phase"]["count"] else totals["perfn"]
    return {"count": src["count"], "timeMs": src["timeMs"]}


def compile_totals(registry: MetricsRegistry = metrics) -> dict:
    """Live-registry :func:`compile_totals_from_snapshot`."""
    return compile_totals_from_snapshot(registry.snapshot())
