"""Critical-path analysis: where a request's (or an epoch's) wall time
actually went, reconstructed from the span DAG.

``flink-ml-tpu-trace summary`` answers "which span names burned the
most self-time"; this module answers the causal question: for ONE
serving request, how much of its submit→resolve wall clock was queue
wait vs padding vs the pipeline handoff vs device dispatch vs result
fetch? The DAG comes from two edge kinds (observability/tracing.py):

- **parent links** — same-thread nesting (``serving.request`` inside
  ``serving.batch``, ``checkpoint.save`` inside ``epoch``);
- **``follows_from`` links** — the explicit cross-thread handoffs the
  batcher records: ``serving.pad`` follows the ``serving.submit``
  spans it drained, ``serving.batch`` follows the ``serving.pad`` that
  prepared it (the pad→device ``queue.Queue`` hop), and each
  ``serving.resolve`` is a child of its request's submit span with a
  follows_from edge back to the batch that computed it.

Per request (joined on the ``req=`` attr submit/resolve spans share),
the wall clock [submit start, resolve end] partitions into named
segments::

    submit   the admission/submit span itself
    queue    submit end -> serving.pad start   (waiting to be drained)
    pad      the serving.pad span              (host padding/vetting)
    handoff  pad end -> serving.batch start    (the pipeline queue)
    device   the serving.batch span            (dispatch + compute)
    resolve  batch end -> resolve end          (fetch + future fan-out)

The segments are interval differences of one request's own timeline, so
their sum IS the wall clock up to clock-read jitter — ``coverage``
reports the attributed fraction and the acceptance bar is >= 0.9.
Epochs reuse the host/device split the iteration seams already attach
(``host_ms``/``device_ms`` epoch-span attrs).

The ``device`` segment is one opaque block to the span DAG — when a
captured device profile's ``profile.json`` sits beside the trace
(observability/profiling.py), :func:`attach_device_ops` sub-attributes
it to the top ops by measured self-time, so a budget verdict names the
owning op instead of "the device was slow".

CLI: ``flink-ml-tpu-trace path <dir> [--trace ID] [--json]
[--check [--budget PCT]]`` — ``--check`` exits 2 when the trace holds
no path-analyzable requests; with ``--budget`` it additionally exits 4
(the ``diff``/``slo`` violation class) when the aggregate queue-wait
share (queue + handoff) of request wall time exceeds PCT percent: the
"my p99 is all queueing" regression gate.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

__all__ = [
    "EXIT_OK", "EXIT_INVALID", "EXIT_OVER_BUDGET",
    "REQUEST_SEGMENTS", "QUEUE_SEGMENTS",
    "analyze_paths", "attach_device_ops", "render_paths", "main",
]

EXIT_OK = 0
EXIT_INVALID = 2
#: --check --budget's violation exit — same class as diff/slo's 4
EXIT_OVER_BUDGET = 4

#: per-request segment names, timeline order
REQUEST_SEGMENTS = ("submit", "queue", "pad", "handoff", "device",
                    "resolve")
#: the segments the --budget gate counts as "queue wait": time the
#: request spent parked, not being worked on
QUEUE_SEGMENTS = ("queue", "handoff")


def _end_us(sp: dict) -> int:
    return int(sp.get("ts_us", 0)) + int(sp.get("dur_us") or 0)


def _link_ids(sp: dict) -> List[str]:
    return [ln.get("span") for ln in sp.get("links", ())
            if ln.get("span")]


def _index(spans: List[dict]) -> Dict[str, List[dict]]:
    by_name: Dict[str, List[dict]] = {}
    for sp in spans:
        by_name.setdefault(str(sp.get("name", "")), []).append(sp)
    return by_name


def _request_rows(spans: List[dict]) -> List[dict]:
    """One row per reconstructable request: a ``serving.resolve``
    span's ``parent`` IS its request's ``serving.submit`` span (the
    batcher opens it with ``parent=req.ctx``) — the primary join,
    collision-free across processes and across batcher instances in a
    merged trace. The shared ``req=`` attr is only the fallback for
    resolve spans whose submit parent record is missing (e.g. a ring
    that rotated it away), and then only when the ordinal is
    unambiguous. From the resolve, walk the follows_from edge to its
    ``serving.batch`` and that batch's edge to its ``serving.pad``."""
    by_name = _index(spans)
    by_id = {sp.get("id"): sp for sp in spans if sp.get("id")}
    submit_by_req: Dict[object, List[dict]] = {}
    for sp in by_name.get("serving.submit", ()):
        req = sp.get("attrs", {}).get("req")
        if req is not None:
            submit_by_req.setdefault(req, []).append(sp)
    rows: List[dict] = []
    for resolve in by_name.get("serving.resolve", ()):
        attrs = resolve.get("attrs", {})
        req = attrs.get("req")
        submit = by_id.get(resolve.get("parent"))
        if submit is not None and \
                submit.get("name") != "serving.submit":
            submit = None
        if submit is None:
            candidates = submit_by_req.get(req, [])
            # two batchers (or two processes) both mint req=0 — an
            # ambiguous ordinal must not cross-wire request paths
            submit = candidates[0] if len(candidates) == 1 else None
        if submit is None:
            continue
        batch = next((by_id[i] for i in _link_ids(resolve)
                      if i in by_id
                      and by_id[i].get("name") == "serving.batch"),
                     None)
        pad = None
        if batch is not None:
            pad = next((by_id[i] for i in _link_ids(batch)
                        if i in by_id
                        and by_id[i].get("name") == "serving.pad"),
                       None)
        t_submit0 = int(submit.get("ts_us", 0))
        wall_us = max(_end_us(resolve) - t_submit0, 1)
        seg = dict.fromkeys(REQUEST_SEGMENTS, 0)
        seg["submit"] = int(submit.get("dur_us") or 0)
        if pad is not None:
            seg["queue"] = max(
                int(pad.get("ts_us", 0)) - _end_us(submit), 0)
            seg["pad"] = int(pad.get("dur_us") or 0)
        if batch is not None:
            after_pad = _end_us(pad) if pad is not None \
                else _end_us(submit)
            seg["handoff"] = max(
                int(batch.get("ts_us", 0)) - after_pad, 0)
            seg["device"] = int(batch.get("dur_us") or 0)
            seg["resolve"] = max(_end_us(resolve) - _end_us(batch), 0)
        else:
            # no reconstructable tick: everything after the submit span
            # is unattributed — the coverage number says so
            seg["resolve"] = int(resolve.get("dur_us") or 0)
        covered = sum(seg.values())
        rows.append({
            "req": req,
            "trace": submit.get("trace"),
            "tick": attrs.get("tick"),
            "rows": attrs.get("rows"),
            "wall_us": wall_us,
            "segments_us": seg,
            "coverage": min(covered / wall_us, 1.0),
        })
    rows.sort(key=lambda r: -r["wall_us"])
    return rows


def _epoch_rows(spans: List[dict]) -> List[dict]:
    """Per-epoch wall-time attribution from the host/device split the
    iteration seams attach to epoch/segment spans."""
    rows: List[dict] = []
    for sp in spans:
        if sp.get("name") not in ("epoch", "segment"):
            continue
        attrs = sp.get("attrs", {})
        total_ms = (sp.get("dur_us") or 0) / 1000.0
        host = attrs.get("host_ms")
        device = attrs.get("device_ms")
        row = {"kind": sp["name"],
               "epoch": attrs.get("epoch", attrs.get("epoch_to")),
               "wall_ms": round(total_ms, 3),
               "follows": len(_link_ids(sp))}
        if host is not None or device is not None:
            h = float(host or 0.0)
            d = float(device or 0.0)
            row["host_ms"] = h
            row["device_ms"] = d
            row["other_ms"] = round(max(total_ms - h - d, 0.0), 3)
            row["coverage"] = (min((h + d) / total_ms, 1.0)
                               if total_ms > 0 else 0.0)
        rows.append(row)
    rows.sort(key=lambda r: (r["epoch"] is None, r["epoch"]))
    return rows


def analyze_paths(spans: List[dict],
                  trace: Optional[str] = None) -> dict:
    """The structured path report: per-request segment attribution
    (aggregate + the slowest requests), the queue-wait share the
    ``--budget`` gate reads, and the per-epoch host/device view.
    ``trace`` narrows the span set to one trace id first."""
    if trace:
        spans = [sp for sp in spans if sp.get("trace") == trace]
    requests = _request_rows(spans)
    agg = dict.fromkeys(REQUEST_SEGMENTS, 0)
    wall_total = 0
    covered = 0
    for row in requests:
        wall_total += row["wall_us"]
        # coverage is per-request (clamped at its own wall clock):
        # requests sharing one tick each legitimately attribute the
        # full pad/device time — summing those against summed wall
        # would read > 1
        covered += min(sum(row["segments_us"].values()),
                       row["wall_us"])
        for name, us in row["segments_us"].items():
            agg[name] += us
    queue_us = sum(agg[name] for name in QUEUE_SEGMENTS)
    report = {
        "spans": len(spans),
        "traces": len({sp.get("trace") for sp in spans}),
        "requests": {
            "count": len(requests),
            "wall_ms_total": round(wall_total / 1000.0, 3),
            "coverage": (round(covered / wall_total, 4)
                         if wall_total else None),
            "queue_share": (round(queue_us / wall_total, 4)
                            if wall_total else None),
            "segments_ms": {name: round(us / 1000.0, 3)
                            for name, us in agg.items()},
            # the attribution mix: each segment's share of ALL
            # attributed time (shared ticks count once per request they
            # served, so the mix reflects what a request experiences)
            "segment_share": {name: (round(us / max(sum(agg.values()),
                                                    1), 4))
                              for name, us in agg.items()},
        },
        "slowest": requests[:10],
        "epochs": _epoch_rows(spans),
    }
    return report


def attach_device_ops(report: dict, trace_dir: str,
                      top: int = 3) -> dict:
    """Sub-attribute the opaque device segment: when a ``profile.json``
    device-profile artifact sits beside the trace
    (observability/profiling.py), attach its top ops by measured
    self-time as ``report["device_ops"]`` — so a ``--budget`` verdict
    names the op that owns the device time instead of one black-box
    block. Best-effort: without an artifact the report is unchanged."""
    try:
        from flink_ml_tpu.observability import profiling

        profile = profiling.read_profile(trace_dir)
    except Exception:  # noqa: BLE001 — most traces carry no profile
        return report
    ops = profile.get("ops") or []
    report["device_ops"] = {
        "source": profile.get("source"),
        "ops": [{"op": row["op"], "fn": row["fn"],
                 "selfMs": row["selfMs"], "count": row["count"]}
                for row in ops[:top]],
    }
    return report


def render_paths(report: dict, top_n: int = 5) -> str:
    req = report["requests"]
    out = [f"{report['spans']} span(s) across {report['traces']} "
           f"trace(s); {req['count']} reconstructed request path(s)"]
    if req["count"]:
        out.append(
            f"  wall {req['wall_ms_total']} ms total, attribution "
            f"coverage {req['coverage']:.1%}, queue-wait share "
            f"{req['queue_share']:.1%}")
        out.append("")
        out.append(f"  {'segment':<10} {'total ms':>12} {'share':>8}")
        for name in REQUEST_SEGMENTS:
            share = req["segment_share"][name]
            out.append(f"  {name:<10} {req['segments_ms'][name]:>12.3f}"
                       f" {share:>7.1%}")
        device_ops = report.get("device_ops")
        if device_ops and device_ops.get("ops"):
            src = device_ops.get("source")
            out.append("")
            out.append(f"  device segment, top op(s) by measured "
                       f"self-time (source: {src}):")
            for row in device_ops["ops"]:
                out.append(f"    {row['op']} (fn={row['fn']}): "
                           f"{row['selfMs']:.3f} ms x{row['count']}")
        if report["slowest"]:
            out.append("")
            out.append("  slowest request(s):")
            for row in report["slowest"][:top_n]:
                segs = " ".join(
                    f"{k}={v / 1000.0:.2f}ms"
                    for k, v in row["segments_us"].items() if v)
                out.append(f"    req {row['req']} tick {row['tick']}: "
                           f"{row['wall_us'] / 1000.0:.2f} ms  {segs}")
    if report["epochs"]:
        out.append("")
        out.append("per-epoch attribution:")
        for row in report["epochs"]:
            if "host_ms" in row:
                out.append(
                    f"  {row['kind']} {row['epoch']}: "
                    f"{row['wall_ms']} ms  host {row['host_ms']} + "
                    f"device {row['device_ms']} + other "
                    f"{row['other_ms']} ms "
                    f"({row['coverage']:.1%} attributed)")
            else:
                out.append(f"  {row['kind']} {row['epoch']}: "
                           f"{row['wall_ms']} ms")
    return "\n".join(out)


def main(argv=None) -> int:
    """``flink-ml-tpu-trace path <dir>`` — critical-path view;
    ``--check`` exits 2 with no reconstructable requests, and with
    ``--budget PCT`` exits 4 when the queue-wait share exceeds PCT%."""
    import argparse
    import sys

    from flink_ml_tpu.observability.exporters import (
        pipe_guard,
        read_spans,
        resolve_trace_dir,
    )

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace path",
        description="Per-request / per-epoch critical-path attribution "
                    "from a FLINK_ML_TPU_TRACE_DIR's span DAG "
                    "(parent + follows_from links).")
    parser.add_argument("trace_dir")
    parser.add_argument("--trace", default=None, metavar="ID",
                        help="narrow to one trace id")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 2 when no request path can be "
                             "reconstructed (the smoke gate)")
    parser.add_argument("--budget", type=float, default=None,
                        metavar="PCT",
                        help="with --check: exit 4 when the queue-wait "
                             "share of request wall time exceeds PCT%%")
    parser.add_argument("--top", type=int, default=5,
                        help="slowest requests rendered")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    args = parser.parse_args(argv)

    try:
        trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        spans = read_spans(trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace path: cannot read "
              f"{args.trace_dir}: {e}", file=sys.stderr)
        return EXIT_INVALID
    report = analyze_paths(spans, trace=args.trace)
    attach_device_ops(report, trace_dir)
    with pipe_guard():
        if args.json:
            print(json.dumps({"trace_dir": trace_dir,
                              "report": report}, indent=2,
                             default=str))
        else:
            print(render_paths(report, top_n=args.top))
    if args.check:
        if not report["requests"]["count"]:
            print(f"flink-ml-tpu-trace path: no reconstructable "
                  f"request paths in {trace_dir} (no serving.submit/"
                  f"serving.resolve span pairs)", file=sys.stderr)
            return EXIT_INVALID
        if args.budget is not None:
            share = report["requests"]["queue_share"] or 0.0
            if share * 100.0 > args.budget:
                print(f"flink-ml-tpu-trace path: queue-wait share "
                      f"{share:.1%} exceeds the {args.budget:g}% "
                      f"budget", file=sys.stderr)
                return EXIT_OVER_BUDGET
    return EXIT_OK


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
