"""Drift detection: training-time baselines, mergeable streaming
sketches, live-vs-baseline comparison wired into serving and SLOs.

The reference is an *online* ML library — FTRL trains continuously and
models hot-swap into serving — so the question "is live traffic still
the distribution this model was trained on?" is the observability layer
this module closes: the prediction gauges (observability/health.py) and
windowed serving metrics see the live side only, with nothing to compare
against. The sketch layer is the streaming-aggregation shape of
"Iterative MapReduce for Large Scale ML" (arXiv:1303.3517): mergeable
partial summaries folded across workers — here across the host-pool
fork (common/hostpool.py ships child sketch state beside metric
snapshots) and across the serving registry's model hot-swap.

Three stages (docs/observability.md "Drift detection"):

- **Sketch** (:class:`StreamingSketch` / :class:`SketchGroup`): a
  fixed-bin histogram with an auto-ranging first pass (values buffer
  until :data:`WARMUP_VALUES`, then the range freezes) plus exact
  count/mean/M2 moments (Chan's parallel update), min/max and a
  non-finite tally. ``merge``/``to_json``/``from_json`` make partial
  sketches fold into the driver exactly like
  :meth:`~flink_ml_tpu.common.metrics.MetricsRegistry.merge`: a merge
  between sketches sharing bin edges is bit-exact; differing edges
  rebin by bin midpoint (deterministic, counted in ``rebinned``).
- **Baseline** (:func:`capture_fit_baseline`): the traced-fit tail
  (models/common.py, models/online.py) sketches a row-capped sample of
  the training inputs per feature plus the final model's predictions
  and attaches the :class:`DriftBaseline` to the fitted model;
  ``serving.publish_model`` serializes it beside the v2 checkpoint
  manifest (``drift-baseline.json``, written before the atomic rename)
  so the hot-swap watcher (serving/registry.py) installs the *matching*
  baseline per model version. No baseline → evaluation reports
  ``source: "missing"`` and never blocks the swap.
- **Compare** (:func:`observe_transform` → :func:`evaluate`): the
  ``_served`` seam feeds per-feature/prediction values into a windowed
  live sketch ring per servable (seeded with the baseline's bin edges,
  so window merges stay exact), and a lazy evaluator on a cadence
  (``FLINK_ML_TPU_DRIFT_INTERVAL_S``) computes **PSI**, **Jensen-
  Shannon distance** and the **KS statistic** per feature and for
  predictions, recording ``drift{servable=,feature=,stat=}`` gauges in
  ``ml.drift``, emitting :data:`DRIFT_EVENT` instant events +
  ``violations{servable=}`` counters past the thresholds, and feeding
  the ``drift`` SLO objective kind (observability/slo.py), the
  ``/drift`` live route (observability/server.py) and the
  ``flink-ml-tpu-trace drift`` CLI (exit 4 drifted / 2 broken
  artifacts, consistent with ``diff``/``slo``).
"""

from __future__ import annotations

import json
import math
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import tracing

__all__ = [
    "DRIFT_ENV",
    "DRIFT_EVENT",
    "BASELINE_FILENAME",
    "STAT_NAMES",
    "StreamingSketch",
    "SketchGroup",
    "DriftBaseline",
    "enabled",
    "capture_armed",
    "sample_rows",
    "capture_fit_baseline",
    "load_baseline_file",
    "install_baseline",
    "forget_servable",
    "baseline_for",
    "observe_transform",
    "evaluate",
    "drift_report",
    "provenance",
    "compare_sketches",
    "psi",
    "js_distance",
    "ks_stat",
    "thresholds",
    "state_snapshot",
    "merge_state",
    "reseed_child",
    "dump_state",
    "read_state",
    "clear",
    "main",
]

#: "0" disables the whole layer (live sketching AND fit-time capture);
#: any other non-empty value force-arms fit-time capture even without a
#: trace dir (live sketching is on by default — it is the serving half)
DRIFT_ENV = "FLINK_ML_TPU_DRIFT"
#: evaluator cadence in seconds (0 = every observation; default 30)
INTERVAL_ENV = "FLINK_ML_TPU_DRIFT_INTERVAL_S"
#: live comparison window in seconds (default 300)
WINDOW_ENV = "FLINK_ML_TPU_DRIFT_WINDOW_S"
#: verdict thresholds per statistic
PSI_ENV = "FLINK_ML_TPU_DRIFT_PSI"
JS_ENV = "FLINK_ML_TPU_DRIFT_JS"
KS_ENV = "FLINK_ML_TPU_DRIFT_KS"
#: minimum live observations per series before a verdict is rendered
MIN_COUNT_ENV = "FLINK_ML_TPU_DRIFT_MIN_COUNT"
#: per-servable cap on sketched feature columns (wide hashed features
#: must not turn every request into a 2^18-column summary)
MAX_FEATURES_ENV = "FLINK_ML_TPU_DRIFT_MAX_FEATURES"
#: row cap for the fit-time training-input sample
SAMPLE_ROWS_ENV = "FLINK_ML_TPU_DRIFT_SAMPLE_ROWS"

#: instant-event name for detected drift in the trace
DRIFT_EVENT = "ml.drift"

#: the baseline artifact filename beside a checkpoint's manifest.json
BASELINE_FILENAME = "drift-baseline.json"

#: the statistics every comparison computes, in reporting order
STAT_NAMES = ("psi", "js", "ks")

#: exit codes (shared convention with diff/slo: 4 = gate fired,
#: 2 = broken artifacts)
EXIT_OK = 0
EXIT_INVALID = 2
EXIT_DRIFTED = 4

#: histogram bins per sketch and the auto-ranging buffer size
DEFAULT_BINS = 32
WARMUP_VALUES = 256

#: threshold defaults: PSI 0.25 is the standard "significant
#: population change" rule of thumb; JS/KS are set above the sampling
#: noise a few hundred observations put on 32-bin estimates, so a
#: same-distribution window does not flap the verdict
_DEFAULTS = {PSI_ENV: 0.25, JS_ENV: 0.2, KS_ENV: 0.25,
             INTERVAL_ENV: 30.0, WINDOW_ENV: 300.0}


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


def enabled() -> bool:
    """The live tier: per-request sketching on the serving seam. On by
    default; ``FLINK_ML_TPU_DRIFT=0`` is the kill switch."""
    return os.environ.get(DRIFT_ENV, "") != "0"


def capture_armed() -> bool:
    """The fit-time tier: baseline capture at the end of a fit. Armed
    when a trace dir is configured or ``FLINK_ML_TPU_DRIFT`` is truthy
    (mirrors health.armed — a plain untraced fit stays zero-cost);
    ``FLINK_ML_TPU_DRIFT=0`` disables it."""
    env = os.environ.get(DRIFT_ENV, "")
    if env == "0":
        return False
    return bool(env) or tracing.tracer.enabled


def thresholds() -> Dict[str, float]:
    """The per-statistic drift thresholds (env-tunable)."""
    return {"psi": _env_float(PSI_ENV, _DEFAULTS[PSI_ENV]),
            "js": _env_float(JS_ENV, _DEFAULTS[JS_ENV]),
            "ks": _env_float(KS_ENV, _DEFAULTS[KS_ENV])}


def _min_count() -> int:
    # below ~100 samples the 10-group estimates are noisy enough that a
    # same-distribution window can brush the thresholds
    return _env_int(MIN_COUNT_ENV, 100)


def _max_features() -> int:
    return _env_int(MAX_FEATURES_ENV, 32)


# -- the mergeable streaming sketch -------------------------------------------

def _merge_moments(n1, mean1, m2_1, n2, mean2, m2_2):
    """Chan's parallel mean/M2 update — deterministic, so the same fold
    order yields bit-identical results on either side of a process
    boundary."""
    if n2 == 0:
        return n1, mean1, m2_1
    if n1 == 0:
        return n2, mean2, m2_2
    n = n1 + n2
    delta = mean2 - mean1
    mean = mean1 + delta * (n2 / n)
    m2 = m2_1 + m2_2 + delta * delta * (n1 * n2 / n)
    return n, mean, m2


class StreamingSketch:
    """Mergeable streaming summary of ONE scalar distribution: exact
    count/mean/M2/min/max moments + a fixed-bin histogram whose range is
    frozen after an auto-ranging first pass (:data:`WARMUP_VALUES`
    buffered values), or seeded explicitly with ``edges`` — how live
    sketches adopt their baseline's binning so window merges and PSI
    comparisons share bins exactly. Thread-safety lives one level up
    (the live window holds the lock); a sketch itself is plain state so
    ``to_json``/``from_json`` round-trip losslessly."""

    __slots__ = ("bins", "edges", "counts", "underflow", "overflow",
                 "pending", "count", "mean", "m2", "vmin", "vmax",
                 "nonfinite", "rebinned")

    def __init__(self, bins: int = DEFAULT_BINS,
                 edges: Optional[Sequence[float]] = None):
        if edges is not None:
            self.edges: Optional[tuple] = tuple(float(e) for e in edges)
            self.bins = len(self.edges) - 1
            if self.bins < 1 or list(self.edges) != sorted(self.edges):
                raise ValueError(f"edges must be >= 2 sorted bounds, "
                                 f"got {edges!r}")
        else:
            self.bins = int(bins)
            if self.bins < 1:
                raise ValueError("bins must be >= 1")
            self.edges = None
        self.counts = [0] * self.bins
        self.underflow = 0
        self.overflow = 0
        self.pending: List[float] = []
        self.count = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.vmin: Optional[float] = None
        self.vmax: Optional[float] = None
        self.nonfinite = 0
        self.rebinned = 0

    # -- observation ---------------------------------------------------------
    def observe(self, value) -> None:
        self.observe_many([value])

    def observe_many(self, values) -> None:
        arr = np.asarray(values, np.float64).ravel()
        if arr.size == 0:
            return
        finite = np.isfinite(arr)
        self.nonfinite += int(arr.size - finite.sum())
        fv = arr[finite]
        if fv.size == 0:
            return
        bmean = float(fv.mean())
        bm2 = float(np.sum(np.square(fv - bmean)))
        self.count, self.mean, self.m2 = _merge_moments(
            self.count, self.mean, self.m2, int(fv.size), bmean, bm2)
        lo, hi = float(fv.min()), float(fv.max())
        self.vmin = lo if self.vmin is None else min(self.vmin, lo)
        self.vmax = hi if self.vmax is None else max(self.vmax, hi)
        if self.edges is None:
            self.pending.extend(float(v) for v in fv)
            if len(self.pending) >= WARMUP_VALUES:
                self._freeze_range()
        else:
            self._bin(fv)

    def _bin(self, fv: np.ndarray) -> None:
        e = np.asarray(self.edges)
        self.underflow += int((fv < e[0]).sum())
        self.overflow += int((fv > e[-1]).sum())
        hist, _ = np.histogram(fv, bins=e)
        for i, c in enumerate(hist):
            self.counts[i] += int(c)

    def _freeze_range(self) -> None:
        lo = min(self.pending)
        hi = max(self.pending)
        if lo == hi:  # a constant series still needs a non-empty range
            lo, hi = lo - 0.5, hi + 0.5
        self.edges = tuple(float(x)
                           for x in np.linspace(lo, hi, self.bins + 1))
        flush, self.pending = self.pending, []
        self._bin(np.asarray(flush, np.float64))

    def finalize(self) -> "StreamingSketch":
        """Freeze the auto-ranged histogram (no-op when already ranged
        or empty) — called before a baseline serializes so comparisons
        always see binned counts."""
        if self.edges is None and self.pending:
            self._freeze_range()
        return self

    # -- derived -------------------------------------------------------------
    @property
    def stddev(self) -> float:
        if self.count <= 0:
            return float("nan")
        return math.sqrt(max(self.m2, 0.0) / self.count)

    # -- merge / serialization -----------------------------------------------
    def merge(self, snap) -> None:
        """Fold another sketch (object or its ``to_json`` dict) in.
        Identical bin edges add bin-wise (bit-exact — the fork-boundary
        contract); an unranged side contributes its buffered raw values
        exactly; differing edges rebin the incoming counts by bin
        midpoint (deterministic, tallied in ``rebinned``)."""
        if isinstance(snap, StreamingSketch):
            snap = snap.to_json()
        n2 = int(snap.get("count", 0))
        self.count, self.mean, self.m2 = _merge_moments(
            self.count, self.mean, self.m2, n2,
            float(snap.get("mean", 0.0)), float(snap.get("m2", 0.0)))
        self.nonfinite += int(snap.get("nonfinite", 0))
        self.rebinned += int(snap.get("rebinned", 0))
        for attr, pick in (("vmin", min), ("vmax", max)):
            other = snap.get(attr[1:])  # "min"/"max" in the JSON
            if other is not None:
                mine = getattr(self, attr)
                setattr(self, attr, float(other) if mine is None
                        else pick(mine, float(other)))
        pending = snap.get("pending") or []
        if pending:
            if self.edges is None:
                self.pending.extend(float(v) for v in pending)
                if len(self.pending) >= WARMUP_VALUES:
                    self._freeze_range()
            else:
                self._bin(np.asarray(pending, np.float64))
        other_edges = snap.get("edges")
        if other_edges is None:
            return
        other_edges = tuple(float(e) for e in other_edges)
        other_counts = [int(c) for c in snap.get("counts", ())]
        if len(other_counts) != len(other_edges) - 1:
            raise ValueError(
                f"sketch snapshot bin mismatch: {len(other_counts)} "
                f"count(s) vs {len(other_edges) - 1} bin(s)")
        if self.edges is None:
            # adopt the ranged side's edges, flushing our buffer into it
            self.edges = other_edges
            self.bins = len(other_edges) - 1
            self.counts = [0] * self.bins
            flush, self.pending = self.pending, []
            if flush:
                self._bin(np.asarray(flush, np.float64))
        if self.edges == other_edges:
            for i, c in enumerate(other_counts):
                self.counts[i] += c
            self.underflow += int(snap.get("underflow", 0))
            self.overflow += int(snap.get("overflow", 0))
            return
        # differing ranges: deterministic midpoint rebin
        self.rebinned += 1
        e = np.asarray(other_edges)
        mids = (e[:-1] + e[1:]) / 2.0
        weights = np.asarray(other_counts, np.float64)
        mine = np.asarray(self.edges)
        self.underflow += int(snap.get("underflow", 0))
        self.overflow += int(snap.get("overflow", 0))
        self.underflow += int(weights[mids < mine[0]].sum())
        self.overflow += int(weights[mids > mine[-1]].sum())
        hist, _ = np.histogram(mids, bins=mine, weights=weights)
        for i, c in enumerate(hist):
            self.counts[i] += int(c)

    def to_json(self) -> dict:
        return {"bins": self.bins,
                "edges": (list(self.edges)
                          if self.edges is not None else None),
                "counts": list(self.counts),
                "underflow": self.underflow,
                "overflow": self.overflow,
                "pending": list(self.pending),
                "count": self.count,
                "mean": self.mean,
                "m2": self.m2,
                "min": self.vmin,
                "max": self.vmax,
                "nonfinite": self.nonfinite,
                "rebinned": self.rebinned}

    @classmethod
    def from_json(cls, snap: dict) -> "StreamingSketch":
        sk = cls(bins=int(snap.get("bins", DEFAULT_BINS)))
        sk.merge(snap)
        return sk


class SketchGroup:
    """A named bundle of sketches — the per-servable unit both the
    baseline and each live window slice hold. ``template`` maps names
    to bin edges new sketches are seeded with (how live sketches adopt
    the baseline's binning)."""

    def __init__(self, template: Optional[Dict[str, Sequence[float]]]
                 = None):
        self.sketches: Dict[str, StreamingSketch] = {}
        self._template = dict(template or {})

    def sketch(self, name: str) -> StreamingSketch:
        sk = self.sketches.get(name)
        if sk is None:
            edges = self._template.get(name)
            sk = self.sketches[name] = StreamingSketch(edges=edges)
        return sk

    def observe(self, columns: Dict[str, np.ndarray]) -> None:
        for name, values in columns.items():
            self.sketch(name).observe_many(values)

    def merge(self, snap: Dict[str, dict]) -> None:
        for name, ssnap in (snap or {}).items():
            self.sketch(name).merge(ssnap)

    def finalize(self) -> "SketchGroup":
        for sk in self.sketches.values():
            sk.finalize()
        return self

    def to_json(self) -> Dict[str, dict]:
        return {name: sk.to_json()
                for name, sk in self.sketches.items()}

    @classmethod
    def from_json(cls, snap: Dict[str, dict]) -> "SketchGroup":
        group = cls()
        group.merge(snap or {})
        return group


# -- comparison statistics ----------------------------------------------------

def _aligned_counts(base: dict, live: dict):
    """(baseline, live) count vectors over the BASELINE's bins plus its
    under/overflow tails — the shared support every statistic needs.
    Returns None when the baseline has no frozen range (empty sketch)."""
    edges = base.get("edges")
    if not edges:
        return None
    edges = tuple(float(e) for e in edges)
    p = np.asarray([base.get("underflow", 0)]
                   + [int(c) for c in base.get("counts", ())]
                   + [base.get("overflow", 0)], np.float64)
    live_edges = live.get("edges")
    if live_edges is not None:
        live_edges = tuple(float(e) for e in live_edges)
    if live_edges == edges:
        q = np.asarray([live.get("underflow", 0)]
                       + [int(c) for c in live.get("counts", ())]
                       + [live.get("overflow", 0)], np.float64)
        return p, q
    # rebin the live side onto the baseline's edges: buffered raw values
    # exactly, binned counts by midpoint, tails by their own endpoints
    values: List[float] = [float(v) for v in live.get("pending") or []]
    weights: List[float] = [1.0] * len(values)
    if live_edges is not None:
        e = np.asarray(live_edges)
        mids = (e[:-1] + e[1:]) / 2.0
        for m, c in zip(mids, live.get("counts", ())):
            if c:
                values.append(float(m))
                weights.append(float(c))
        if live.get("underflow"):
            values.append(float(e[0]))
            weights.append(float(live["underflow"]))
        if live.get("overflow"):
            values.append(float(e[-1]))
            weights.append(float(live["overflow"]))
    varr = np.asarray(values, np.float64)
    warr = np.asarray(weights, np.float64)
    me = np.asarray(edges)
    q = np.zeros(len(edges) + 1, np.float64)
    if varr.size:
        q[0] = warr[varr < me[0]].sum()
        q[-1] = warr[varr > me[-1]].sum()
        hist, _ = np.histogram(varr, bins=me, weights=warr)
        q[1:-1] = hist
    return p, q


def _coarsen(p_counts: np.ndarray, q_counts: np.ndarray,
             target_groups: int = 10):
    """Regroup two aligned count vectors into ~``target_groups``
    adjacent-bin groups, each holding at least 1/target of the
    BASELINE's mass — the standard PSI preparation: a small live sample
    spread over many fine bins otherwise accrues empty-bin penalties
    that read as drift when nothing moved."""
    pt = float(p_counts.sum())
    if pt <= 0:
        return p_counts, q_counts
    min_mass = pt / target_groups
    gp: List[float] = []
    gq: List[float] = []
    accp = accq = 0.0
    for pi, qi in zip(p_counts, q_counts):
        accp += float(pi)
        accq += float(qi)
        if accp >= min_mass:
            gp.append(accp)
            gq.append(accq)
            accp = accq = 0.0
    if accp or accq:  # the trailing partial group
        if gp:
            gp[-1] += accp
            gq[-1] += accq
        else:
            gp.append(accp)
            gq.append(accq)
    return np.asarray(gp, np.float64), np.asarray(gq, np.float64)


def psi(p_counts: np.ndarray, q_counts: np.ndarray) -> float:
    """Population Stability Index between two aligned count vectors
    (expected=baseline, actual=live), with Laplace (+0.5 per bin)
    smoothing so a sparse live sample's empty bins contribute a
    sample-size-bounded penalty instead of a fixed floor blowup."""
    pt, qt = float(p_counts.sum()), float(q_counts.sum())
    if pt <= 0 or qt <= 0:
        return float("nan")
    k = len(p_counts)
    p = (np.asarray(p_counts, np.float64) + 0.5) / (pt + 0.5 * k)
    q = (np.asarray(q_counts, np.float64) + 0.5) / (qt + 0.5 * k)
    return float(np.sum((q - p) * np.log(q / p)))


def js_distance(p_counts: np.ndarray, q_counts: np.ndarray) -> float:
    """Jensen-Shannon *distance* (sqrt of the base-2 divergence, so the
    value lives in [0, 1]) between two aligned count vectors."""
    pt, qt = float(p_counts.sum()), float(q_counts.sum())
    if pt <= 0 or qt <= 0:
        return float("nan")
    p = p_counts / pt
    q = q_counts / qt
    m = (p + q) / 2.0

    def _kl(a, b):
        mask = a > 0
        return float(np.sum(a[mask] * np.log2(a[mask] / b[mask])))

    jsd = 0.5 * _kl(p, m) + 0.5 * _kl(q, m)
    return math.sqrt(min(max(jsd, 0.0), 1.0))


def ks_stat(p_counts: np.ndarray, q_counts: np.ndarray) -> float:
    """Kolmogorov-Smirnov statistic (max CDF gap at the shared bin
    boundaries — binned, so a lower bound on the exact statistic)."""
    pt, qt = float(p_counts.sum()), float(q_counts.sum())
    if pt <= 0 or qt <= 0:
        return float("nan")
    return float(np.max(np.abs(np.cumsum(p_counts / pt)
                               - np.cumsum(q_counts / qt))))


def compare_sketches(baseline: dict, live: dict) -> Optional[dict]:
    """All :data:`STAT_NAMES` between a baseline sketch snapshot and a
    live one, plus the sample counts and the moment deltas; None when
    the baseline cannot anchor a comparison (no frozen range)."""
    if isinstance(baseline, StreamingSketch):
        baseline = baseline.to_json()
    if isinstance(live, StreamingSketch):
        live = live.to_json()
    aligned = _aligned_counts(baseline, live)
    if aligned is None:
        return None
    p, q = _coarsen(*aligned)
    return {"psi": round(psi(p, q), 6),
            "js": round(js_distance(p, q), 6),
            "ks": round(ks_stat(p, q), 6),
            "baseline_n": int(baseline.get("count", 0)),
            "live_n": int(live.get("count", 0)),
            "mean_delta": round(float(live.get("mean", 0.0))
                                - float(baseline.get("mean", 0.0)), 6)}


# -- the training-time baseline -----------------------------------------------

class DriftBaseline:
    """A fitted model's training-time distribution summary: one sketch
    per (capped) feature column plus one for the predictions, with the
    model/version provenance the hot-swap keys on."""

    def __init__(self, model: str, version: Optional[int] = None,
                 group: Optional[SketchGroup] = None,
                 created_unix: Optional[float] = None):
        self.model = model
        self.version = None if version is None else int(version)
        self.group = group or SketchGroup()
        self.created_unix = (time.time() if created_unix is None
                             else float(created_unix))

    def edges_template(self) -> Dict[str, tuple]:
        """name → frozen bin edges, for seeding live sketches."""
        return {name: sk.edges
                for name, sk in self.group.sketches.items()
                if sk.edges is not None}

    def to_json(self) -> dict:
        self.group.finalize()
        return {"version": 1, "model": self.model,
                "modelVersion": self.version,
                "created_unix": self.created_unix,
                "sketches": self.group.to_json()}

    @classmethod
    def from_json(cls, doc: dict) -> "DriftBaseline":
        if not isinstance(doc, dict) or "sketches" not in doc:
            raise ValueError(
                "drift baseline document must be a mapping with a "
                "'sketches' key")
        return cls(model=str(doc.get("model", "?")),
                   version=doc.get("modelVersion"),
                   group=SketchGroup.from_json(doc["sketches"]),
                   created_unix=doc.get("created_unix"))


def sample_rows(x, cap: Optional[int] = None):
    """Leading-row sample of a feature matrix for baseline capture —
    bounded work at fit end regardless of training-set size. Works on
    ndarray/jax arrays and CSR matrices alike."""
    cap = cap if cap is not None else _env_int(SAMPLE_ROWS_ENV, 4096)
    try:
        n = x.shape[0]
    except (AttributeError, IndexError):
        return x
    return x[:cap] if n > cap else x


def _matrix_columns(x, max_features: int) -> Dict[str, np.ndarray]:
    """A feature matrix → ``{"f0": col, ...}`` (capped), or
    ``{"value": vec}`` for a 1-D input. CSR inputs densify only the
    capped column slice."""
    if hasattr(x, "tocsr") or hasattr(x, "toarray"):
        x = x[:, :max_features].toarray()
    arr = np.asarray(x, np.float64)
    if arr.ndim == 1:
        return {"value": arr}
    if arr.ndim != 2:
        return {}
    return {f"f{i}": arr[:, i]
            for i in range(min(arr.shape[1], max_features))}


def feature_columns(values,
                    max_features: Optional[int] = None
                    ) -> Dict[str, np.ndarray]:
    """Row-oriented feature values (a DataFrame column: vectors or
    scalars per row) → named columns for sketching. Ragged or
    non-numeric rows yield ``{}`` — the seam must never raise."""
    cap = max_features if max_features is not None else _max_features()
    if not values:
        return {}
    first = values[0]
    try:
        if hasattr(first, "to_array"):
            mat = np.stack([np.asarray(v.to_array(), np.float64)
                            for v in values])
            return _matrix_columns(mat, cap)
        arr = np.asarray(values, np.float64)
    except (TypeError, ValueError):
        return {}
    if arr.ndim == 1:
        return {"value": arr}
    return _matrix_columns(arr, cap)


def capture_fit_baseline(model, algo: str, features=None,
                         predictions=None,
                         version: Optional[int] = None
                         ) -> Optional[DriftBaseline]:
    """Build the training-time baseline from a (row-capped) feature
    sample and the final model's predictions on it, attach it to the
    fitted model as ``model.drift_baseline``, and record the capture
    (``ml.drift baselineCaptured{algo=}`` counter + a trace-dir
    ``drift-baseline-<algo>.json`` artifact when tracing is armed).
    Returns the baseline (None when there was nothing numeric to
    sketch). Never raises past its own logging — a baseline failure
    must not fail the fit that produced the model."""
    group = SketchGroup()
    if features is not None:
        for name, col in _matrix_columns(features,
                                         _max_features()).items():
            group.sketch(name).observe_many(col)
    if predictions is not None:
        try:
            pred = np.asarray(predictions, np.float64).ravel()
        except (TypeError, ValueError):
            pred = None  # vector prediction column: no scalar sketch
        if pred is not None and pred.size:
            group.sketch("prediction").observe_many(pred)
    if not group.sketches:
        return None
    baseline = DriftBaseline(algo, version=version,
                             group=group.finalize())
    try:
        model.drift_baseline = baseline
    except AttributeError:
        pass  # __slots__ model: the caller still gets the return value
    metrics.group(ML_GROUP, "drift").counter(
        "baselineCaptured", labels={"algo": algo})
    if tracing.tracer.enabled:
        try:
            path = os.path.join(tracing.tracer.trace_dir,
                                f"drift-baseline-{algo}.json")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(baseline.to_json(), f)
            os.replace(tmp, path)
        except OSError:
            pass  # artifact only; the in-memory baseline is attached
    return baseline


def load_baseline_file(path: str) -> Optional[DriftBaseline]:
    """Read a serialized baseline (the checkpoint-side artifact or a
    ``--baseline`` override); None when the file does not exist, raises
    ValueError on an unreadable/malformed document."""
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"{path}: unreadable drift baseline: {e}") from e
    return DriftBaseline.from_json(doc)


# -- live state ---------------------------------------------------------------

class _LiveWindow:
    """Sliding window of live sketches for one servable: a ring of
    closed :class:`SketchGroup` slices plus the open one, rotated lazily
    (no timer thread — the WindowedHistogram shape in
    common/metrics.py). Slices seed their sketches from the baseline's
    bin edges so in-window merges stay bit-exact."""

    def __init__(self, horizon_s: float, slices: int = 30,
                 template: Optional[Dict[str, tuple]] = None,
                 clock=time.monotonic):
        self.horizon_s = float(horizon_s)
        self._slice_s = self.horizon_s / max(1, int(slices))
        self._template = dict(template or {})
        self._clock = clock
        self._ring: List[tuple] = []  # (t_closed, SketchGroup)
        self._current = SketchGroup(self._template)
        self._last_slice = clock()
        self.total = 0  # observations ever (cheap freshness probe)

    def _rotate(self, now: float) -> None:
        if now - self._last_slice < self._slice_s:
            return
        if self._current.sketches:
            self._ring.append((now, self._current))
            self._current = SketchGroup(self._template)
        self._last_slice = now
        cutoff = now - self.horizon_s
        while self._ring and self._ring[0][0] <= cutoff:
            self._ring.pop(0)

    def observe(self, columns: Dict[str, np.ndarray]) -> None:
        self._rotate(self._clock())
        self._current.observe(columns)
        self.total += 1

    def merge(self, snap: Dict[str, dict]) -> None:
        """Fold a child-process group snapshot into the open slice (so
        merged counts are window-visible from merge time — the
        WindowedCounter contract)."""
        self._rotate(self._clock())
        self._current.merge(snap)
        self.total += 1

    def window_json(self, window_s: Optional[float] = None
                    ) -> Dict[str, dict]:
        w = self.horizon_s if window_s is None \
            else min(float(window_s), self.horizon_s)
        now = self._clock()
        self._rotate(now)
        cutoff = now - w
        merged = SketchGroup(self._template)
        for t, group in self._ring:
            if t > cutoff:
                merged.merge(group.to_json())
        merged.merge(self._current.to_json())
        return merged.to_json()


_lock = make_lock("observability.drift")
_baselines: Dict[str, DriftBaseline] = {}
_missing: set = set()       # servables that swapped in without a baseline
_windows: Dict[str, _LiveWindow] = {}
_last_eval: Dict[str, float] = {}
_last_results: Dict[str, dict] = {}
#: insertion-ordered registry of tracked servable names — the eviction
#: order. A continuously-republishing online deployment mints a new
#: versioned name per hot-swap; without a cap, baselines/windows/
#: results for dead versions would grow (and /drift scrapes slow down)
#: without bound while the checkpoint side prunes to keep=8.
_tracked: Dict[str, None] = {}
MAX_TRACKED_SERVABLES = 64


def _track_locked(servable: str) -> None:
    """Mark ``servable`` as live (most-recently tracked) and evict the
    oldest tracked names past :data:`MAX_TRACKED_SERVABLES`. Caller
    holds ``_lock``."""
    _tracked.pop(servable, None)
    _tracked[servable] = None
    while len(_tracked) > MAX_TRACKED_SERVABLES:
        old = next(iter(_tracked))
        if old == servable:  # never evict the name just touched
            break
        _tracked.pop(old)
        _baselines.pop(old, None)
        _missing.discard(old)
        _windows.pop(old, None)
        _last_eval.pop(old, None)
        _last_results.pop(old, None)


def forget_servable(servable: str) -> None:
    """Drop all drift state for one servable — a rejected hot-swap
    candidate whose versioned name will never serve (serving/
    registry.py), or a caller retiring an old version early."""
    with _lock:
        _tracked.pop(servable, None)
        _baselines.pop(servable, None)
        _missing.discard(servable)
        _windows.pop(servable, None)
        _last_eval.pop(servable, None)
        _last_results.pop(servable, None)


def install_baseline(servable: str,
                     baseline: Optional[DriftBaseline]) -> None:
    """Install (or record as missing) the baseline the live comparison
    for ``servable`` anchors on — called by the serving registry's
    hot-swap with the baseline shipped beside that version's checkpoint
    manifest. Keyed by the *versioned* serving name (``lr@v2``), so
    requests still in flight on the previous version keep comparing
    against the previous baseline."""
    with _lock:
        _track_locked(servable)
        if baseline is None:
            _missing.add(servable)
            _baselines.pop(servable, None)
        else:
            _missing.discard(servable)
            _baselines[servable] = baseline
    metrics.group(ML_GROUP, "drift").gauge(
        "baselineInstalled", 0 if baseline is None else 1,
        labels={"servable": servable})


def baseline_for(servable: str) -> Optional[DriftBaseline]:
    with _lock:
        return _baselines.get(servable)


def _window_for(servable: str) -> _LiveWindow:
    with _lock:
        win = _windows.get(servable)
        if win is None:
            _track_locked(servable)
            base = _baselines.get(servable)
            win = _windows[servable] = _LiveWindow(
                _env_float(WINDOW_ENV, _DEFAULTS[WINDOW_ENV]),
                template=(base.edges_template()
                          if base is not None else None))
        return win


def observe_transform(servable: str, features=None,
                      predictions=None) -> None:
    """The serving seam (servable/api.py ``_served``): sketch one
    transform's feature columns and prediction values into the
    servable's live window, then give the lazy evaluator its tick.
    Quietly does nothing when disabled or when the values don't reduce
    to numeric columns — recording must never sink a serving call."""
    if not enabled():
        return
    columns: Dict[str, np.ndarray] = {}
    if features is not None:
        columns.update(feature_columns(features))
    if predictions is not None:
        try:
            pred = np.asarray(list(predictions), np.float64).ravel()
            if pred.size:
                columns["prediction"] = pred
        except (TypeError, ValueError):
            pass
    if not columns:
        return
    win = _window_for(servable)
    with _lock:
        win.observe(columns)
    maybe_evaluate(servable)


def maybe_evaluate(servable: str) -> Optional[dict]:
    """Run :func:`evaluate` when the cadence
    (``FLINK_ML_TPU_DRIFT_INTERVAL_S``) has lapsed for this servable;
    the fast path is one clock read + dict lookup."""
    interval = _env_float(INTERVAL_ENV, _DEFAULTS[INTERVAL_ENV])
    now = time.monotonic()
    with _lock:
        last = _last_eval.get(servable)
        if last is not None and now - last < interval:
            return None
        _last_eval[servable] = now
    return evaluate(servable)


def evaluate(servable: str, emit: bool = True,
             window_s: Optional[float] = None) -> dict:
    """Compare ``servable``'s live window against its installed
    baseline: per-series PSI / JS distance / KS statistic, recorded as
    ``drift{servable=,feature=,stat=}`` gauges in ``ml.drift``; past any
    threshold (and the ``FLINK_ML_TPU_DRIFT_MIN_COUNT`` sample floor)
    the series is *drifted* — with ``emit``, each drifted series lands a
    :data:`DRIFT_EVENT` instant event and the
    ``violations{servable=}`` counter. Without a baseline the verdict is
    ``source: "missing"`` and never a violation."""
    with _lock:
        base = _baselines.get(servable)
        win = _windows.get(servable)
        live = win.window_json(window_s) if win is not None else {}
    thr = thresholds()
    result = {"servable": servable,
              "source": "baseline" if base is not None else "missing",
              "baselineVersion": (base.version
                                  if base is not None else None),
              "thresholds": thr,
              "minCount": _min_count(),
              "series": {},
              "drifted": [],
              "evaluated_unix": time.time()}
    if base is not None:
        group = metrics.group(ML_GROUP, "drift")
        for name, bsnap in sorted(base.group.to_json().items()):
            stats = compare_sketches(bsnap, live.get(name, {}))
            if stats is None:
                continue
            fresh = stats["live_n"] >= _min_count()
            over = [s for s in STAT_NAMES
                    if math.isfinite(stats[s]) and stats[s] > thr[s]]
            drifted = bool(fresh and over)
            row = dict(stats)
            row["drifted"] = drifted
            row["thin"] = not fresh
            row["over"] = over if fresh else []
            result["series"][name] = row
            if fresh:
                # gauges carry the same sample floor as the verdict: a
                # thin window's estimates are noise (a 10-sample window
                # reads psi ~0.9 on clean traffic), and the drift SLO
                # kind consumes these gauges raw — publishing them
                # would flip /slo to VIOLATED on a service that just
                # started
                for stat in STAT_NAMES:
                    group.gauge("drift", stats[stat],
                                labels={"servable": servable,
                                        "feature": name, "stat": stat})
            if drifted:
                result["drifted"].append(name)
                if emit:
                    group.counter("violations",
                                  labels={"servable": servable})
                    tracing.tracer.event(
                        DRIFT_EVENT, servable=servable, feature=name,
                        over=",".join(over),
                        **{s: stats[s] for s in STAT_NAMES})
    if emit and result["drifted"]:
        try:
            # flight recorder (observability/flightrecorder.py): the
            # live sketches and span ring that explain the shift are
            # rotating windows — freeze them with the verdict
            # (debounced/capped; no-op without an armed trace dir)
            from flink_ml_tpu.observability import flightrecorder

            flightrecorder.record_incident(
                "drift", servable=servable,
                drifted=",".join(result["drifted"]))
        except Exception:  # noqa: BLE001 — recording must never break
            # the evaluation (the ops controller acts on this verdict)
            pass
    with _lock:
        _last_results[servable] = result
    return result


def drift_report(emit: bool = False,
                 window_s: Optional[float] = None) -> dict:
    """Evaluate every servable with live sketches or an installed
    baseline — the ``/drift`` live route and the provenance seam."""
    with _lock:
        names = sorted(set(_windows) | set(_baselines) | set(_missing))
    servables = {name: evaluate(name, emit=emit, window_s=window_s)
                 for name in names}
    return {"servables": servables,
            "drifted": sorted(n for n, r in servables.items()
                              if r["drifted"]),
            "thresholds": thresholds()}


def provenance() -> dict:
    """``driftPsiMax`` (worst prediction/feature PSI across the last
    evaluations) + ``baselineVersion`` (newest installed) — benchmark
    row fields (scripts/serve_bench.py, bench.py one-liner). Nones when
    the process recorded no drift telemetry."""
    with _lock:
        results = list(_last_results.values())
        versions = [b.version for b in _baselines.values()
                    if b.version is not None]
    psis = [row["psi"] for r in results
            for row in r.get("series", {}).values()
            if math.isfinite(row.get("psi", float("nan")))]
    return {"driftPsiMax": (round(max(psis), 6) if psis else None),
            "baselineVersion": (max(versions) if versions else None)}


# -- fork boundary / artifacts ------------------------------------------------

def state_snapshot() -> dict:
    """Serializable live-sketch state — what a host-pool child ships
    back beside its metric snapshot (common/hostpool.py)."""
    with _lock:
        return {"servables": {
            name: {"live": win.window_json()}
            for name, win in _windows.items() if win.total}}


def merge_state(snap: dict) -> None:
    """Fold a child's :func:`state_snapshot` into this process — the
    drift twin of :meth:`MetricsRegistry.merge`; merged sketches land
    in the open window slice, so they are window-visible immediately."""
    for name, entry in (snap or {}).get("servables", {}).items():
        live = entry.get("live")
        if not live:
            continue
        win = _window_for(name)
        with _lock:
            win.merge(live)


def reseed_child() -> None:
    """Reset drift state in a freshly forked host-pool child WITHOUT
    touching the inherited lock (a driver thread may hold it at fork
    time — the metrics.reseed_child contract): the child's snapshot
    must hold only child-produced sketches. The installed BASELINES are
    kept — they are read-only reference data, and keeping them means a
    child's live sketches seed from the same bin edges as the driver's,
    so the fold back is bin-exact."""
    global _lock, _windows, _last_eval, _last_results
    _lock = make_lock("observability.drift")
    _windows = {}
    _last_eval = {}
    _last_results = {}
    # _tracked/_baselines stay: read-only reference data (see above)


def clear() -> None:
    """Drop all live drift state (tests)."""
    with _lock:
        _tracked.clear()
        _baselines.clear()
        _missing.clear()
        _windows.clear()
        _last_eval.clear()
        _last_results.clear()


def dump_state(trace_dir: str) -> Optional[str]:
    """Write this process's drift state as ``drift-<pid>.json``
    (``drift-p<k>-<pid>.json`` in a multi-process runtime —
    exporters.artifact_suffix) beside the metrics snapshots
    (exporters.dump_metrics calls this when the module is loaded);
    returns the path, or None when there is nothing to write."""
    with _lock:
        names = sorted(set(_windows) | set(_baselines) | set(_missing))
        if not names:
            return None
        doc = {"version": 1, "servables": {}}
        for name in names:
            win = _windows.get(name)
            base = _baselines.get(name)
            doc["servables"][name] = {
                "live": win.window_json() if win is not None else {},
                "baseline": base.to_json() if base is not None else None,
                "results": _last_results.get(name)}
    from flink_ml_tpu.observability.exporters import artifact_suffix

    os.makedirs(trace_dir, exist_ok=True)
    path = os.path.join(trace_dir, f"drift-{artifact_suffix()}.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    return path


def read_state(trace_dir: str) -> Dict[str, dict]:
    """Merge every ``drift-*.json`` in a trace dir:
    ``{servable: {"live": SketchGroup-json, "baseline": json|None,
    "results": json|None}}`` — the CLI's artifact reader. Torn files
    are skipped, like the metrics reader."""
    import glob

    merged: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "drift-*.json"))):
        if os.path.basename(path).startswith("drift-baseline-"):
            continue  # fit-side baseline artifacts have their own shape
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for name, entry in (doc.get("servables") or {}).items():
            row = merged.setdefault(
                name, {"live": SketchGroup(), "baseline": None,
                       "results": None})
            try:
                row["live"].merge(entry.get("live") or {})
            except ValueError:
                continue
            if entry.get("baseline"):
                row["baseline"] = entry["baseline"]
            if entry.get("results"):
                row["results"] = entry["results"]
    return merged


# -- the `flink-ml-tpu-trace drift` view --------------------------------------

def _artifact_verdicts(state: Dict[str, dict],
                       override: Optional[DriftBaseline],
                       thr: Dict[str, float],
                       min_count: int) -> List[dict]:
    verdicts = []
    for name in sorted(state):
        entry = state[name]
        base_doc = entry.get("baseline")
        baseline = override
        if baseline is None and base_doc:
            baseline = DriftBaseline.from_json(base_doc)
        live = entry["live"].to_json()
        row = {"servable": name,
               "source": "baseline" if baseline is not None
               else "missing",
               "baselineVersion": (baseline.version
                                   if baseline is not None else None),
               "series": {}, "drifted": []}
        if baseline is not None:
            for sname, bsnap in sorted(
                    baseline.group.to_json().items()):
                stats = compare_sketches(bsnap, live.get(sname, {}))
                if stats is None:
                    continue
                fresh = stats["live_n"] >= min_count
                over = [s for s in STAT_NAMES
                        if math.isfinite(stats[s])
                        and stats[s] > thr[s]]
                srow = dict(stats)
                srow["drifted"] = bool(fresh and over)
                srow["thin"] = not fresh
                srow["over"] = over if fresh else []
                row["series"][sname] = srow
                if srow["drifted"]:
                    row["drifted"].append(sname)
        verdicts.append(row)
    return verdicts


def _fmt_stat(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "-"
    if math.isnan(f):
        return "nan"
    return f"{f:.4f}"


def render_drift(verdicts: List[dict], thr: Dict[str, float]) -> str:
    drifted = sum(1 for v in verdicts if v["drifted"])
    out = [f"{len(verdicts)} servable(s), {drifted} drifted  "
           f"(thresholds: psi>{thr['psi']:g} js>{thr['js']:g} "
           f"ks>{thr['ks']:g})"]
    for v in verdicts:
        out.append("")
        ver = (f" baseline v{v['baselineVersion']}"
               if v.get("baselineVersion") is not None else "")
        flag = "DRIFTED" if v["drifted"] else (
            "no baseline" if v["source"] == "missing" else "ok")
        out.append(f"servable {v['servable']}{ver}  [{flag}]")
        if v["source"] == "missing":
            out.append("  source: missing — published without a "
                       "training-time baseline")
            continue
        out.append(f"  {'series':<14} {'psi':>8} {'js':>8} {'ks':>8} "
                   f"{'base n':>8} {'live n':>8}  verdict")
        for name, st in v["series"].items():
            # "thin" = below the sample floor: the truthful answer is
            # "not enough samples yet", never "ok"
            verdict = ("DRIFTED(" + ",".join(st["over"]) + ")"
                       if st["drifted"] else
                       ("thin" if st.get("thin") else "ok"))
            out.append(
                f"  {name:<14} {_fmt_stat(st['psi']):>8} "
                f"{_fmt_stat(st['js']):>8} {_fmt_stat(st['ks']):>8} "
                f"{st['baseline_n']:>8} {st['live_n']:>8}  {verdict}")
    return "\n".join(out)


def main(argv=None) -> int:
    """``flink-ml-tpu-trace drift <dir>`` — live-vs-baseline drift
    verdicts from a trace dir's ``drift-*.json`` artifacts.
    ``--baseline F`` overrides the artifact baselines with a serialized
    :class:`DriftBaseline` file (e.g. a fit's
    ``drift-baseline-<algo>.json``). ``--check`` exits 4 when any
    servable drifted, 2 on missing/broken artifacts; a servable that
    shipped without a baseline reports ``source: missing`` and exits 0
    — the absence of a baseline is a publishing gap, not drift."""
    import argparse

    from flink_ml_tpu.observability.exporters import (
        pipe_guard,
        resolve_trace_dir,
    )

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace drift",
        description="Drift verdicts (PSI / JS distance / KS) from a "
                    "FLINK_ML_TPU_TRACE_DIR's drift artifacts.")
    parser.add_argument("trace_dir")
    parser.add_argument("--baseline", metavar="FILE",
                        help="serialized DriftBaseline overriding the "
                             "artifact baselines for every servable")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 4 when any servable drifted, 2 on "
                             "broken artifacts")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    parser.add_argument("--psi", type=float, default=None,
                        help="PSI threshold (default env/0.25)")
    parser.add_argument("--js", type=float, default=None,
                        help="JS-distance threshold (default env/0.2)")
    parser.add_argument("--ks", type=float, default=None,
                        help="KS threshold (default env/0.25)")
    parser.add_argument("--min-count", type=int, default=None,
                        help="min live samples per series before a "
                             "verdict (default env/100)")
    args = parser.parse_args(argv)

    try:
        trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        state = read_state(trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace drift: cannot read "
              f"{args.trace_dir}: {e}", file=sys.stderr)
        return EXIT_INVALID
    override = None
    if args.baseline:
        try:
            override = load_baseline_file(args.baseline)
            if override is None:
                raise ValueError(f"{args.baseline}: no such file")
        except ValueError as e:
            print(f"flink-ml-tpu-trace drift: {e}", file=sys.stderr)
            return EXIT_INVALID
    if not state:
        print(f"flink-ml-tpu-trace drift: no drift-*.json artifacts "
              f"in {trace_dir}", file=sys.stderr)
        return EXIT_INVALID
    thr = thresholds()
    for stat in STAT_NAMES:
        flag = getattr(args, stat)
        if flag is not None:
            thr[stat] = float(flag)
    min_count = (args.min_count if args.min_count is not None
                 else _min_count())
    try:
        verdicts = _artifact_verdicts(state, override, thr, min_count)
    except ValueError as e:
        print(f"flink-ml-tpu-trace drift: {e}", file=sys.stderr)
        return EXIT_INVALID

    with pipe_guard():
        if args.json:
            # strict JSON: a baseline series never observed live has
            # NaN stats, and the bare NaN token breaks jq exactly when
            # someone is debugging coverage — render as strings (the
            # health --json precedent)
            from flink_ml_tpu.observability.health import _json_safe

            print(json.dumps(_json_safe({"trace_dir": trace_dir,
                                         "thresholds": thr,
                                         "min_count": min_count,
                                         "verdicts": verdicts}),
                             indent=2, default=str))
        else:
            print(render_drift(verdicts, thr))
    drifted = [v["servable"] for v in verdicts if v["drifted"]]
    if args.check and drifted:
        print(f"flink-ml-tpu-trace drift: {len(drifted)} drifted "
              f"servable(s): {', '.join(drifted)}", file=sys.stderr)
        return EXIT_DRIFTED
    return EXIT_OK


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
