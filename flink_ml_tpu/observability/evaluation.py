"""Continuous evaluation: streaming ground-truth quality joined to
live traffic, quality SLOs, and quality-gated canaries.

Every other live signal in the stack is a proxy — latency, drift,
liveness, device efficiency — and none of them measures whether the
model is actually *correct* on production traffic. A model whose labels
flip while its input distribution stays stable is invisible to the
drift plane (observability/drift.py) and the ops controller alike. This
module closes that gap with the same mergeable-aggregation shape drift
uses ("Iterative MapReduce for Large Scale ML", arXiv:1303.3517), plus
the delayed-label staleness accounting of "Just-in-Time Aggregation for
Federated Learning" (arXiv:2208.09740): ground truth arrives late, so
coverage and lag are first-class telemetry, not footnotes.

Three layers (docs/observability.md "Continuous evaluation"):

- **Sketch** (:class:`QualitySketch`): fixed-bin score histograms per
  label class — one :class:`~flink_ml_tpu.observability.drift
  .StreamingSketch` for positives, one for negatives, both seeded with
  the same frozen [0, 1] bin edges so every merge is bin-exact (the
  drift-baseline idiom) — plus an exact logloss accumulator. Streaming
  AUC (the tie-corrected Mann-Whitney sum, i.e. trapezoidal over the
  binned ROC), logloss, accuracy/precision/recall at a configurable
  threshold and expected calibration error are all *derived* from the
  sketch; ``merge``/``to_json``/``from_json`` fold across the host-pool
  fork, multi-process artifacts, and fleet beacons exactly like drift
  state.
- **Join** (:func:`record_feedback`): delayed ground-truth labels join
  a bounded ring of recent predictions captured at the ``_served`` seam
  (keyed by the causal-trace ``req`` ordinal the batcher mints), routed
  into per-servable-VERSION quality windows like drift state. The ring
  is capped and evicted with lag/coverage telemetry
  (``ml.quality labelLagMs`` / ``feedbackCoverage{servable=}``), and a
  fit-time quality baseline (:func:`capture_fit_baseline`) rides the
  checkpoint's atomic rename as ``quality-baseline.json`` beside the
  drift baseline.
- **Actuate** (:func:`evaluate`): windowed ``ml.quality`` gauges and
  :data:`QUALITY_EVENT` instant events, the ``quality`` SLO objective
  kind (observability/slo.py — live AUC floor / delta-vs-baseline,
  process and fleet scope), the ``/quality`` live route
  (observability/server.py), the ``flink-ml-tpu-trace quality`` CLI
  (exit 4 degraded / 2 broken artifacts, consistent with
  ``drift``/``slo``), and the OpsController's canary quality stage
  (serving/controller.py): a canary is judged on its live AUC vs its
  published quality baseline, thin-window = insufficient evidence.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import tracing
from flink_ml_tpu.observability.drift import StreamingSketch

__all__ = [
    "QUALITY_ENV",
    "QUALITY_EVENT",
    "BASELINE_FILENAME",
    "QualitySketch",
    "QualityBaseline",
    "enabled",
    "capture_armed",
    "score_edges",
    "positive_scores",
    "capture_fit_baseline",
    "load_baseline_file",
    "install_baseline",
    "forget_servable",
    "baseline_for",
    "observe_served",
    "record_feedback",
    "evaluate",
    "quality_report",
    "provenance",
    "quality_thresholds",
    "state_snapshot",
    "merge_state",
    "reseed_child",
    "dump_state",
    "read_state",
    "clear",
    "main",
]

#: "0" disables the whole layer (join ring AND fit-time capture); any
#: other non-empty value force-arms fit-time capture even without a
#: trace dir (the join ring is on by default — it is the serving half)
QUALITY_ENV = "FLINK_ML_TPU_QUALITY"
#: evaluator cadence in seconds (0 = every joined label; default 30)
INTERVAL_ENV = "FLINK_ML_TPU_QUALITY_INTERVAL_S"
#: live quality window in seconds (default 300)
WINDOW_ENV = "FLINK_ML_TPU_QUALITY_WINDOW_S"
#: live AUC floor — below it a fresh window is *degraded*
MIN_AUC_ENV = "FLINK_ML_TPU_QUALITY_MIN_AUC"
#: max tolerated (baseline AUC - live AUC) before *degraded*
MAX_DELTA_ENV = "FLINK_ML_TPU_QUALITY_MAX_AUC_DELTA"
#: minimum joined labels per servable before a verdict is rendered
MIN_LABELS_ENV = "FLINK_ML_TPU_QUALITY_MIN_LABELS"
#: join-ring capacity (predictions awaiting feedback, process-wide)
RING_ENV = "FLINK_ML_TPU_QUALITY_RING"
#: decision threshold for accuracy/precision/recall
THRESHOLD_ENV = "FLINK_ML_TPU_QUALITY_THRESHOLD"

#: instant-event name for detected quality degradation in the trace
QUALITY_EVENT = "ml.quality"

#: the baseline artifact filename beside a checkpoint's manifest.json
#: (rides ``CheckpointManager.save(extras=)`` next to drift-baseline)
BASELINE_FILENAME = "quality-baseline.json"

#: exit codes (shared convention with diff/slo/drift: 4 = gate fired,
#: 2 = broken artifacts)
EXIT_OK = 0
EXIT_INVALID = 2
EXIT_DEGRADED = 4

#: score-histogram bins. Scores are probabilities, so the bin edges are
#: the SAME frozen [0, 1] grid in every process — merges across the
#: fork, artifacts and beacons are bin-exact by construction, no
#: auto-ranging warmup to disagree about. 64 bins keep the binned-ROC
#: trapezoid within ~1e-3 of the exact AUC at serving sample sizes
#: while 0.5 stays an exact edge for the default decision threshold.
DEFAULT_BINS = 64

_DEFAULTS = {MIN_AUC_ENV: 0.6, MAX_DELTA_ENV: 0.1,
             INTERVAL_ENV: 30.0, WINDOW_ENV: 300.0,
             THRESHOLD_ENV: 0.5}

#: logloss clamp — a hard 0/1 score would otherwise contribute inf
_EPS = 1e-12


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


def enabled() -> bool:
    """The live tier: prediction capture + feedback join on the serving
    seam. On by default; ``FLINK_ML_TPU_QUALITY=0`` is the kill
    switch."""
    return os.environ.get(QUALITY_ENV, "") != "0"


def capture_armed() -> bool:
    """The fit-time tier: quality-baseline capture at the end of a fit.
    Armed when a trace dir is configured or ``FLINK_ML_TPU_QUALITY`` is
    truthy (mirrors drift.capture_armed — a plain untraced fit stays
    zero-cost); ``FLINK_ML_TPU_QUALITY=0`` disables it."""
    env = os.environ.get(QUALITY_ENV, "")
    if env == "0":
        return False
    return bool(env) or tracing.tracer.enabled


def quality_thresholds() -> Dict[str, float]:
    """The quality-verdict thresholds (env-tunable)."""
    return {"minAuc": _env_float(MIN_AUC_ENV, _DEFAULTS[MIN_AUC_ENV]),
            "maxAucDelta": _env_float(MAX_DELTA_ENV,
                                      _DEFAULTS[MAX_DELTA_ENV])}


def _min_labels() -> int:
    # below ~100 joined labels the binned AUC estimate is noisy enough
    # that a healthy window can brush the floor
    return _env_int(MIN_LABELS_ENV, 100)


def _ring_capacity() -> int:
    return _env_int(RING_ENV, 4096)


def decision_threshold() -> float:
    return _env_float(THRESHOLD_ENV, _DEFAULTS[THRESHOLD_ENV])


def score_edges(bins: int = DEFAULT_BINS) -> tuple:
    """The frozen [0, 1] score-bin grid every quality sketch shares."""
    return tuple(float(x) for x in np.linspace(0.0, 1.0, bins + 1))


# -- the mergeable quality sketch ---------------------------------------------

class QualitySketch:
    """Mergeable streaming summary of (score, binary label) pairs: one
    fixed-bin :class:`StreamingSketch` score histogram per label class
    (both seeded with the same frozen [0, 1] edges, so merges are
    bin-exact) plus an exact logloss sum. AUC, logloss,
    accuracy/precision/recall at a threshold and expected calibration
    error are all derived views of the same state — no second
    bookkeeping to drift out of sync. Thread-safety lives one level up
    (the live window holds the lock), like :class:`StreamingSketch`."""

    __slots__ = ("pos", "neg", "logloss_sum", "nonbinary")

    def __init__(self, edges: Optional[Sequence[float]] = None):
        e = tuple(float(x) for x in edges) if edges is not None \
            else score_edges()
        self.pos = StreamingSketch(edges=e)
        self.neg = StreamingSketch(edges=e)
        self.logloss_sum = 0.0
        self.nonbinary = 0

    # -- observation ---------------------------------------------------------
    def observe(self, scores, labels) -> None:
        """Fold (score, label) pairs in. Scores are positive-class
        probabilities; labels coerce to {0, 1} (anything else is
        tallied in ``nonbinary`` and dropped — the seam must never
        raise on a malformed feedback payload)."""
        s = np.asarray(scores, np.float64).ravel()
        y = np.asarray(labels, np.float64).ravel()
        if y.size == 1 and s.size > 1:
            y = np.full(s.size, float(y[0]))
        n = min(s.size, y.size)
        if n == 0:
            return
        s, y = s[:n], y[:n]
        ok = np.isfinite(s) & ((y == 0.0) | (y == 1.0))
        self.nonbinary += int(n - ok.sum())
        s, y = s[ok], y[ok]
        if s.size == 0:
            return
        pos = y == 1.0
        self.pos.observe_many(s[pos])
        self.neg.observe_many(s[~pos])
        p = np.clip(s, _EPS, 1.0 - _EPS)
        self.logloss_sum += float(
            -np.sum(y * np.log(p) + (1.0 - y) * np.log(1.0 - p)))

    # -- derived -------------------------------------------------------------
    @property
    def n(self) -> int:
        return self.pos.count + self.neg.count

    def _class_bins(self, sk: StreamingSketch) -> np.ndarray:
        # underflow + bins + overflow: the tails carry out-of-[0,1]
        # scores (a miscalibrated head) instead of silently vanishing
        return np.asarray([sk.underflow] + list(sk.counts)
                          + [sk.overflow], np.float64)

    def auc(self) -> float:
        """Streaming AUC: the tie-corrected Mann-Whitney sum over the
        shared bins — exactly the trapezoidal area under the binned
        ROC. NaN until both classes have mass."""
        p = self._class_bins(self.pos)
        q = self._class_bins(self.neg)
        pt, qt = float(p.sum()), float(q.sum())
        if pt <= 0 or qt <= 0:
            return float("nan")
        # negatives strictly below each bin count fully; same-bin
        # negatives count half (the trapezoid through a tied bin)
        below = np.concatenate(([0.0], np.cumsum(q)[:-1]))
        return float(np.sum(p * (below + q / 2.0)) / (pt * qt))

    def logloss(self) -> float:
        return self.logloss_sum / self.n if self.n else float("nan")

    def confusion(self, threshold: Optional[float] = None
                  ) -> Dict[str, int]:
        """tp/fp/tn/fn at ``threshold`` (snapped to the nearest bin
        edge — exact for the default 0.5 on the frozen grid)."""
        thr = decision_threshold() if threshold is None else threshold
        e = np.asarray(self.pos.edges)
        k = int(np.argmin(np.abs(e - thr)))
        pos_hi = int(sum(self.pos.counts[k:]) + self.pos.overflow)
        neg_hi = int(sum(self.neg.counts[k:]) + self.neg.overflow)
        return {"tp": pos_hi, "fn": self.pos.count - pos_hi,
                "fp": neg_hi, "tn": self.neg.count - neg_hi}

    def calibration_error(self) -> float:
        """Expected calibration error: per-bin |positive fraction -
        bin-midpoint confidence| weighted by bin mass (the standard
        binned ECE; tails anchor at their own edge)."""
        p = self._class_bins(self.pos)
        q = self._class_bins(self.neg)
        tot = p + q
        n = float(tot.sum())
        if n <= 0:
            return float("nan")
        e = np.asarray(self.pos.edges)
        conf = np.concatenate(([e[0]], (e[:-1] + e[1:]) / 2.0,
                               [e[-1]]))
        mask = tot > 0
        frac = p[mask] / tot[mask]
        return float(np.sum(tot[mask] * np.abs(frac - conf[mask])) / n)

    def quality_metrics(self, threshold: Optional[float] = None
                        ) -> dict:
        """Every derived metric in one dict — the evaluation row."""
        thr = decision_threshold() if threshold is None else threshold
        c = self.confusion(thr)
        n = self.n
        tp, fp, tn, fn = c["tp"], c["fp"], c["tn"], c["fn"]
        div = lambda a, b: (a / b) if b else float("nan")  # noqa: E731
        return {"n": n,
                "positives": self.pos.count,
                "negatives": self.neg.count,
                "auc": self.auc(),
                "logloss": self.logloss(),
                "threshold": thr,
                "accuracy": div(tp + tn, n),
                "precision": div(tp, tp + fp),
                "recall": div(tp, tp + fn),
                "calibrationError": self.calibration_error(),
                "nonbinary": self.nonbinary}

    # -- merge / serialization -----------------------------------------------
    def merge(self, snap) -> None:
        """Fold another quality sketch (object or ``to_json`` dict) in
        — bin-exact when edges match (always true on the frozen grid;
        the :meth:`StreamingSketch.merge` contract covers the rest)."""
        if isinstance(snap, QualitySketch):
            snap = snap.to_json()
        self.pos.merge(snap.get("pos") or {})
        self.neg.merge(snap.get("neg") or {})
        self.logloss_sum += float(snap.get("loglossSum", 0.0))
        self.nonbinary += int(snap.get("nonbinary", 0))

    def to_json(self) -> dict:
        return {"version": 1,
                "pos": self.pos.to_json(),
                "neg": self.neg.to_json(),
                "loglossSum": self.logloss_sum,
                "nonbinary": self.nonbinary}

    @classmethod
    def from_json(cls, snap: dict) -> "QualitySketch":
        edges = (snap.get("pos") or {}).get("edges")
        sk = cls(edges=edges)
        sk.merge(snap or {})
        return sk


# -- the training-time quality baseline ---------------------------------------

class QualityBaseline:
    """A fitted model's training-time quality summary — the final
    model's scores on a (row-capped) training sample vs the true
    labels, with the model/version provenance the hot-swap keys on.
    The live canary verdict anchors on its AUC."""

    def __init__(self, model: str, version: Optional[int] = None,
                 sketch: Optional[QualitySketch] = None,
                 created_unix: Optional[float] = None):
        self.model = model
        self.version = None if version is None else int(version)
        self.sketch = sketch or QualitySketch()
        self.created_unix = (time.time() if created_unix is None
                             else float(created_unix))

    def edges_template(self) -> tuple:
        """The frozen score-bin edges live sketches seed from."""
        return self.sketch.pos.edges or score_edges()

    def to_json(self) -> dict:
        return {"version": 1, "model": self.model,
                "modelVersion": self.version,
                "created_unix": self.created_unix,
                "sketch": self.sketch.to_json()}

    @classmethod
    def from_json(cls, doc: dict) -> "QualityBaseline":
        if not isinstance(doc, dict) or "sketch" not in doc:
            raise ValueError(
                "quality baseline document must be a mapping with a "
                "'sketch' key")
        return cls(model=str(doc.get("model", "?")),
                   version=doc.get("modelVersion"),
                   sketch=QualitySketch.from_json(doc["sketch"]),
                   created_unix=doc.get("created_unix"))


def positive_scores(raw_values=None, predictions=None
                    ) -> Optional[np.ndarray]:
    """The positive-class probability per row from a transform's
    output: the raw-prediction vectors' LAST element (the LR servable's
    ``[1-p, p]`` shape) when available, else the thresholded prediction
    column (a degenerate {0, 1} score — still rankable). None when
    neither reduces to numbers — the seam must never raise."""
    if raw_values is not None:
        try:
            first = raw_values[0]
        except (IndexError, TypeError):
            first = None
        if first is not None and hasattr(first, "to_array"):
            try:
                return np.asarray(
                    [float(np.asarray(v.to_array()).ravel()[-1])
                     for v in raw_values], np.float64)
            except (TypeError, ValueError, IndexError):
                pass
        elif first is not None:
            try:
                arr = np.asarray(raw_values, np.float64)
                if arr.ndim == 2:
                    return arr[:, -1]
                if arr.ndim == 1:
                    return arr
            except (TypeError, ValueError):
                pass
    if predictions is not None:
        try:
            return np.asarray(list(predictions), np.float64).ravel()
        except (TypeError, ValueError):
            return None
    return None


def capture_fit_baseline(model, algo: str, scores=None, labels=None,
                         version: Optional[int] = None
                         ) -> Optional[QualityBaseline]:
    """Build the training-time quality baseline from the final model's
    scores on a (row-capped) training sample and the matching labels,
    attach it to the fitted model as ``model.quality_baseline``, and
    record the capture (``ml.quality baselineCaptured{algo=}`` counter
    + a trace-dir ``quality-baseline-<algo>.json`` artifact when
    tracing is armed). Returns the baseline (None when there was
    nothing to sketch). Never raises past its own logging — a baseline
    failure must not fail the fit that produced the model."""
    sketch = QualitySketch()
    if scores is not None and labels is not None:
        sketch.observe(scores, labels)
    if not sketch.n:
        return None
    baseline = QualityBaseline(algo, version=version, sketch=sketch)
    try:
        model.quality_baseline = baseline
    except AttributeError:
        pass  # __slots__ model: the caller still gets the return value
    metrics.group(ML_GROUP, "quality").counter(
        "baselineCaptured", labels={"algo": algo})
    if tracing.tracer.enabled:
        try:
            path = os.path.join(tracing.tracer.trace_dir,
                                f"quality-baseline-{algo}.json")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(baseline.to_json(), f)
            os.replace(tmp, path)
        except OSError:
            pass  # artifact only; the in-memory baseline is attached
    return baseline


def load_baseline_file(path: str) -> Optional[QualityBaseline]:
    """Read a serialized quality baseline (the checkpoint-side artifact
    or a ``--baseline`` override); None when the file does not exist,
    raises ValueError on an unreadable/malformed document."""
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(
            f"{path}: unreadable quality baseline: {e}") from e
    return QualityBaseline.from_json(doc)


# -- live state: join ring + quality windows ----------------------------------

class _QualityWindow:
    """Sliding window of joined (score, label) quality sketches for one
    servable: a ring of closed :class:`QualitySketch` slices plus the
    open one, rotated lazily (the drift ``_LiveWindow`` shape). Slices
    share the frozen score grid, so in-window merges are bit-exact."""

    def __init__(self, horizon_s: float, slices: int = 30,
                 edges: Optional[tuple] = None, clock=time.monotonic):
        self.horizon_s = float(horizon_s)
        self._slice_s = self.horizon_s / max(1, int(slices))
        self._edges = tuple(edges) if edges is not None \
            else score_edges()
        self._clock = clock
        self._ring: List[tuple] = []  # (t_closed, QualitySketch)
        self._current = QualitySketch(edges=self._edges)
        self._last_slice = clock()
        self.total = 0  # joins ever (cheap freshness probe)

    def _rotate(self, now: float) -> None:
        if now - self._last_slice < self._slice_s:
            return
        if self._current.n or self._current.nonbinary:
            self._ring.append((now, self._current))
            self._current = QualitySketch(edges=self._edges)
        self._last_slice = now
        cutoff = now - self.horizon_s
        while self._ring and self._ring[0][0] <= cutoff:
            self._ring.pop(0)

    def observe(self, scores, labels) -> None:
        self._rotate(self._clock())
        self._current.observe(scores, labels)
        self.total += 1

    def merge(self, snap: dict) -> None:
        """Fold a child-process sketch snapshot into the open slice (so
        merged labels are window-visible from merge time — the
        WindowedCounter contract)."""
        self._rotate(self._clock())
        self._current.merge(snap)
        self.total += 1

    def window_sketch(self, window_s: Optional[float] = None
                      ) -> QualitySketch:
        w = self.horizon_s if window_s is None \
            else min(float(window_s), self.horizon_s)
        now = self._clock()
        self._rotate(now)
        cutoff = now - w
        merged = QualitySketch(edges=self._edges)
        for t, sk in self._ring:
            if t > cutoff:
                merged.merge(sk.to_json())
        merged.merge(self._current.to_json())
        return merged


_lock = make_lock("observability.evaluation")
_baselines: Dict[str, QualityBaseline] = {}
_missing: set = set()       # servables that swapped in without a baseline
_windows: Dict[str, _QualityWindow] = {}
#: the join ring: request ordinal → (servable, scores, t_served). One
#: process-wide ring (feedback callers hold a request id, not a
#: servable name); entries carry the VERSIONED serving name so joins
#: land in that version's window. Bounded by FLINK_ML_TPU_QUALITY_RING.
_ring: "OrderedDict[int, tuple]" = OrderedDict()
#: recently evicted request ids (servable-tagged) — a late label for
#: one of these is "late", not "unknown": honest staleness accounting
_evicted: "OrderedDict[int, str]" = OrderedDict()
#: per-servable join/coverage tallies (lifetime, snapshot-mergeable)
_coverage: Dict[str, Dict[str, int]] = {}
#: recent label lags in ms (provenance p99), process-wide
_lags: deque = deque(maxlen=1024)
_last_eval: Dict[str, float] = {}
_last_results: Dict[str, dict] = {}
#: insertion-ordered registry of tracked servable names — the eviction
#: order (the drift MAX_TRACKED_SERVABLES rationale: a continuously
#: republishing deployment mints a new versioned name per hot-swap)
_tracked: Dict[str, None] = {}
MAX_TRACKED_SERVABLES = 64


def _track_locked(servable: str) -> None:
    """Mark ``servable`` as live (most-recently tracked) and evict the
    oldest tracked names past :data:`MAX_TRACKED_SERVABLES`. Caller
    holds ``_lock``."""
    _tracked.pop(servable, None)
    _tracked[servable] = None
    while len(_tracked) > MAX_TRACKED_SERVABLES:
        old = next(iter(_tracked))
        if old == servable:  # never evict the name just touched
            break
        _tracked.pop(old)
        _baselines.pop(old, None)
        _missing.discard(old)
        _windows.pop(old, None)
        _coverage.pop(old, None)
        _last_eval.pop(old, None)
        _last_results.pop(old, None)


def _coverage_locked(servable: str) -> Dict[str, int]:
    cov = _coverage.get(servable)
    if cov is None:
        cov = _coverage[servable] = {
            "predictions": 0, "joined": 0, "evicted": 0, "late": 0}
    return cov


def forget_servable(servable: str) -> None:
    """Drop all quality state for one servable — a rejected hot-swap
    candidate whose versioned name will never serve (serving/
    registry.py), or a caller retiring an old version early."""
    with _lock:
        _tracked.pop(servable, None)
        _baselines.pop(servable, None)
        _missing.discard(servable)
        _windows.pop(servable, None)
        _coverage.pop(servable, None)
        _last_eval.pop(servable, None)
        _last_results.pop(servable, None)
        for rid in [r for r, entry in _ring.items()
                    if entry[0] == servable]:
            _ring.pop(rid, None)


def install_baseline(servable: str,
                     baseline: Optional[QualityBaseline]) -> None:
    """Install (or record as missing) the quality baseline the live
    verdict for ``servable`` anchors on — called by the serving
    registry's hot-swap with the baseline shipped beside that version's
    checkpoint manifest. Keyed by the *versioned* serving name
    (``lr@v2``), like drift baselines."""
    with _lock:
        _track_locked(servable)
        if baseline is None:
            _missing.add(servable)
            _baselines.pop(servable, None)
        else:
            _missing.discard(servable)
            _baselines[servable] = baseline
    metrics.group(ML_GROUP, "quality").gauge(
        "baselineInstalled", 0 if baseline is None else 1,
        labels={"servable": servable})


def baseline_for(servable: str) -> Optional[QualityBaseline]:
    with _lock:
        return _baselines.get(servable)


def _window_for_locked(servable: str) -> _QualityWindow:
    win = _windows.get(servable)
    if win is None:
        _track_locked(servable)
        base = _baselines.get(servable)
        win = _windows[servable] = _QualityWindow(
            _env_float(WINDOW_ENV, _DEFAULTS[WINDOW_ENV]),
            edges=(base.edges_template()
                   if base is not None else None))
    return win


def observe_served(servable: str, scores, segments=None) -> None:
    """The serving seam (servable/api.py ``_served``): park each
    request's positive-class scores in the join ring keyed by the
    batcher's ``req`` ordinal, awaiting :func:`record_feedback`.
    ``segments`` is the batcher's per-request ``(seq, rows)`` layout
    (``df.request_segments``); without it there are no request ids to
    join on (a direct transform, a canary probe) and nothing is
    recorded — such rows must not sink coverage either. Quietly does
    nothing when disabled — recording must never sink a serving
    call."""
    if not enabled() or not segments:
        return
    arr = positive_scores(raw_values=None, predictions=scores) \
        if not isinstance(scores, np.ndarray) else scores
    if arr is None or arr.size == 0:
        return
    cap = _ring_capacity()
    now = time.monotonic()
    grp = metrics.group(ML_GROUP, "quality")
    evictions = 0
    with _lock:
        cov = _coverage_locked(servable)
        offset = 0
        for seq, rows in segments:
            chunk = arr[offset:offset + int(rows)]
            offset += int(rows)
            if chunk.size == 0:
                continue
            _ring[int(seq)] = (servable, chunk, now)
            cov["predictions"] += 1
        while len(_ring) > cap:
            rid, (sname, _, _) = _ring.popitem(last=False)
            _evicted[rid] = sname
            _coverage_locked(sname)["evicted"] += 1
            evictions += 1
        while len(_evicted) > cap:
            _evicted.popitem(last=False)
    if evictions:
        grp.counter("ringEvicted", evictions,
                    labels={"servable": servable})


def record_feedback(request_id: int, label) -> bool:
    """Join one delayed ground-truth label (scalar, broadcast across
    the request's rows, or a per-row sequence) to the prediction parked
    under ``request_id`` — the ordinal ``MicroBatcher.submit`` attached
    to the returned future as ``future.request_id``. Feeds the
    servable-version's quality window plus the staleness telemetry
    (``labelLagMs`` windowed histogram, ``labelsJoined`` /
    ``labelsLate`` / ``feedbackUnknown`` counters). Returns True when
    the join landed; False for a label that arrived after eviction
    (late) or for an id never seen (unknown)."""
    if not enabled():
        return False
    grp = metrics.group(ML_GROUP, "quality")
    with _lock:
        entry = _ring.pop(int(request_id), None)
        if entry is None:
            late_servable = _evicted.pop(int(request_id), None)
            if late_servable is not None:
                _coverage_locked(late_servable)["late"] += 1
        else:
            servable, chunk, t_served = entry
            lag_ms = (time.monotonic() - t_served) * 1000.0
            win = _window_for_locked(servable)
            win.observe(chunk, label)
            cov = _coverage_locked(servable)
            cov["joined"] += 1
            _lags.append(lag_ms)
    if entry is None:
        if late_servable is not None:
            grp.counter("labelsLate",
                        labels={"servable": late_servable})
        else:
            grp.counter("feedbackUnknown")
        return False
    grp.counter("labelsJoined", labels={"servable": servable})
    grp.windowed_histogram("labelLagMs", horizon_s=300.0,
                           slices=30,
                           labels={"servable": servable}).observe(
                               lag_ms)
    maybe_evaluate(servable)
    return True


def maybe_evaluate(servable: str) -> Optional[dict]:
    """Run :func:`evaluate` when the cadence
    (``FLINK_ML_TPU_QUALITY_INTERVAL_S``) has lapsed for this servable;
    the fast path is one clock read + dict lookup."""
    interval = _env_float(INTERVAL_ENV, _DEFAULTS[INTERVAL_ENV])
    now = time.monotonic()
    with _lock:
        last = _last_eval.get(servable)
        if last is not None and now - last < interval:
            return None
        _last_eval[servable] = now
    return evaluate(servable)


def _coverage_row(cov: Dict[str, int]) -> dict:
    preds = cov.get("predictions", 0)
    joined = cov.get("joined", 0)
    return {"predictions": preds, "joined": joined,
            "evicted": cov.get("evicted", 0),
            "late": cov.get("late", 0),
            "coverage": (joined / preds) if preds else None}


def _lag_p99_locked() -> Optional[float]:
    if not _lags:
        return None
    return round(float(np.percentile(np.asarray(_lags, np.float64),
                                     99.0)), 3)


def evaluate(servable: str, emit: bool = True,
             window_s: Optional[float] = None) -> dict:
    """Judge ``servable``'s joined-label quality window: live AUC /
    logloss / accuracy / calibration vs the installed quality baseline,
    recorded as ``quality{servable=,metric=}`` gauges in ``ml.quality``
    (plus ``qualityBaseline{servable=,metric=}`` for the anchor and
    ``feedbackCoverage{servable=}``). Below the live AUC floor — or
    past the allowed delta under the baseline's AUC — with the
    ``FLINK_ML_TPU_QUALITY_MIN_LABELS`` sample floor met, the servable
    is *degraded*: with ``emit``, a :data:`QUALITY_EVENT` instant event
    + the ``violations{servable=}`` counter land, and the flight
    recorder freezes the moment. A thin window (too few joined labels)
    is *insufficient evidence*, never a verdict — the drift
    precedent."""
    with _lock:
        base = _baselines.get(servable)
        win = _windows.get(servable)
        sketch = win.window_sketch(window_s) if win is not None \
            else QualitySketch()
        cov = dict(_coverage_locked(servable))
        lag_p99 = _lag_p99_locked()
    thr = quality_thresholds()
    live = sketch.quality_metrics()
    base_metrics = (base.sketch.quality_metrics()
                    if base is not None else None)
    fresh = live["n"] >= _min_labels()
    over: List[str] = []
    auc = live["auc"]
    if fresh and math.isfinite(auc):
        if auc < thr["minAuc"]:
            over.append("min-auc")
        if (base_metrics is not None
                and math.isfinite(base_metrics["auc"])
                and base_metrics["auc"] - auc > thr["maxAucDelta"]):
            over.append("auc-delta")
    degraded = bool(fresh and over)
    result = {"servable": servable,
              "source": "baseline" if base is not None else "missing",
              "baselineVersion": (base.version
                                  if base is not None else None),
              "thresholds": thr,
              "minLabels": _min_labels(),
              "live": live,
              "baseline": base_metrics,
              "aucDelta": (round(base_metrics["auc"] - auc, 6)
                           if base_metrics is not None
                           and math.isfinite(auc)
                           and math.isfinite(base_metrics["auc"])
                           else None),
              "coverage": _coverage_row(cov),
              "labelLagP99Ms": lag_p99,
              "degraded": degraded,
              "thin": not fresh,
              "over": over if fresh else [],
              "evaluated_unix": time.time()}
    group = metrics.group(ML_GROUP, "quality")
    if fresh:
        # gauges carry the same sample floor as the verdict: a thin
        # window's AUC is noise, and the quality SLO kind consumes
        # these gauges raw — publishing them would flip /slo to
        # VIOLATED on a service whose labels just started arriving
        for metric in ("auc", "logloss", "accuracy", "precision",
                       "recall", "calibrationError"):
            v = live[metric]
            if v is not None and math.isfinite(v):
                group.gauge("quality", round(v, 6),
                            labels={"servable": servable,
                                    "metric": metric})
        if base_metrics is not None \
                and math.isfinite(base_metrics["auc"]):
            group.gauge("qualityBaseline",
                        round(base_metrics["auc"], 6),
                        labels={"servable": servable,
                                "metric": "auc"})
    covr = result["coverage"]["coverage"]
    if covr is not None:
        group.gauge("feedbackCoverage", round(covr, 4),
                    labels={"servable": servable})
    if degraded and emit:
        group.counter("violations", labels={"servable": servable})
        tracing.tracer.event(
            QUALITY_EVENT, servable=servable, over=",".join(over),
            auc=round(auc, 6) if math.isfinite(auc) else None,
            baselineAuc=(round(base_metrics["auc"], 6)
                         if base_metrics is not None else None),
            n=live["n"])
        try:
            # flight recorder (observability/flightrecorder.py): the
            # joined window and span ring that explain the regression
            # are rotating state — freeze them with the verdict
            # (debounced/capped; no-op without an armed trace dir)
            from flink_ml_tpu.observability import flightrecorder

            flightrecorder.record_incident(
                "quality", servable=servable, over=",".join(over))
        except Exception:  # noqa: BLE001 — recording must never break
            # the evaluation (the ops controller acts on this verdict)
            pass
    with _lock:
        _last_results[servable] = result
    return result


def quality_report(emit: bool = False,
                   window_s: Optional[float] = None) -> dict:
    """Evaluate every servable with joined labels or an installed
    baseline — the ``/quality`` live route and the provenance seam."""
    with _lock:
        names = sorted(set(_windows) | set(_baselines) | set(_missing))
    servables = {name: evaluate(name, emit=emit, window_s=window_s)
                 for name in names}
    return {"servables": servables,
            "degraded": sorted(n for n, r in servables.items()
                               if r["degraded"]),
            "thresholds": quality_thresholds()}


def provenance() -> dict:
    """``aucLive`` (worst fresh live AUC across the last evaluations),
    ``feedbackCoverage`` (worst) and ``labelLagP99Ms`` — benchmark row
    fields (scripts/serve_bench.py, bench.py one-liner). Nones when no
    feedback flowed (the shared-schema rule: the fields are always
    present, null when the plane is dark)."""
    with _lock:
        results = list(_last_results.values())
        lag_p99 = _lag_p99_locked()
    aucs = [r["live"]["auc"] for r in results
            if not r.get("thin")
            and math.isfinite(r["live"].get("auc", float("nan")))]
    covs = [r["coverage"]["coverage"] for r in results
            if r["coverage"].get("coverage") is not None]
    return {"aucLive": (round(min(aucs), 6) if aucs else None),
            "feedbackCoverage": (round(min(covs), 4)
                                 if covs else None),
            "labelLagP99Ms": lag_p99}


# -- fork boundary / artifacts ------------------------------------------------

def state_snapshot() -> dict:
    """Serializable joined-quality state — what a host-pool child ships
    back beside its metric snapshot (common/hostpool.py). Carries the
    window sketch, the coverage tallies and the recent lags; the join
    RING does not travel (an unjoined prediction's feedback arrives in
    the process that parked it)."""
    with _lock:
        servables = {}
        for name, win in _windows.items():
            if not win.total:
                continue
            servables[name] = {
                "sketch": win.window_sketch().to_json(),
                "coverage": dict(_coverage_locked(name))}
        return {"servables": servables,
                "lags": [round(v, 3) for v in _lags]}


def merge_state(snap: dict) -> None:
    """Fold a child's :func:`state_snapshot` into this process — the
    quality twin of :meth:`MetricsRegistry.merge`; merged sketches land
    in the open window slice, so they are window-visible
    immediately."""
    for name, entry in (snap or {}).get("servables", {}).items():
        sketch = entry.get("sketch")
        with _lock:
            win = _window_for_locked(name)
            if sketch:
                win.merge(sketch)
            cov = _coverage_locked(name)
            for key, val in (entry.get("coverage") or {}).items():
                if key in cov:
                    cov[key] += int(val)
    with _lock:
        for lag in (snap or {}).get("lags", ()):
            _lags.append(float(lag))


def reseed_child() -> None:
    """Reset quality state in a freshly forked host-pool child WITHOUT
    touching the inherited lock (a driver thread may hold it at fork
    time — the metrics.reseed_child contract): the child's snapshot
    must hold only child-produced joins. The installed BASELINES are
    kept — read-only reference data, and keeping them means a child's
    windows seed from the same score grid as the driver's, so the fold
    back is bin-exact."""
    global _lock, _windows, _ring, _evicted, _coverage, _lags
    global _last_eval, _last_results
    _lock = make_lock("observability.evaluation")
    _windows = {}
    _ring = OrderedDict()
    _evicted = OrderedDict()
    _coverage = {}
    _lags = deque(maxlen=1024)
    _last_eval = {}
    _last_results = {}
    # _tracked/_baselines stay: read-only reference data (see above)


def clear() -> None:
    """Drop all live quality state (tests)."""
    with _lock:
        _tracked.clear()
        _baselines.clear()
        _missing.clear()
        _windows.clear()
        _ring.clear()
        _evicted.clear()
        _coverage.clear()
        _lags.clear()
        _last_eval.clear()
        _last_results.clear()


def dump_state(trace_dir: str) -> Optional[str]:
    """Write this process's quality state as ``quality-<pid>.json``
    (``quality-p<k>-<pid>.json`` in a multi-process runtime —
    exporters.artifact_suffix) beside the metrics snapshots
    (exporters.dump_metrics calls this when the module is loaded);
    returns the path, or None when there is nothing to write."""
    with _lock:
        names = sorted(set(_windows) | set(_baselines) | set(_missing))
        if not names:
            return None
        doc = {"version": 1, "lagP99Ms": _lag_p99_locked(),
               "servables": {}}
        for name in names:
            win = _windows.get(name)
            base = _baselines.get(name)
            doc["servables"][name] = {
                "sketch": (win.window_sketch().to_json()
                           if win is not None else None),
                "coverage": dict(_coverage_locked(name)),
                "baseline": (base.to_json()
                             if base is not None else None),
                "results": _last_results.get(name)}
    from flink_ml_tpu.observability.exporters import artifact_suffix

    os.makedirs(trace_dir, exist_ok=True)
    path = os.path.join(trace_dir, f"quality-{artifact_suffix()}.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    return path


def read_state(trace_dir: str) -> Dict[str, dict]:
    """Merge every ``quality-*.json`` in a trace dir:
    ``{servable: {"sketch": QualitySketch, "coverage": {...},
    "baseline": json|None, "results": json|None}}`` — the CLI's
    artifact reader. Torn files are skipped, like the metrics
    reader."""
    import glob

    merged: Dict[str, dict] = {}
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "quality-*.json"))):
        if os.path.basename(path).startswith("quality-baseline-"):
            continue  # fit-side baseline artifacts have their own shape
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for name, entry in (doc.get("servables") or {}).items():
            row = merged.setdefault(
                name, {"sketch": QualitySketch(), "baseline": None,
                       "coverage": {"predictions": 0, "joined": 0,
                                    "evicted": 0, "late": 0},
                       "results": None})
            try:
                row["sketch"].merge(entry.get("sketch") or {})
            except ValueError:
                continue
            for key, val in (entry.get("coverage") or {}).items():
                if key in row["coverage"]:
                    row["coverage"][key] += int(val)
            if entry.get("baseline"):
                row["baseline"] = entry["baseline"]
            if entry.get("results"):
                row["results"] = entry["results"]
    return merged


# -- the `flink-ml-tpu-trace quality` view ------------------------------------

def _artifact_verdicts(state: Dict[str, dict],
                       override: Optional[QualityBaseline],
                       thr: Dict[str, float],
                       min_labels: int) -> List[dict]:
    verdicts = []
    for name in sorted(state):
        entry = state[name]
        base_doc = entry.get("baseline")
        baseline = override
        if baseline is None and base_doc:
            baseline = QualityBaseline.from_json(base_doc)
        sketch: QualitySketch = entry["sketch"]
        live = sketch.quality_metrics()
        base_metrics = (baseline.sketch.quality_metrics()
                        if baseline is not None else None)
        fresh = live["n"] >= min_labels
        over = []
        auc = live["auc"]
        if fresh and math.isfinite(auc):
            if auc < thr["minAuc"]:
                over.append("min-auc")
            if (base_metrics is not None
                    and math.isfinite(base_metrics["auc"])
                    and base_metrics["auc"] - auc
                    > thr["maxAucDelta"]):
                over.append("auc-delta")
        verdicts.append(
            {"servable": name,
             "source": ("baseline" if baseline is not None
                        else "missing"),
             "baselineVersion": (baseline.version
                                 if baseline is not None else None),
             "live": live,
             "baseline": base_metrics,
             "coverage": _coverage_row(entry.get("coverage") or {}),
             "degraded": bool(fresh and over),
             "thin": not fresh,
             "over": over if fresh else []})
    return verdicts


def _fmt(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "-"
    if math.isnan(f):
        return "nan"
    return f"{f:.4f}"


def render_quality(verdicts: List[dict], thr: Dict[str, float]) -> str:
    degraded = sum(1 for v in verdicts if v["degraded"])
    out = [f"{len(verdicts)} servable(s), {degraded} degraded  "
           f"(auc floor {thr['minAuc']:g}, max delta "
           f"{thr['maxAucDelta']:g})"]
    for v in verdicts:
        out.append("")
        ver = (f" baseline v{v['baselineVersion']}"
               if v.get("baselineVersion") is not None else "")
        flag = "DEGRADED" if v["degraded"] else (
            "thin" if v.get("thin") else (
                "no baseline" if v["source"] == "missing" else "ok"))
        out.append(f"servable {v['servable']}{ver}  [{flag}]")
        live = v["live"]
        base = v.get("baseline")
        cov = v.get("coverage") or {}
        out.append(
            f"  {'':<10} {'auc':>8} {'logloss':>8} {'acc':>8} "
            f"{'prec':>8} {'recall':>8} {'ece':>8} {'n':>8}")
        out.append(
            f"  {'live':<10} {_fmt(live['auc']):>8} "
            f"{_fmt(live['logloss']):>8} {_fmt(live['accuracy']):>8} "
            f"{_fmt(live['precision']):>8} {_fmt(live['recall']):>8} "
            f"{_fmt(live['calibrationError']):>8} {live['n']:>8}")
        if base is not None:
            out.append(
                f"  {'baseline':<10} {_fmt(base['auc']):>8} "
                f"{_fmt(base['logloss']):>8} "
                f"{_fmt(base['accuracy']):>8} "
                f"{_fmt(base['precision']):>8} "
                f"{_fmt(base['recall']):>8} "
                f"{_fmt(base['calibrationError']):>8} "
                f"{base['n']:>8}")
        covr = cov.get("coverage")
        out.append(
            f"  coverage {_fmt(covr) if covr is not None else '-'} "
            f"({cov.get('joined', 0)}/{cov.get('predictions', 0)} "
            f"joined, {cov.get('evicted', 0)} evicted, "
            f"{cov.get('late', 0)} late)")
        if v["over"]:
            out.append(f"  over: {', '.join(v['over'])}")
    return "\n".join(out)


def main(argv=None) -> int:
    """``flink-ml-tpu-trace quality <dir>`` — live-vs-baseline quality
    verdicts from a trace dir's ``quality-*.json`` artifacts.
    ``--baseline F`` overrides the artifact baselines with a serialized
    :class:`QualityBaseline` file (e.g. a fit's
    ``quality-baseline-<algo>.json``). ``--check`` exits 4 when any
    servable degraded, 2 on missing/broken artifacts; a servable that
    shipped without a baseline reports ``source: missing`` and its AUC
    is judged against the floor alone — the absence of a baseline is a
    publishing gap, not a regression."""
    import argparse

    from flink_ml_tpu.observability.exporters import (
        pipe_guard,
        resolve_trace_dir,
    )

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace quality",
        description="Continuous-evaluation quality verdicts (AUC / "
                    "logloss / calibration) from a "
                    "FLINK_ML_TPU_TRACE_DIR's quality artifacts.")
    parser.add_argument("trace_dir")
    parser.add_argument("--baseline", metavar="FILE",
                        help="serialized QualityBaseline overriding "
                             "the artifact baselines for every "
                             "servable")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 4 when any servable degraded, 2 on "
                             "broken artifacts")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    parser.add_argument("--min-auc", type=float, default=None,
                        help="live AUC floor (default env/0.6)")
    parser.add_argument("--max-delta", type=float, default=None,
                        help="max baseline-minus-live AUC delta "
                             "(default env/0.1)")
    parser.add_argument("--min-labels", type=int, default=None,
                        help="min joined labels per servable before a "
                             "verdict (default env/100)")
    args = parser.parse_args(argv)

    try:
        trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        state = read_state(trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace quality: cannot read "
              f"{args.trace_dir}: {e}", file=sys.stderr)
        return EXIT_INVALID
    override = None
    if args.baseline:
        try:
            override = load_baseline_file(args.baseline)
            if override is None:
                raise ValueError(f"{args.baseline}: no such file")
        except ValueError as e:
            print(f"flink-ml-tpu-trace quality: {e}", file=sys.stderr)
            return EXIT_INVALID
    if not state:
        print(f"flink-ml-tpu-trace quality: no quality-*.json "
              f"artifacts in {trace_dir}", file=sys.stderr)
        return EXIT_INVALID
    thr = quality_thresholds()
    if args.min_auc is not None:
        thr["minAuc"] = float(args.min_auc)
    if args.max_delta is not None:
        thr["maxAucDelta"] = float(args.max_delta)
    min_labels = (args.min_labels if args.min_labels is not None
                  else _min_labels())
    try:
        verdicts = _artifact_verdicts(state, override, thr, min_labels)
    except ValueError as e:
        print(f"flink-ml-tpu-trace quality: {e}", file=sys.stderr)
        return EXIT_INVALID

    with pipe_guard():
        if args.json:
            # strict JSON: an empty window's AUC is NaN, and the bare
            # NaN token breaks jq exactly when someone is debugging
            # coverage — render as strings (the health --json
            # precedent)
            from flink_ml_tpu.observability.health import _json_safe

            print(json.dumps(_json_safe({"trace_dir": trace_dir,
                                         "thresholds": thr,
                                         "min_labels": min_labels,
                                         "verdicts": verdicts}),
                             indent=2, default=str))
        else:
            print(render_quality(verdicts, thr))
    degraded = [v["servable"] for v in verdicts if v["degraded"]]
    if args.check and degraded:
        print(f"flink-ml-tpu-trace quality: {len(degraded)} degraded "
              f"servable(s): {', '.join(degraded)}", file=sys.stderr)
        return EXIT_DEGRADED
    return EXIT_OK


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
