"""Span-based tracing: trace/span ids, parent links, attributes, events.

The reference delegates run visibility to Flink's web UI (SURVEY.md §5);
here the runtime is this process, so the trace is a first-class artifact:
every instrumented seam (api/stage.py fit/transform, the iteration epoch
loop, checkpoint save/restore, the host pool, the resilience supervisor,
the benchmark runner) opens a :class:`Span` through the process-wide
:data:`tracer`, and finished spans stream to JSON-lines files under
``FLINK_ML_TPU_TRACE_DIR`` — one file per process, merged by the readers
(observability/exporters.py, the ``flink-ml-tpu-trace`` CLI).

Context propagation is thread-local (a span opened on one thread never
implicitly parents a span on another) — crossing a boundary is explicit
through a :class:`TraceContext`, a serializable (trace id, span id)
pair:

- **threads/queues**: capture :func:`current_context` on the producing
  thread, carry it with the work item (a Future, a ``queue.Queue``
  element — serving/batcher.py does both), and open the consuming span
  with ``span(..., parent=ctx)`` (a child) or ``span(...,
  links=[ctx])`` (an explicit ``follows_from`` link: the handoff edge
  of a span DAG, rendered by ``flink-ml-tpu-trace path``); a linked
  root span adopts the first link's trace id so the whole causal chain
  shares ONE trace;
- **fork** (common/hostpool.py): the dispatching span's context is
  captured pre-fork and frozen by :func:`Tracer.reseed_child` as the
  child's remote parent, while the sink re-points at the child's own
  ``spans-<pid>.jsonl`` — child spans nest under the dispatching span
  when the files merge at collect time;
- **processes** (parallel/distributed.py): the launcher serializes a
  context into ``FLINK_ML_TPU_TRACE_PARENT``; every child's root spans
  join that trace, so the merged ``spans-p<k>-*.jsonl`` artifacts of a
  multi-process run stitch into ONE trace.

When no trace dir is armed (env or :meth:`Tracer.configure`), ``span``
returns a shared no-op context manager — one dict lookup of overhead —
so the instrumentation stays compiled into production paths, same policy
as resilience.faults.

This composes with (does not replace) the ``FLINK_ML_TPU_PROFILE_DIR``
jax.profiler hook: the profiler captures device/XLA internals, the
tracer captures the host-side structure around them.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional

#: env var holding a directory; when set, instrumented seams emit spans
#: as ``spans-<pid>.jsonl`` files there (docs/observability.md)
TRACE_DIR_ENV = "FLINK_ML_TPU_TRACE_DIR"

#: env var holding a serialized :class:`TraceContext`
#: (``<trace_id>:<span_id>``; the span half may be empty) — how a
#: launched child process (parallel/distributed.py) inherits its
#: parent's trace id: the child's ROOT spans join that trace instead of
#: minting their own, so merged per-process artifacts stitch into one
TRACE_PARENT_ENV = "FLINK_ML_TPU_TRACE_PARENT"

#: default capacity of the recent-span ring (the live ``/spans/recent``
#: endpoint and the flight recorder's span evidence —
#: observability/flightrecorder.py); override with
#: ``FLINK_ML_TPU_TRACE_RING``
RECENT_SPANS = 256

#: env var overriding the ring capacity (a bigger ring = more incident
#: evidence, more resident memory); read once per Tracer construction /
#: ``reseed_child``
RING_ENV = "FLINK_ML_TPU_TRACE_RING"


def ring_capacity() -> int:
    """The recent-span ring capacity: ``FLINK_ML_TPU_TRACE_RING`` when
    set to a positive integer, else :data:`RECENT_SPANS` (garbage or
    non-positive values fall back rather than disarming the flight
    recorder's evidence ring)."""
    raw = os.environ.get(RING_ENV)
    if raw:
        try:
            n = int(raw)
            if n > 0:
                return n
        except ValueError:
            pass
    return RECENT_SPANS


_id_counter = itertools.count(1)
_id_lock = threading.Lock()


def _new_id() -> str:
    """Process-unique span/trace id: pid + monotonic counter. ids only
    need to be unique within one trace dir; embedding the pid keeps
    forked children (which inherit the counter) from colliding."""
    with _id_lock:
        n = next(_id_counter)
    return f"{os.getpid():x}-{n:x}"


class TraceContext:
    """A serializable span coordinate: ``(trace_id, span_id)``.

    THE currency of cross-boundary causality: capture it where work is
    produced (:func:`current_context`), carry it with the work item (a
    Future, a queue element, a pickled fork payload, an env var), and
    spend it where the work is consumed — as ``parent=`` (the consumer
    is *inside* the producer) or ``links=[...]`` (the consumer *follows
    from* the producer: a queue handoff, a batch serving many requests,
    a controller cycle chained across steps). ``span_id`` may be None:
    a trace-only context (what :func:`fresh_context` mints for process
    launchers) adopts the trace without claiming a parent span."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"TraceContext({self.trace_id}, {self.span_id})"

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id)

    def to_dict(self) -> dict:
        return {"trace": self.trace_id, "span": self.span_id}

    @classmethod
    def from_dict(cls, d: dict) -> "TraceContext":
        return cls(str(d["trace"]), d.get("span") or None)

    def to_header(self) -> str:
        """``<trace_id>:<span_id>`` — the env-var / wire spelling
        (ids are hex+dash, so ``:`` can never appear inside one)."""
        return f"{self.trace_id}:{self.span_id or ''}"

    @classmethod
    def from_header(cls, header: str) -> Optional["TraceContext"]:
        """Parse the ``to_header`` spelling; malformed input returns
        None — a corrupt env var must never sink span creation."""
        if not header or ":" not in header:
            return None
        trace_id, _, span_id = header.partition(":")
        if not trace_id.strip():
            return None
        return cls(trace_id.strip(), span_id.strip() or None)


class Span:
    """One timed region. ``ts_us`` is wall-clock epoch microseconds (what
    Chrome trace-event ``ts`` wants); duration is measured on the
    monotonic clock. ``links`` are explicit ``follows_from`` edges to
    other spans (by :class:`TraceContext`): the DAG edges parent links
    cannot express — queue handoffs, batches serving many requests —
    consumed by ``flink-ml-tpu-trace path``."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "ts_us",
                 "dur_us", "attrs", "events", "links", "_t0")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict,
                 links: Optional[List[TraceContext]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.ts_us = time.time_ns() // 1000
        self.dur_us = None
        self.attrs = dict(attrs)
        self.events: List[dict] = []
        self.links = [ctx for ctx in (links or ())
                      if ctx is not None and ctx.span_id is not None]
        self._t0 = time.perf_counter_ns()

    def set_attribute(self, key: str, value) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, **attrs) -> None:
        self.events.append({"name": name,
                            "ts_us": time.time_ns() // 1000,
                            "attrs": attrs})

    def add_link(self, ctx: Optional[TraceContext]) -> None:
        """Attach a ``follows_from`` link after the span opened (e.g.
        the handoff context only becomes known mid-span)."""
        if ctx is not None and ctx.span_id is not None:
            self.links.append(ctx)

    def finish(self) -> None:
        self.dur_us = (time.perf_counter_ns() - self._t0) // 1000

    def to_record(self, pid: int, tid: int) -> dict:
        record = {"type": "span", "name": self.name,
                  "trace": self.trace_id, "id": self.span_id,
                  "parent": self.parent_id, "ts_us": self.ts_us,
                  "dur_us": self.dur_us, "pid": pid, "tid": tid,
                  "attrs": self.attrs, "events": self.events}
        if self.links:
            record["links"] = [{"trace": ctx.trace_id,
                                "span": ctx.span_id,
                                "kind": "follows_from"}
                               for ctx in self.links]
        return record


class _NoopSpan:
    """Shared do-nothing span/context-manager for the disarmed tracer."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attribute(self, key, value):
        pass

    def add_event(self, name, **attrs):
        pass

    def add_link(self, ctx):
        pass


_NOOP = _NoopSpan()


class _ActiveSpan:
    """Context manager pairing a real Span with its tracer."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.span.set_attribute("error", exc_type.__name__)
        self._tracer._end(self.span)
        return False


class Tracer:
    """Process-wide tracer with thread-local context propagation."""

    def __init__(self):
        self._tls = threading.local()
        self._configured_dir: Optional[str] = None
        self._sink = None           # open file handle, lazily created
        self._sink_pid = None       # pid the sink belongs to (fork guard)
        self._sink_path = None      # path it writes (re-arm guard)
        self._sink_lock = threading.Lock()
        # a frozen TraceContext parent inherited across fork / attached
        # from a launcher's env (see TRACE_PARENT_ENV)
        self._remote_parent: Optional[TraceContext] = None
        # the recent-span ring: the live /spans/recent endpoint AND the
        # flight recorder's span evidence (observability/
        # flightrecorder.py). keep_recent arms it without a trace dir
        # (observability/server.py); with a dir armed it fills as a side
        # effect of writing — the ring must already hold history when an
        # incident fires, so it cannot wait to be asked
        self.keep_recent = False
        self.recent = collections.deque(maxlen=ring_capacity())
        #: spans evicted from the full ring since process start — the
        #: flight recorder's evidence-window pressure, mirrored into
        #: the ``ml.tracing droppedSpans`` counter by
        #: :meth:`mirror_dropped` (artifact/incident dump points, not
        #: per span)
        self.dropped_spans = 0
        self._drop_mirrored = 0

    # -- arming --------------------------------------------------------------
    @property
    def trace_dir(self) -> Optional[str]:
        return self._configured_dir or os.environ.get(TRACE_DIR_ENV)

    @property
    def enabled(self) -> bool:
        return bool(self.trace_dir)

    @property
    def active(self) -> bool:
        """Spans are being recorded somewhere: to the trace dir
        (``enabled``) and/or to the in-memory recent ring for the live
        telemetry endpoint (``keep_recent``)."""
        return self.enabled or self.keep_recent

    def configure(self, trace_dir: Optional[str]) -> None:
        """Programmatic arming (tests, embedding); ``None`` reverts to
        the environment."""
        self.shutdown()
        self._configured_dir = trace_dir

    def shutdown(self) -> None:
        """Close the sink (spans already written stay on disk)."""
        with self._sink_lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None
                self._sink_pid = None
        self._configured_dir = None

    # -- context -------------------------------------------------------------
    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def root(self) -> Optional[Span]:
        """The outermost open span on this thread (the fit/transform
        root) — where run-wide attributes like the mesh topology belong."""
        stack = self._stack()
        return stack[0] if stack else None

    def current_context(self) -> Optional[TraceContext]:
        """The current span's :class:`TraceContext` (None with no open
        span) — what a producer captures before handing work to another
        thread, process or queue."""
        cur = self.current()
        if cur is None:
            return None
        return TraceContext(cur.trace_id, cur.span_id)

    def attach_context(self, ctx: Optional[TraceContext]) -> None:
        """Pin a remote parent: root spans of THIS process (any thread
        with an empty stack) become children of ``ctx`` — the
        programmatic twin of :data:`TRACE_PARENT_ENV`, and what
        :meth:`reseed_child` installs after a fork."""
        self._remote_parent = ctx

    def _env_parent(self) -> Optional[TraceContext]:
        return TraceContext.from_header(
            os.environ.get(TRACE_PARENT_ENV, ""))

    def span(self, name: str, parent: Optional[TraceContext] = None,
             links: Optional[List[TraceContext]] = None, **attrs):
        """Open a span under the current one (or as a new trace root).
        Use as a context manager; yields the :class:`Span`.

        ``parent`` overrides the thread-local context: the span becomes
        a child of that (possibly remote) span — how a consumer thread
        re-enters the producer's trace. ``links`` attach explicit
        ``follows_from`` edges; a span with neither a local nor an
        explicit parent adopts the first link's trace id, so a causal
        chain built purely from handoffs still shares one trace. With
        no context at all, a root span joins the process-wide remote
        parent (fork reseed / :data:`TRACE_PARENT_ENV`) before minting
        a fresh trace."""
        if not self.active:
            return _NOOP
        stack = self._stack()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif stack:
            top = stack[-1]
            trace_id, parent_id = top.trace_id, top.span_id
        else:
            remote = self._remote_parent or self._env_parent()
            if remote is not None:
                trace_id, parent_id = remote.trace_id, remote.span_id
            elif links:
                first = next((c for c in links if c is not None), None)
                trace_id = (first.trace_id if first is not None
                            else _new_id())
                parent_id = None
            else:
                trace_id, parent_id = _new_id(), None
        sp = Span(name, trace_id, _new_id(), parent_id, attrs,
                  links=links)
        stack.append(sp)
        return _ActiveSpan(self, sp)

    def event(self, name: str, **attrs) -> None:
        """Record an instant event on the current span; with no span
        open, emit a standalone zero-duration span carrying it — the
        event must reach the trace either way (a supervisor restart
        outside any fit still matters)."""
        if not self.active:
            return
        cur = self.current()
        if cur is not None:
            cur.add_event(name, **attrs)
            return
        with self.span(f"event:{name}") as sp:
            sp.add_event(name, **attrs)

    def _end(self, sp: Span) -> None:
        sp.finish()
        stack = self._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        else:  # out-of-order exit: drop it from wherever it sits
            try:
                stack.remove(sp)
            except ValueError:
                pass
        record = sp.to_record(os.getpid(), threading.get_ident())
        from flink_ml_tpu.observability.exporters import (
            safe_process_label)

        proc = safe_process_label()
        if proc is not None:
            # attribution for multi-process trace merges: same-pid span
            # records from different hosts must not fold into one process
            record["process"] = proc
        # the ring fills whenever spans are recorded at all (not just
        # under keep_recent): it is the flight recorder's evidence of
        # "what ran before the incident", which must exist BEFORE the
        # incident asks for it. deque.append is thread-safe; a bounded
        # deque evicts silently, so evictions are tallied here — a
        # plain int increment, NOT a registry-lock hit per span on the
        # always-on serving path; mirror_dropped() folds the tally
        # into the ml.tracing droppedSpans counter at artifact-dump /
        # incident-dump / scrape points
        if (self.recent.maxlen is not None
                and len(self.recent) >= self.recent.maxlen):
            self.dropped_spans += 1
        self.recent.append(record)
        self._write(record)

    def mirror_dropped(self) -> int:
        """Fold ring evictions tallied since the last call into the
        ``ml.tracing droppedSpans`` counter — called where the number
        becomes visible (metrics dumps, incident bundles), never per
        span. Returns the cumulative eviction count."""
        delta = self.dropped_spans - self._drop_mirrored
        if delta > 0:
            try:
                from flink_ml_tpu.common.metrics import ML_GROUP, metrics

                metrics.group(ML_GROUP, "tracing").counter(
                    "droppedSpans", delta)
                self._drop_mirrored += delta
            except Exception:  # noqa: BLE001 — accounting must never
                # sink the dump it rides on
                pass
        return self.dropped_spans

    # -- sink ----------------------------------------------------------------
    def span_file(self) -> Optional[str]:
        d = self.trace_dir
        if not d:
            return None
        # multi-process runtimes prefix the process index
        # (spans-p<k>-<pid>.jsonl): two hosts can share a pid, and the
        # shared trace dir must keep their streams apart
        from flink_ml_tpu.observability.exporters import artifact_suffix

        return os.path.join(d, f"spans-{artifact_suffix()}.jsonl")

    def _write(self, record: dict) -> None:
        path = self.span_file()
        if path is None:
            return
        line = json.dumps(record, default=str) + "\n"
        with self._sink_lock:
            if self._sink is not None and self._sink_pid != os.getpid():
                # forked child inherited the parent's handle: abandon it
                # (closing could flush into the parent's file)
                self._sink = None
            elif self._sink is not None and self._sink_path != path:
                # the trace dir was re-armed mid-process: follow it
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None
            if self._sink is None:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                self._sink = open(path, "a", encoding="utf-8")
                self._sink_pid = os.getpid()
                self._sink_path = path
            self._sink.write(line)
            self._sink.flush()  # line-per-span: nothing buffered at fork
                                # or os._exit time

    # -- fork boundary -------------------------------------------------------
    def reseed_child(self, parent: Optional[TraceContext] = None) -> None:
        """Called in a freshly forked host-pool child: freeze the
        dispatching span as a remote parent link, drop the inherited
        context/sink, and point writes at this pid's own span file. The
        child's spans then merge under the dispatching parent span at
        collect time.

        ``parent`` is the context the dispatcher captured PRE-fork
        (common/hostpool.py passes it); falling back to the inherited
        thread-local stack covers embedders that fork without capturing
        one — but only sees the forking thread's context."""
        if parent is None:
            parent = self.current_context()
        self._remote_parent = parent
        self._tls = threading.local()
        # the child is single-threaded here, and the inherited
        # _sink_lock may have been snapshotted HELD by a parent thread
        # — taking it could deadlock; it is replaced two lines down
        self._sink = None  # jaxlint: disable=unguarded-shared-state -- single-threaded post-fork; the guard itself is stale and replaced below
        self._sink_pid = None  # jaxlint: disable=unguarded-shared-state -- single-threaded post-fork; the guard itself is stale and replaced below
        self._sink_path = None  # jaxlint: disable=unguarded-shared-state -- single-threaded post-fork; the guard itself is stale and replaced below
        self._sink_lock = threading.Lock()
        # the live endpoint is driver-only (observability/server.py) and
        # the child's incident evidence merges through its own span
        # file: the ring restarts empty
        self.keep_recent = False
        self.recent = collections.deque(maxlen=ring_capacity())
        self.dropped_spans = 0
        self._drop_mirrored = 0


#: default process-wide tracer
tracer = Tracer()


def span(name: str, **attrs):
    """Module-level convenience: ``tracer.span`` on the default tracer."""
    return tracer.span(name, **attrs)


def event(name: str, **attrs) -> None:
    """Module-level convenience: ``tracer.event`` on the default tracer."""
    tracer.event(name, **attrs)


def current_context() -> Optional[TraceContext]:
    """Module-level convenience: the default tracer's current context."""
    return tracer.current_context()


def context_of(sp) -> Optional[TraceContext]:
    """The :class:`TraceContext` of a span yielded by :func:`span`
    (None for the disarmed no-op span) — capture it INSIDE the ``with``
    block; the ids stay valid after the span closes."""
    span_id = getattr(sp, "span_id", None)
    if span_id is None:
        return None
    return TraceContext(sp.trace_id, span_id)


def fresh_context() -> TraceContext:
    """Mint a trace-only context (no parent span): what a process
    launcher (parallel/distributed.py) exports through
    :data:`TRACE_PARENT_ENV` when it has no open span of its own, so
    every launched child still joins ONE shared trace."""
    return TraceContext(_new_id(), None)


def maybe_dump_root_metrics() -> None:
    """Snapshot the process registry into the trace dir when the tracer
    is armed and no span remains open (an outermost span just closed) —
    the shared tail of every instrumented entry point (stage wrappers,
    the benchmark runner), so the trace dir is inspectable without the
    process."""
    if tracer.enabled and tracer.current() is None:
        from flink_ml_tpu.observability.exporters import dump_metrics

        dump_metrics(tracer.trace_dir)
