"""Span-based tracing: trace/span ids, parent links, attributes, events.

The reference delegates run visibility to Flink's web UI (SURVEY.md §5);
here the runtime is this process, so the trace is a first-class artifact:
every instrumented seam (api/stage.py fit/transform, the iteration epoch
loop, checkpoint save/restore, the host pool, the resilience supervisor,
the benchmark runner) opens a :class:`Span` through the process-wide
:data:`tracer`, and finished spans stream to JSON-lines files under
``FLINK_ML_TPU_TRACE_DIR`` — one file per process, merged by the readers
(observability/exporters.py, the ``flink-ml-tpu-trace`` CLI).

Context propagation is thread-local (a span opened on one thread never
parents a span on another), and survives the host-pool ``os.fork``
boundary: the parent's current span rides into the child through the
fork, :func:`Tracer.reseed_child` freezes it as a remote parent link and
points the child's sink at its own ``spans-<pid>.jsonl``, so child spans
nest under the dispatching parent span when the files are merged at
collect time.

When no trace dir is armed (env or :meth:`Tracer.configure`), ``span``
returns a shared no-op context manager — one dict lookup of overhead —
so the instrumentation stays compiled into production paths, same policy
as resilience.faults.

This composes with (does not replace) the ``FLINK_ML_TPU_PROFILE_DIR``
jax.profiler hook: the profiler captures device/XLA internals, the
tracer captures the host-side structure around them.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional

#: env var holding a directory; when set, instrumented seams emit spans
#: as ``spans-<pid>.jsonl`` files there (docs/observability.md)
TRACE_DIR_ENV = "FLINK_ML_TPU_TRACE_DIR"

#: closed spans kept in memory for the live ``/spans/recent`` endpoint
#: (observability/server.py) — populated only while ``keep_recent`` is
#: armed, so the ring costs nothing in untelemetered processes
RECENT_SPANS = 256

_id_counter = itertools.count(1)
_id_lock = threading.Lock()


def _new_id() -> str:
    """Process-unique span/trace id: pid + monotonic counter. ids only
    need to be unique within one trace dir; embedding the pid keeps
    forked children (which inherit the counter) from colliding."""
    with _id_lock:
        n = next(_id_counter)
    return f"{os.getpid():x}-{n:x}"


class Span:
    """One timed region. ``ts_us`` is wall-clock epoch microseconds (what
    Chrome trace-event ``ts`` wants); duration is measured on the
    monotonic clock."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "ts_us",
                 "dur_us", "attrs", "events", "_t0")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.ts_us = time.time_ns() // 1000
        self.dur_us = None
        self.attrs = dict(attrs)
        self.events: List[dict] = []
        self._t0 = time.perf_counter_ns()

    def set_attribute(self, key: str, value) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, **attrs) -> None:
        self.events.append({"name": name,
                            "ts_us": time.time_ns() // 1000,
                            "attrs": attrs})

    def finish(self) -> None:
        self.dur_us = (time.perf_counter_ns() - self._t0) // 1000

    def to_record(self, pid: int, tid: int) -> dict:
        return {"type": "span", "name": self.name,
                "trace": self.trace_id, "id": self.span_id,
                "parent": self.parent_id, "ts_us": self.ts_us,
                "dur_us": self.dur_us, "pid": pid, "tid": tid,
                "attrs": self.attrs, "events": self.events}


class _NoopSpan:
    """Shared do-nothing span/context-manager for the disarmed tracer."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attribute(self, key, value):
        pass

    def add_event(self, name, **attrs):
        pass


_NOOP = _NoopSpan()


class _ActiveSpan:
    """Context manager pairing a real Span with its tracer."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.span.set_attribute("error", exc_type.__name__)
        self._tracer._end(self.span)
        return False


class Tracer:
    """Process-wide tracer with thread-local context propagation."""

    def __init__(self):
        self._tls = threading.local()
        self._configured_dir: Optional[str] = None
        self._sink = None           # open file handle, lazily created
        self._sink_pid = None       # pid the sink belongs to (fork guard)
        self._sink_path = None      # path it writes (re-arm guard)
        self._sink_lock = threading.Lock()
        # a frozen (trace_id, span_id) parent inherited across fork
        self._remote_parent = None
        # the live-endpoint ring: recently closed span records, armed by
        # observability/server.py (spans then exist even without a dir)
        self.keep_recent = False
        self.recent = collections.deque(maxlen=RECENT_SPANS)

    # -- arming --------------------------------------------------------------
    @property
    def trace_dir(self) -> Optional[str]:
        return self._configured_dir or os.environ.get(TRACE_DIR_ENV)

    @property
    def enabled(self) -> bool:
        return bool(self.trace_dir)

    @property
    def active(self) -> bool:
        """Spans are being recorded somewhere: to the trace dir
        (``enabled``) and/or to the in-memory recent ring for the live
        telemetry endpoint (``keep_recent``)."""
        return self.enabled or self.keep_recent

    def configure(self, trace_dir: Optional[str]) -> None:
        """Programmatic arming (tests, embedding); ``None`` reverts to
        the environment."""
        self.shutdown()
        self._configured_dir = trace_dir

    def shutdown(self) -> None:
        """Close the sink (spans already written stay on disk)."""
        with self._sink_lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None
                self._sink_pid = None
        self._configured_dir = None

    # -- context -------------------------------------------------------------
    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def root(self) -> Optional[Span]:
        """The outermost open span on this thread (the fit/transform
        root) — where run-wide attributes like the mesh topology belong."""
        stack = self._stack()
        return stack[0] if stack else None

    def span(self, name: str, **attrs):
        """Open a span under the current one (or as a new trace root).
        Use as a context manager; yields the :class:`Span`."""
        if not self.active:
            return _NOOP
        stack = self._stack()
        if stack:
            parent = stack[-1]
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif self._remote_parent is not None:
            trace_id, parent_id = self._remote_parent
        else:
            trace_id, parent_id = _new_id(), None
        sp = Span(name, trace_id, _new_id(), parent_id, attrs)
        stack.append(sp)
        return _ActiveSpan(self, sp)

    def event(self, name: str, **attrs) -> None:
        """Record an instant event on the current span; with no span
        open, emit a standalone zero-duration span carrying it — the
        event must reach the trace either way (a supervisor restart
        outside any fit still matters)."""
        if not self.active:
            return
        cur = self.current()
        if cur is not None:
            cur.add_event(name, **attrs)
            return
        with self.span(f"event:{name}") as sp:
            sp.add_event(name, **attrs)

    def _end(self, sp: Span) -> None:
        sp.finish()
        stack = self._stack()
        if stack and stack[-1] is sp:
            stack.pop()
        else:  # out-of-order exit: drop it from wherever it sits
            try:
                stack.remove(sp)
            except ValueError:
                pass
        record = sp.to_record(os.getpid(), threading.get_ident())
        from flink_ml_tpu.observability.exporters import (
            safe_process_label)

        proc = safe_process_label()
        if proc is not None:
            # attribution for multi-process trace merges: same-pid span
            # records from different hosts must not fold into one process
            record["process"] = proc
        if self.keep_recent:
            self.recent.append(record)  # deque.append is thread-safe
        self._write(record)

    # -- sink ----------------------------------------------------------------
    def span_file(self) -> Optional[str]:
        d = self.trace_dir
        if not d:
            return None
        # multi-process runtimes prefix the process index
        # (spans-p<k>-<pid>.jsonl): two hosts can share a pid, and the
        # shared trace dir must keep their streams apart
        from flink_ml_tpu.observability.exporters import artifact_suffix

        return os.path.join(d, f"spans-{artifact_suffix()}.jsonl")

    def _write(self, record: dict) -> None:
        path = self.span_file()
        if path is None:
            return
        line = json.dumps(record, default=str) + "\n"
        with self._sink_lock:
            if self._sink is not None and self._sink_pid != os.getpid():
                # forked child inherited the parent's handle: abandon it
                # (closing could flush into the parent's file)
                self._sink = None
            elif self._sink is not None and self._sink_path != path:
                # the trace dir was re-armed mid-process: follow it
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None
            if self._sink is None:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                self._sink = open(path, "a", encoding="utf-8")
                self._sink_pid = os.getpid()
                self._sink_path = path
            self._sink.write(line)
            self._sink.flush()  # line-per-span: nothing buffered at fork
                                # or os._exit time

    # -- fork boundary -------------------------------------------------------
    def reseed_child(self) -> None:
        """Called in a freshly forked host-pool child: freeze the
        inherited current span as a remote parent link, drop the
        inherited context/sink, and point writes at this pid's own span
        file. The child's spans then merge under the dispatching parent
        span at collect time."""
        cur = self.current()
        self._remote_parent = ((cur.trace_id, cur.span_id)
                               if cur is not None else None)
        self._tls = threading.local()
        self._sink = None
        self._sink_pid = None
        self._sink_path = None
        self._sink_lock = threading.Lock()
        # the live endpoint is driver-only (observability/server.py):
        # a forked child neither serves nor rings
        self.keep_recent = False
        self.recent = collections.deque(maxlen=RECENT_SPANS)


#: default process-wide tracer
tracer = Tracer()


def span(name: str, **attrs):
    """Module-level convenience: ``tracer.span`` on the default tracer."""
    return tracer.span(name, **attrs)


def event(name: str, **attrs) -> None:
    """Module-level convenience: ``tracer.event`` on the default tracer."""
    tracer.event(name, **attrs)


def maybe_dump_root_metrics() -> None:
    """Snapshot the process registry into the trace dir when the tracer
    is armed and no span remains open (an outermost span just closed) —
    the shared tail of every instrumented entry point (stage wrappers,
    the benchmark runner), so the trace dir is inspectable without the
    process."""
    if tracer.enabled and tracer.current() is None:
        from flink_ml_tpu.observability.exporters import dump_metrics

        dump_metrics(tracer.trace_dir)
