"""Trace/metrics exporters: JSONL span merge, Chrome trace-event JSON,
Prometheus text exposition.

Writers (observability/tracing.py) stream one ``spans-<pid>.jsonl`` per
process into the trace dir; host-pool children add their own pid files.
The readers here merge the whole directory — that merge IS the
"collect" step of the fork-boundary design, so a trace survives any mix
of parent/child crashes that left files behind.

Chrome trace-event output loads in Perfetto / chrome://tracing: spans
become complete (``ph: "X"``) events, span events become instants
(``ph: "i"``). Prometheus output is the text exposition format
(name{labels} value), rendered from a registry snapshot — the labeled
key syntax in common/metrics.py is chosen so this is a string split,
not a parser.
"""

from __future__ import annotations

import contextlib
import glob
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional

from flink_ml_tpu.common.metrics import MetricsRegistry, metrics

#: metrics snapshot files in a trace dir (one per traced process)
METRICS_GLOB = "metrics-*.json"
SPANS_GLOB = "spans-*.jsonl"

PROM_PREFIX = "flink_ml_tpu"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


@contextlib.contextmanager
def pipe_guard():
    """Swallow the BrokenPipeError every ``flink-ml-tpu-trace``
    subcommand's stdout rendering is exposed to (``... | head`` closing
    the pipe is how the CLI is used, not an error) — shared by summary,
    diff, health, shards and the exporter paths so the guard cannot
    drift per subcommand. Exit-code logic stays with the caller: the
    guard only absorbs the write failure."""
    try:
        yield
    except BrokenPipeError:
        try:
            sys.stdout.close()
        except OSError:
            pass


# -- trace-dir resolution -----------------------------------------------------
def latest_trace_dir(root: str) -> Optional[str]:
    """The newest trace dir under ``root``: ``root`` itself or any
    direct child holding ``spans-*.jsonl`` / ``metrics-*.json``
    artifacts, newest by the artifacts' own mtimes (a dir's newest
    artifact decides). Returns None when nothing qualifies — shared by
    every CLI subcommand's ``--latest`` so CI and humans stop
    hand-globbing ``trace-*`` dirs."""
    candidates: Dict[str, float] = {}
    for pat in (SPANS_GLOB, METRICS_GLOB):
        for path in (glob.glob(os.path.join(root, pat))
                     + glob.glob(os.path.join(root, "*", pat))):
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            d = os.path.dirname(path)
            if os.path.basename(d).startswith("incident-"):
                # a flight-recorder bundle (observability/
                # flightrecorder.py) carries spans-recent.jsonl /
                # metrics.json copies of its OWNING trace dir — it is
                # evidence inside a trace dir, never the trace dir
                # itself (and it is always the newest thing around)
                continue
            candidates[d] = max(candidates.get(d, 0.0), mtime)
    if not candidates:
        return None
    # mtime ties (same-second writes) break on the path so the pick is
    # deterministic
    return max(candidates.items(), key=lambda kv: (kv[1], kv[0]))[0]


def resolve_trace_dir(path: str, latest: bool = False) -> str:
    """The ``--latest`` seam of the trace CLI: with ``latest``, treat
    ``path`` as a root and return its newest trace dir (raising
    FileNotFoundError — an OSError, so existing exit-2 paths catch it —
    when none exists); otherwise return ``path`` unchanged."""
    if not latest:
        return path
    resolved = latest_trace_dir(path)
    if resolved is None:
        raise FileNotFoundError(
            f"{path}: no trace dirs with spans-*.jsonl or "
            f"metrics-*.json under it")
    return resolved


# -- span collection ---------------------------------------------------------
def read_spans(trace_dir: str) -> List[dict]:
    """All span records from every ``spans-*.jsonl`` in ``trace_dir``
    (parent + forked children), in start-time order. Truncated trailing
    lines (a process killed mid-write) are skipped, not fatal — a trace
    from a crashed run is exactly when this reader matters most."""
    records: List[dict] = []
    for path in sorted(glob.glob(os.path.join(trace_dir, SPANS_GLOB))):
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("type") == "span":
                    records.append(rec)
    records.sort(key=lambda r: (r.get("ts_us", 0), r.get("id", "")))
    return records


# -- Chrome trace-event format ----------------------------------------------
def chrome_trace_events(spans: List[dict]) -> List[dict]:
    events: List[dict] = []
    for sp in spans:
        args = dict(sp.get("attrs", {}))
        args["span_id"] = sp.get("id")
        if sp.get("parent"):
            args["parent_id"] = sp["parent"]
        if sp.get("links"):
            # the follows_from handoff edges (tracing.TraceContext):
            # Perfetto has no native link rendering, but the ids in
            # args make the DAG walkable from the event inspector
            args["follows_from"] = [ln.get("span")
                                    for ln in sp["links"]]
        events.append({
            "name": sp.get("name", "?"),
            "cat": "span",
            "ph": "X",
            "ts": sp.get("ts_us", 0),
            "dur": sp.get("dur_us") or 0,
            "pid": sp.get("pid", 0),
            "tid": sp.get("tid", 0),
            "args": args,
        })
        for ev in sp.get("events", ()):
            ev_args = dict(ev.get("attrs", {}))
            # the owning span's ids must ride along, or Perfetto shows a
            # floating instant nobody can correlate with its span
            ev_args["span_id"] = sp.get("id")
            if sp.get("parent"):
                ev_args["parent_id"] = sp["parent"]
            events.append({
                "name": ev.get("name", "?"),
                "cat": "event",
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "ts": ev.get("ts_us", sp.get("ts_us", 0)),
                "pid": sp.get("pid", 0),
                "tid": sp.get("tid", 0),
                "args": ev_args,
            })
    return events


def chrome_trace(trace_dir: str) -> dict:
    """Perfetto-loadable JSON object for a whole trace directory."""
    return {"traceEvents": chrome_trace_events(read_spans(trace_dir)),
            "displayTimeUnit": "ms"}


def write_chrome_trace(trace_dir: str, out_path: str) -> int:
    """Write the merged Chrome trace; returns the number of span records
    exported."""
    doc = chrome_trace(trace_dir)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return sum(1 for e in doc["traceEvents"] if e["ph"] == "X")


# -- metrics snapshots in the trace dir --------------------------------------
def safe_process_label() -> Optional[int]:
    """``distributed.process_label()`` that never raises — THE wrapper
    every artifact writer (span sink, metrics/drift dumps, span-record
    attribution) shares: labeling must never sink a write. Recomputed
    per call rather than cached: cheap (two env lookups) next to the
    disk write it accompanies, and tests re-point the env mid-process."""
    try:
        from flink_ml_tpu.parallel.distributed import process_label

        return process_label()
    except Exception:
        return None


def artifact_suffix() -> str:
    """The per-process artifact name suffix: the pid alone in a
    single-process runtime, ``p<index>-<pid>`` when the runtime spans
    processes (``jax.process_count() > 1`` or the launcher env —
    parallel/distributed.py). Two hosts routinely hand out the same pid,
    so pid-only names silently collide when a multi-process run shares
    one trace dir: one process's ``metrics-<pid>.json`` overwrites
    another's and their spans interleave under one pid. Shared by the
    span sink (tracing.py), the metrics snapshots below and the drift
    state dump — every writer into a trace dir names files through this
    one seam."""
    k = safe_process_label()
    pid = os.getpid()
    return f"p{k}-{pid}" if k is not None else str(pid)


def dump_metrics(trace_dir: str,
                 registry: MetricsRegistry = metrics) -> str:
    """Write the registry snapshot as ``metrics-<pid>.json``
    (``metrics-p<k>-<pid>.json`` in a multi-process runtime — see
    :func:`artifact_suffix`; overwrite: the newest snapshot per process
    supersedes earlier ones). When the drift module is loaded
    (observability/drift.py — the package import chain loads it; the
    sys.modules gate only protects embeddings that strip it), its
    live-sketch state dumps alongside as ``drift-<pid>.json`` — a no-op
    for processes that never sketched."""
    os.makedirs(trace_dir, exist_ok=True)
    if registry is metrics:
        # fold the span-ring eviction tally into ml.tracing
        # droppedSpans before snapshotting — the per-span hot path
        # only increments an int (tracing.Tracer.mirror_dropped)
        from flink_ml_tpu.observability import tracing

        tracing.tracer.mirror_dropped()
        # same dump-point pattern for the lock watchdog (common/
        # locks.py): hold-time histograms and cycle/long-hold counters
        # fold into ml.lock BEFORE the snapshot is written
        from flink_ml_tpu.common import locks

        locks.mirror_metrics()
    path = os.path.join(trace_dir, f"metrics-{artifact_suffix()}.json")
    snap = registry.snapshot()
    proc = _process_labels()
    if proc is not None:
        # multi-process runs share one trace dir: label every series
        # with its member so the artifact merge keeps them distinct
        # (the Prometheus-collision fix, see relabel_snapshot)
        snap = relabel_snapshot(snap, proc)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(snap, f, default=str)
    os.replace(tmp, path)
    drift_mod = sys.modules.get("flink_ml_tpu.observability.drift")
    if drift_mod is not None:
        try:
            drift_mod.dump_state(trace_dir)
        except OSError:
            pass  # the metrics snapshot is the primary artifact
    eval_mod = sys.modules.get(
        "flink_ml_tpu.observability.evaluation")
    if eval_mod is not None:
        try:
            eval_mod.dump_state(trace_dir)
        except OSError:
            pass  # same rule as drift: the snapshot is primary
    # lock-watchdog acquisition graph rides alongside as
    # locks-<suffix>.json (a no-op for processes that never armed it)
    try:
        from flink_ml_tpu.common import locks as locks_mod

        locks_mod.dump_state(trace_dir)
    except OSError:
        pass
    return path


def read_metrics(trace_dir: str) -> Dict[str, dict]:
    """Merge every ``metrics-*.json`` in the dir into one snapshot."""
    merged = MetricsRegistry()
    for path in sorted(glob.glob(os.path.join(trace_dir, METRICS_GLOB))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                merged.merge(json.load(f))
        except (OSError, json.JSONDecodeError, ValueError):
            continue  # a torn snapshot must not sink the readable ones
    return merged.snapshot()


# -- multi-process series disambiguation --------------------------------------
def _relabel_key(key: str, extra: Dict[str, str]) -> str:
    """Fold ``extra`` labels into a rendered series key; labels the key
    already carries win (a series explicitly attributed stays as
    written)."""
    from flink_ml_tpu.common.metrics import metric_key
    from flink_ml_tpu.observability.health import _parse_labels

    name, rest = _split_labels(key)
    got = _parse_labels(rest)
    for k, v in extra.items():
        got.setdefault(k, v)
    return metric_key(name, got)


def relabel_snapshot(snapshot: Dict[str, dict],
                     extra: Dict[str, str]) -> Dict[str, dict]:
    """A copy of a registry snapshot with ``extra`` labels folded into
    every series key. The multi-process collision fix: two replicas
    both recording ``transformMs{servable="lr"}`` would otherwise dump
    and expose IDENTICAL series names — a scraper silently
    last-writes-wins, and the artifact merge sums them with no way to
    tell members apart. A ``process="p<k>"`` label keeps every member's
    series distinct while the slo/diff readers' label-subset matching
    still aggregates across them."""
    out: Dict[str, dict] = {}
    for group, gsnap in snapshot.items():
        gout = dict(gsnap)
        for section in ("gauges", "counters", "histograms"):
            entries = gsnap.get(section)
            if isinstance(entries, dict):
                gout[section] = {_relabel_key(k, extra): v
                                 for k, v in entries.items()}
        out[group] = gout
    return out


def _process_labels() -> Optional[Dict[str, str]]:
    """``{"process": "p<k>"}`` in a multi-process runtime, else None."""
    k = safe_process_label()
    return {"process": f"p{k}"} if k is not None else None


# -- Prometheus text exposition ----------------------------------------------
def _prom_name(group: str, metric: str, suffix: str = "") -> str:
    name = f"{PROM_PREFIX}_{group}_{metric}{suffix}".replace(".", "_")
    return _NAME_OK.sub("_", name)


def _split_labels(key: str):
    """``name{k="v"}`` → (name, 'k="v"'); plain names → (key, '')."""
    if "{" in key and key.endswith("}"):
        name, _, rest = key.partition("{")
        return name, rest[:-1]
    return key, ""


def _with_labels(name: str, labels: str, extra: str = "") -> str:
    inner = ",".join(x for x in (labels, extra) if x)
    return f"{name}{{{inner}}}" if inner else name


def _fmt(value) -> str:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _series_by_name(entries: Dict[str, object]):
    """Group ``key -> value`` (key possibly labeled) by bare metric name:
    name → [(labels, value), ...] — one exposition family per name (the
    text format allows exactly one ``# TYPE`` line per metric name, so
    labeled series of one metric must render under a single header)."""
    by_name: Dict[str, List] = {}
    for key in sorted(entries):
        name, labels = _split_labels(key)
        by_name.setdefault(name, []).append((labels, entries[key]))
    return by_name


def prometheus_text(snapshot: Optional[Dict[str, dict]] = None) -> str:
    """Render a registry snapshot (default: the live process registry) in
    the Prometheus text exposition format, histograms as cumulative
    ``_bucket{le=...}`` series plus ``_sum``/``_count``. In a
    multi-process runtime every series gains a ``process="p<k>"`` label
    (see :func:`relabel_snapshot` — two scraped replicas must never
    emit identical series names)."""
    if snapshot is None:
        snapshot = metrics.snapshot()
    proc = _process_labels()
    if proc is not None:
        snapshot = relabel_snapshot(snapshot, proc)
    lines: List[str] = []
    for group in sorted(snapshot):
        gsnap = snapshot[group]
        for name, series in _series_by_name(
                gsnap.get("gauges", {})).items():
            prom = _prom_name(group, name)
            lines.append(f"# TYPE {prom} gauge")
            for labels, value in series:
                lines.append(f"{_with_labels(prom, labels)} "
                             f"{_fmt(value)}")
        for name, series in _series_by_name(
                gsnap.get("counters", {})).items():
            prom = _prom_name(group, name, "_total")
            lines.append(f"# TYPE {prom} counter")
            for labels, value in series:
                lines.append(f"{_with_labels(prom, labels)} "
                             f"{_fmt(value)}")
        for name, series in _series_by_name(
                gsnap.get("histograms", {})).items():
            prom = _prom_name(group, name)
            lines.append(f"# TYPE {prom} histogram")
            for labels, hist in series:
                # counts are already cumulative (metrics.Histogram)
                for bound, cnt in zip(hist["buckets"], hist["counts"]):
                    lines.append(
                        f"{_with_labels(prom + '_bucket', labels, _le(bound))}"
                        f" {_fmt(cnt)}")
                lines.append(
                    f"{_with_labels(prom + '_bucket', labels, _le(math.inf))}"
                    f" {_fmt(hist['count'])}")
                lines.append(f"{_with_labels(prom + '_sum', labels)} "
                             f"{_fmt(hist['sum'])}")
                lines.append(f"{_with_labels(prom + '_count', labels)} "
                             f"{_fmt(hist['count'])}")
    return "\n".join(lines) + "\n"


def _le(bound: float) -> str:
    return f'le="{_fmt(bound)}"'
