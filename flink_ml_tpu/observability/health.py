"""Model-health telemetry: convergence series, non-finite sentinels,
divergence classification, serving-path metrics, and the
``flink-ml-tpu-trace health`` view.

The reference ships ``MLMetrics`` in its engine-free servable core —
model-facing metrics are part of the serving contract — yet the
observability layer so far instruments only systems seams (span timings,
compile stats, memory watermarks): a fit that silently diverges or a
servable emitting NaN predictions looks *healthy* in every existing
artifact. This module closes that gap, DrJAX-style (arXiv:2403.07128):
numeric health aggregates are first-class **outputs of the jitted
program**, never host-side per-leaf probes that would cost a device sync
per check.

Two tiers, matching the cost of each:

- **Always on** (``FLINK_ML_TPU_HEALTH`` unset or truthy): a cheap
  host-side guard over the *final* fit state — loss + coefficient
  arrays that are already on host — raising the terminal
  :class:`~flink_ml_tpu.resilience.policy.NonFiniteState` so
  ``run_supervised`` fails fast instead of burning retries on a
  deterministic NaN. ``FLINK_ML_TPU_HEALTH=0`` disables the layer.
- **Armed** (a trace dir is configured, or ``FLINK_ML_TPU_HEALTH`` is
  truthy): the fit programs compile a health variant that additionally
  returns per-epoch convergence rows (loss, update norm, parameter
  norm) and ONE non-finite sentinel scalar — loss + every parameter
  leaf folded into a single ``isfinite`` reduction *inside* the jitted
  step (:func:`finite_sentinel` / :func:`convergence_row`; jaxlint
  JL107-clean by design: only the scalar *result* is recorded on host,
  at epoch/segment boundaries). The series land as labeled histograms
  in the ``ml.health`` registry group and as ``ml.convergence`` span
  events; divergence classification (non-finite, exploding norm over a
  configurable window) emits ``ml.health`` events.

Serving path: every :class:`~flink_ml_tpu.servable.api
.TransformerServable` transform records latency + row-count histograms
and a prediction-distribution summary (min/max/mean/finite-fraction)
into ``ml.serving`` — the drift baseline; a batch with non-finite
predictions emits an ``ml.health`` event but never fails the serving
call. The latency/row histograms and the transform/error counters are
**windowed** (common/metrics.py WindowedHistogram/WindowedCounter, the
cumulative view unchanged) so the SLO engine (observability/slo.py)
and the live ``/slo`` endpoint (observability/server.py) can answer
"p99 over the last 60 seconds" from a running process; the seam also
tracks an in-flight gauge, per-exception-class error counters, and
probabilistically samples request-scoped spans
(``FLINK_ML_TPU_TRACE_SAMPLE``).

Inspect with ``flink-ml-tpu-trace health <dir>`` (``--check`` exits 3 —
the sweep's correctness class — when any ``ml.health`` event is
present). See docs/observability.md "Model health".
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import tracing
from flink_ml_tpu.resilience.policy import NonFiniteState

__all__ = [
    "HEALTH_ENV",
    "HEALTH_EVENT",
    "CONVERGENCE_EVENT",
    "VALUE_BUCKETS",
    "COUNT_BUCKETS",
    "SUMMARY_BUCKETS",
    "armed",
    "guard_enabled",
    "finite_sentinel",
    "convergence_row",
    "record_fit_series",
    "classify_divergence",
    "report_divergence",
    "check_fit",
    "guard_final_state",
    "ConvergenceListener",
    "SAMPLE_ENV",
    "observe_serving",
    "observe_serving_error",
    "observe_serving_rejected",
    "observe_serving_shards",
    "serving_inflight",
    "summarize_values",
    "trace_sample_rate",
    "trace_sampled",
    "health_summary",
    "render_health",
    "main",
]

#: "0" disables the whole layer (guard + series); any other non-empty
#: value force-arms the rich series telemetry even without a trace dir
HEALTH_ENV = "FLINK_ML_TPU_HEALTH"

#: window (epochs) and growth factor for the exploding-norm classifier
WINDOW_ENV = "FLINK_ML_TPU_HEALTH_WINDOW"
FACTOR_ENV = "FLINK_ML_TPU_HEALTH_FACTOR"
#: absolute norm floor below which growth is never flagged (early
#: training legitimately grows norms from ~0 by large ratios)
FLOOR_ENV = "FLINK_ML_TPU_HEALTH_FLOOR"

#: instant-event names in the trace (docs/observability.md)
HEALTH_EVENT = "ml.health"
CONVERGENCE_EVENT = "ml.convergence"

#: magnitude-shaped histogram bounds for losses/norms (the default
#: DEFAULT_BUCKETS are latency-shaped and would flatten a loss curve)
VALUE_BUCKETS = (1e-8, 1e-6, 1e-4, 1e-3, 1e-2, 0.1, 0.25, 0.5, 1.0, 2.5,
                 5.0, 10.0, 100.0, 1e4, 1e6, 1e9, 1e12)

#: row-count-shaped bounds for serving batch sizes
COUNT_BUCKETS = (1.0, 8.0, 32.0, 128.0, 512.0, 2048.0, 8192.0, 65536.0,
                 1048576.0)

#: prediction/probability-shaped bounds for the windowed value
#: distributions :func:`summarize_values` records — symmetric around 0
#: with fine structure in [0, 1] (probabilities, 0/1 predictions) and
#: coarse decades outward (margins, regression outputs)
SUMMARY_BUCKETS = (-1e6, -1e3, -10.0, -1.0, -0.1, 0.0, 0.1, 0.25, 0.5,
                   0.75, 0.9, 1.0, 10.0, 1e3, 1e6)

#: probabilistic request-trace sampling rate for the serving seam
#: (0..1; default 1.0 — every request, turn it down under load)
SAMPLE_ENV = "FLINK_ML_TPU_TRACE_SAMPLE"

#: sliding-window horizon for the serving metrics: covers the default
#: SLO burn windows (observability/slo.py, up to 300 s) at 10-second
#: slice granularity
SERVING_HORIZON_S = 900.0
SERVING_SLICES = 90

#: at most this many ml.convergence span events per fit (stride-sampled,
#: first/last always kept) — a 10k-epoch host loop must not bloat the
#: trace; the registry histograms still see every epoch
MAX_CONVERGENCE_EVENTS = 256

#: the canonical convergence-series names (column order of
#: :func:`convergence_row`)
SERIES_NAMES = ("loss", "updateNorm", "paramNorm")

#: series the exploding-norm classifier inspects, in preference order
_NORM_SERIES = ("paramNorm", "centerShift", "updateNorm")


def guard_enabled() -> bool:
    """The always-on tier: the final-state non-finite guard (and the
    NonFiniteState raise). Off only with ``FLINK_ML_TPU_HEALTH=0``."""
    return os.environ.get(HEALTH_ENV, "") != "0"


def armed() -> bool:
    """The rich tier: per-epoch series + in-program sentinel variants of
    the fit programs. On when a trace dir is configured (the series have
    somewhere to land) or ``FLINK_ML_TPU_HEALTH`` is truthy."""
    env = os.environ.get(HEALTH_ENV, "")
    if env == "0":
        return False
    return bool(env) or tracing.tracer.enabled


def _window() -> int:
    try:
        return max(1, int(os.environ.get(WINDOW_ENV, "5")))
    except ValueError:
        return 5


def _factor() -> float:
    try:
        return float(os.environ.get(FACTOR_ENV, "1e3"))
    except ValueError:
        return 1e3


def _floor() -> float:
    try:
        return float(os.environ.get(FLOOR_ENV, "1e6"))
    except ValueError:
        return 1e6


# -- device-side helpers (pure jnp: safe inside jit/shard_map) ----------------

def finite_sentinel(*leaves):
    """Fold arbitrary array leaves into ONE boolean scalar: True iff
    every element of every leaf is finite. Pure ``jnp`` math — designed
    to run *inside* a jitted step (JL107-clean: no metric/tracer calls);
    the caller records only the scalar result on host, so the check
    costs one cheap reduction, not a per-leaf device sync."""
    import jax.numpy as jnp

    acc = jnp.asarray(True)
    for leaf in leaves:
        acc = jnp.logical_and(
            acc, jnp.all(jnp.isfinite(jnp.asarray(leaf))))
    return acc


def convergence_row(loss, prev_params, new_params, model_axis=None):
    """One per-epoch health sample as a float32 ``(3,)`` row —
    ``[loss, ||new-prev||, ||new||]`` — plus its finite fold (ONE
    scalar: a NaN/Inf anywhere in the parameters poisons the squared
    sums, so the row's ``isfinite`` covers loss and every parameter
    element without a separate per-leaf pass). Pure jnp; call inside
    the jitted step. With ``model_axis`` (tensor-parallel map body)
    the squared sums all-reduce over that axis — through the named
    collective seam (JL108) — so the norms are global."""
    import jax.numpy as jnp

    from flink_ml_tpu.parallel.collective import all_reduce_sum

    upd_sq = jnp.sum(jnp.square(new_params - prev_params))
    prm_sq = jnp.sum(jnp.square(new_params))
    if model_axis is not None:
        upd_sq = all_reduce_sum(upd_sq, model_axis)
        prm_sq = all_reduce_sum(prm_sq, model_axis)
    row = jnp.stack([jnp.asarray(loss, jnp.float32),
                     jnp.sqrt(upd_sq).astype(jnp.float32),
                     jnp.sqrt(prm_sq).astype(jnp.float32)])
    return row, jnp.all(jnp.isfinite(row))


# -- host-side recording ------------------------------------------------------

def _health_group():
    return metrics.group(ML_GROUP, "health")


def record_fit_series(algo: str, series: Dict[str, Sequence[float]],
                      epoch0: int = 0,
                      labels: Optional[Dict[str, str]] = None) -> None:
    """Record per-epoch convergence series for one fit: each named
    series becomes a labeled ``ml.health`` histogram (every epoch) and
    the epochs become ``ml.convergence`` span events (stride-sampled
    past :data:`MAX_CONVERGENCE_EVENTS`) on the current span so
    ``mltrace health`` can render the curve from the artifacts alone.
    Non-finite values are skipped by the histograms (bucket math cannot
    hold them) but ride into the events verbatim.

    ``labels`` (e.g. ``{"shard": "3", "device": "3"}`` from the mesh
    telemetry layer, docs/observability.md "Distributed telemetry")
    ride onto every histogram/gauge key and convergence event, so a
    per-replica series stays attributable through registry merges."""
    group = _health_group()
    named = {k: list(v) for k, v in series.items() if v is not None}
    if not named:
        return
    key_labels = {"algo": algo, **(labels or {})}
    length = max(len(v) for v in named.values())
    for name, values in named.items():
        hist = group.histogram(name, buckets=VALUE_BUCKETS,
                               labels=key_labels)
        last = None
        for v in values:
            v = float(v)
            if math.isfinite(v):
                hist.observe(v)
                last = v
        if last is not None:
            group.gauge(f"last_{name}", last, labels=key_labels)
    group.gauge("epochs", epoch0 + length, labels=key_labels)
    if not tracing.tracer.enabled:
        return
    stride = max(1, -(-length // MAX_CONVERGENCE_EVENTS))
    for i in range(length):
        if i % stride and i != length - 1:
            continue
        attrs = {"algo": algo, "epoch": epoch0 + i, **(labels or {})}
        for name, values in named.items():
            if i < len(values):
                attrs[name] = float(values[i])
        tracing.tracer.event(CONVERGENCE_EVENT, **attrs)


def classify_divergence(series: Dict[str, Sequence[float]],
                        finite: bool = True,
                        window: Optional[int] = None,
                        factor: Optional[float] = None):
    """``("non-finite" | "exploding-norm", epoch_index)`` or ``None``.

    Non-finite wins: the ``finite`` flag (the in-program sentinel) or
    any non-finite value in any series. Exploding norm: the first norm
    series present (:data:`_NORM_SERIES` order) grew by more than
    ``factor`` over a trailing ``window`` epochs while already above
    the absolute floor — a drift alarm for fits still technically
    finite."""
    named = {k: list(v) for k, v in series.items() if v is not None}
    bad_epoch = None
    for values in named.values():
        for i, v in enumerate(values):
            if not math.isfinite(float(v)):
                bad_epoch = i if bad_epoch is None else min(bad_epoch, i)
                break
    if bad_epoch is not None:
        return "non-finite", bad_epoch
    if not finite:
        length = max((len(v) for v in named.values()), default=0)
        return "non-finite", max(length - 1, 0)
    w = window if window is not None else _window()
    f = factor if factor is not None else _factor()
    floor = _floor()
    for name in _NORM_SERIES:
        values = named.get(name)
        if not values:
            continue
        for i in range(w, len(values)):
            now, then = float(values[i]), float(values[i - w])
            if now > floor and now > f * max(then, floor / f):
                return "exploding-norm", i
        break
    return None


def report_divergence(algo: str, kind: str,
                      epoch: Optional[int] = None, **detail) -> None:
    """Emit the ``ml.health`` divergence event + labeled counter, and
    trip the flight recorder — the divergence that precedes a terminal
    :class:`NonFiniteState` is exactly the moment the convergence-series
    spans and recent metrics still explain what blew up."""
    _health_group().counter("divergences",
                            labels={"algo": algo, "kind": kind})
    attrs = {"algo": algo, "kind": kind}
    if epoch is not None:
        attrs["epoch"] = int(epoch)
    attrs.update(detail)
    tracing.tracer.event(HEALTH_EVENT, **attrs)
    try:
        from flink_ml_tpu.observability import flightrecorder

        payload = dict(attrs)
        # the event's "kind" (non-finite / exploding-norm) must not
        # collide with the incident's own kind parameter
        payload["divergence"] = payload.pop("kind")
        flightrecorder.record_incident("divergence", **payload)
    except Exception:  # noqa: BLE001 — recording must never mask the
        # divergence verdict (the caller may be about to raise on it)
        pass


def check_fit(algo: str, series: Dict[str, Sequence[float]],
              finite: bool = True, epoch0: int = 0,
              raise_nonfinite: bool = True):
    """The fit-side health tail: record the convergence series, classify
    divergence, report any finding, and raise the terminal
    :class:`NonFiniteState` on a non-finite verdict (unless the layer is
    disabled or ``raise_nonfinite`` is False). Returns the
    classification (``(kind, epoch)`` or ``None``)."""
    record_fit_series(algo, series, epoch0=epoch0)
    cls = classify_divergence(series, finite=finite)
    if cls is None:
        return None
    kind, epoch = cls
    report_divergence(algo, kind, epoch=epoch0 + epoch)
    if kind == "non-finite" and raise_nonfinite and guard_enabled():
        raise NonFiniteState(algo, epoch=epoch0 + epoch)
    return cls


def guard_final_state(algo: str, *leaves, loss=None) -> None:
    """The always-on tier: a cheap non-finite check over host arrays the
    fit already fetched (final coefficients, final mean loss) — no
    device sync, no series. Raises :class:`NonFiniteState` and emits the
    ``ml.health`` event when anything is non-finite."""
    if not guard_enabled():
        return
    bad = loss is not None and not math.isfinite(float(loss))
    for leaf in leaves:
        if leaf is not None and not bool(np.all(np.isfinite(
                np.asarray(leaf, np.float64)))):
            bad = True
    if bad:
        report_divergence(algo, "non-finite")
        raise NonFiniteState(algo)


class ConvergenceListener:
    """Health recorder for host-driven iteration modes: per epoch,
    ``extract(carry, epoch) -> {series_name: float}`` pulls the health
    scalars from the carry; a non-finite sample fails the fit fast at
    an epoch boundary, a clean run records the whole series at
    termination. Extraction LAGS one epoch: the host loop deliberately
    overlaps listener/checkpoint work with the still-executing device
    round (iteration._host_loop), and fetching the freshly-returned
    carry would serialize that — so each boundary reads the *previous*
    epoch's carry (whose device work has had a full epoch to drain) and
    the last carry flushes at termination. Duck-types
    :class:`~flink_ml_tpu.iteration.iteration.IterationListener` (all
    hooks are looked up by name)."""

    def __init__(self, algo: str, extract):
        self.algo = algo
        self._extract = extract
        self.series: Dict[str, List[float]] = {}
        self.finite = True
        self._done = False
        self._pending = None  # (epoch, carry) not yet extracted

    def _record(self, epoch, carry) -> None:
        vals = self._extract(carry, epoch)
        fin = True
        for name, v in vals.items():
            v = float(v)
            self.series.setdefault(name, []).append(v)
            fin = fin and math.isfinite(v)
        if not fin:
            self.finite = False
            self._done = True
            check_fit(self.algo, self.series, finite=False)

    def on_epoch_watermark_incremented(self, epoch, carry) -> None:
        pending, self._pending = self._pending, (epoch, carry)
        if pending is not None:
            self._record(*pending)

    def on_iteration_terminated(self, carry) -> None:
        if self._pending is not None:
            pending, self._pending = self._pending, None
            self._record(*pending)
        if not self._done:
            self._done = True
            check_fit(self.algo, self.series, finite=self.finite)

    def on_restart(self, attempt, error) -> None:
        pass

    def on_recovered(self, attempt) -> None:
        pass

    # -- canonical extracts (one definition for every host-mode fit) --------
    @classmethod
    def for_params(cls, algo: str, init_params) -> "ConvergenceListener":
        """For carries shaped ``(params, ..., mean_loss)`` (the SGD host
        and CSR rounds): records loss, ``‖Δparams‖`` against the
        previous epoch and ``‖params‖``."""
        prev = {"c": np.asarray(init_params, np.float64)}

        def extract(carry, epoch):
            c = np.asarray(carry[0], np.float64)
            row = {"loss": float(carry[2]),
                   "updateNorm": float(np.linalg.norm(c - prev["c"])),
                   "paramNorm": float(np.linalg.norm(c))}
            prev["c"] = c
            return row

        return cls(algo, extract)

    @classmethod
    def for_centroids(cls, algo: str,
                      init_centroids) -> "ConvergenceListener":
        """For carries shaped ``(centroids, ...)`` (the Lloyd host
        rounds): records the Frobenius center shift per epoch."""
        prev = {"c": np.asarray(init_centroids, np.float64)}

        def extract(carry, epoch):
            c = np.asarray(carry[0], np.float64)
            shift = float(np.linalg.norm(c - prev["c"]))
            prev["c"] = c
            return {"centerShift": shift}

        return cls(algo, extract)


# -- serving-path metrics -----------------------------------------------------

def trace_sample_rate() -> float:
    """The request-span sampling rate from ``FLINK_ML_TPU_TRACE_SAMPLE``
    (clamped to [0, 1]; default 1.0 — unparseable values fall back to
    the default rather than silently disabling tracing)."""
    raw = os.environ.get(SAMPLE_ENV)
    if raw is None or raw == "":
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 1.0


def trace_sampled() -> bool:
    """One Bernoulli draw at the configured sampling rate — the serving
    seam's per-request span decision (0 and 1 skip the RNG)."""
    rate = trace_sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    import random

    return random.random() < rate


_inflight: Dict[str, int] = {}
_inflight_lock = make_lock("observability.health.inflight")


def serving_inflight(servable: str, delta: int) -> int:
    """Track concurrent in-flight transforms per servable as the
    ``ml.serving inFlight{servable=}`` gauge (clamped at 0 — a lone
    decrement from an unbalanced error path must not go negative).
    Returns the new value."""
    with _inflight_lock:
        value = max(0, _inflight.get(servable, 0) + int(delta))
        _inflight[servable] = value
    metrics.group(ML_GROUP, "serving").gauge(
        "inFlight", value, labels={"servable": servable})
    return value


def observe_serving_error(servable: str, exception: str,
                          latency_ms: float) -> None:
    """Record one FAILED servable transform: the windowed
    ``errors{servable=}`` counter (the error-rate SLO numerator), a
    per-exception-class ``errorsByClass{servable=,exception=}``
    counter, and the failure latency as an ``errorMs`` histogram —
    kept apart from ``transformMs`` so fast-failing requests cannot
    flatter the success latency distribution."""
    group = metrics.group(ML_GROUP, "serving")
    labels = {"servable": servable}
    group.windowed_counter("errors", horizon_s=SERVING_HORIZON_S,
                           slices=SERVING_SLICES, labels=labels).inc()
    group.counter("errorsByClass",
                  labels={"servable": servable, "exception": exception})
    group.histogram("errorMs", labels=labels).observe(latency_ms)


def observe_serving_rejected(servable: str, reason: str) -> None:
    """Record one request shed by admission control (deadline expired
    in queue, queue full, shape outside the bucket table — serving/
    batcher.py) as the windowed ``rejected{servable=,reason=}`` counter.
    Kept apart from ``errors``: shed load is the server *protecting* its
    SLO, and a loadgen verdict must be able to tell the two apart."""
    metrics.group(ML_GROUP, "serving").windowed_counter(
        "rejected", horizon_s=SERVING_HORIZON_S, slices=SERVING_SLICES,
        labels={"servable": servable, "reason": reason}).inc()


def summarize_values(servable: str, name: str, values) -> None:
    """Record a distribution summary for one batch of numeric values:
    the ``<name>Min/Max/Mean/FiniteFraction`` gauges in ``ml.serving``
    (labeled by servable — per-batch, last-write-wins: the cumulative
    Prometheus view, byte-identical to before) PLUS a **windowed**
    ``<name>Values`` histogram (common/metrics.py WindowedHistogram,
    :data:`SUMMARY_BUCKETS`), so ``/slo``, ``/drift`` and the drift
    evaluator (observability/drift.py) can read the *recent* value
    distribution instead of whatever batch happened to write the gauges
    last — one early outlier batch no longer poisons the only record of
    the distribution for the process lifetime. A batch with non-finite
    values emits an ``ml.health`` ``non-finite-<name>`` event; nothing
    ever raises from here."""
    group = metrics.group(ML_GROUP, "serving")
    labels = {"servable": servable}
    try:
        vals = np.asarray(list(values), np.float64)
    except (TypeError, ValueError):
        return  # non-scalar column (vectors): no summary
    if vals.ndim != 1 or vals.size == 0:
        return
    finite = np.isfinite(vals)
    frac = float(finite.mean())
    fv = vals[finite]
    group.gauge(f"{name}FiniteFraction", frac, labels=labels)
    if fv.size:
        group.gauge(f"{name}Min", float(fv.min()), labels=labels)
        group.gauge(f"{name}Max", float(fv.max()), labels=labels)
        group.gauge(f"{name}Mean", float(fv.mean()), labels=labels)
        hist = group.windowed_histogram(
            f"{name}Values", buckets=SUMMARY_BUCKETS,
            horizon_s=SERVING_HORIZON_S, slices=SERVING_SLICES,
            labels=labels)
        for v in fv:
            hist.observe(float(v))
    if frac < 1.0:
        report_divergence(servable, f"non-finite-{name}",
                          fraction=round(frac, 6), rows=int(vals.size))


def observe_serving_shards(servable: str, counts, device_ids) -> None:
    """Record one mesh-sharded serving dispatch's per-device row split
    (serving/batcher.py → servable/lr.py sharded twin): the real rows
    each device's slice of the padded bucket holds as
    ``ml.serving shardRows{servable=,device=}`` gauges plus one
    ``shardImbalance{servable=}`` gauge (max/mean over the per-device
    counts; 1.0 = perfectly balanced, N = all real rows on one of N
    devices). The per-tick serving twin of the training-side
    ``ml.shard rows`` series — deliberately without the straggler
    detector, since a partially-filled bucket loading shard 0 first is
    the dispatch contract, not a straggler."""
    group = metrics.group(ML_GROUP, "serving")
    counts = [int(c) for c in counts]
    for dev, rows in zip(device_ids, counts):
        group.gauge("shardRows", rows,
                    labels={"servable": servable, "device": str(dev)})
    mean = sum(counts) / max(len(counts), 1)
    imbalance = (max(counts) / mean) if mean > 0 else 0.0
    group.gauge("shardImbalance", round(imbalance, 4),
                labels={"servable": servable})


def observe_serving(servable: str, rows: int, latency_ms: float,
                    predictions=None) -> None:
    """Record one servable ``transform`` into ``ml.serving``: windowed
    latency + row-count histograms and transform/row counters (labeled
    by servable — cumulative views unchanged, so merges and Prometheus
    keep working while the SLO engine reads sliding windows) and, when
    a numeric prediction column is available, its
    :func:`summarize_values` distribution summary. Non-finite
    predictions emit an ``ml.health`` event but never fail the serving
    call."""
    group = metrics.group(ML_GROUP, "serving")
    labels = {"servable": servable}
    group.windowed_counter("transforms", horizon_s=SERVING_HORIZON_S,
                           slices=SERVING_SLICES, labels=labels).inc()
    group.windowed_counter("rowsTotal", horizon_s=SERVING_HORIZON_S,
                           slices=SERVING_SLICES,
                           labels=labels).inc(int(rows))
    # registering the errors window here (no increment) keeps the
    # error-rate SLO's numerator and denominator on the same windowed
    # source even before the first failure
    group.windowed_counter("errors", horizon_s=SERVING_HORIZON_S,
                           slices=SERVING_SLICES, labels=labels)
    group.windowed_histogram("transformMs",
                             horizon_s=SERVING_HORIZON_S,
                             slices=SERVING_SLICES,
                             labels=labels).observe(latency_ms)
    group.windowed_histogram("rows", buckets=COUNT_BUCKETS,
                             horizon_s=SERVING_HORIZON_S,
                             slices=SERVING_SLICES,
                             labels=labels).observe(float(rows))
    if predictions is not None:
        summarize_values(servable, "prediction", predictions)


# -- the `flink-ml-tpu-trace health` view -------------------------------------

_LABEL_RE = None


def _parse_labels(label_str: str) -> Dict[str, str]:
    """Inverse of metrics.metric_key's label rendering. Unescaping is
    ONE pass over ``\\.`` pairs — sequential str.replace cannot decode
    this grammar (``a\\nb`` with a literal backslash encodes to
    ``a\\\\nb``; replacing ``\\n`` first would turn the escaped
    backslash + ``n`` into a real newline)."""
    global _LABEL_RE
    import re
    if _LABEL_RE is None:
        _LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
    out = {}
    for k, v in _LABEL_RE.findall(label_str or ""):
        out[k] = re.sub(
            r"\\(.)", lambda m: {"n": "\n"}.get(m.group(1), m.group(1)),
            v)
    return out


def _fmtv(v) -> str:
    if v is None:
        return "-"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if math.isnan(f):
        return "nan"
    if abs(f) >= 1e5 or (f != 0 and abs(f) < 1e-3):
        return f"{f:.3e}"
    return f"{f:.4g}"


def health_summary(spans: List[dict],
                   snapshot: Dict[str, dict]) -> dict:
    """Structured model-health view of a trace dir: per-fit convergence
    tables (from ``ml.convergence`` events, grouped per trace+algo),
    the ``ml.health`` divergence timeline, and the ``ml.serving``
    summary from the metrics snapshot."""
    fits: Dict[tuple, dict] = {}
    health_events: List[dict] = []
    for sp in spans:
        for ev in sp.get("events", ()):
            attrs = ev.get("attrs", {})
            if ev.get("name") == CONVERGENCE_EVENT:
                key = (sp.get("trace"), attrs.get("algo", "?"))
                fit = fits.setdefault(key, {
                    "algo": attrs.get("algo", "?"),
                    "trace": sp.get("trace"),
                    "epochs": []})
                row = {k: attrs[k] for k in attrs if k != "algo"}
                fit["epochs"].append(row)
            elif ev.get("name") == HEALTH_EVENT:
                health_events.append({"ts_us": ev.get("ts_us", 0),
                                      "attrs": attrs})
    fit_rows = []
    for fit in fits.values():
        epochs = sorted(fit["epochs"],
                        key=lambda r: r.get("epoch", 0))
        row = {"algo": fit["algo"], "trace": fit["trace"],
               "epochs": len(epochs), "series": {}}
        names = {k for e in epochs for k in e if k != "epoch"}
        for name in sorted(names):
            vals = [float(e[name]) for e in epochs if name in e]
            finite = [v for v in vals if math.isfinite(v)]
            row["series"][name] = {
                "first": vals[0] if vals else None,
                "last": vals[-1] if vals else None,
                "min": min(finite) if finite else None,
                "nonfinite": len(vals) - len(finite)}
        fit_rows.append(row)
    fit_rows.sort(key=lambda r: r["algo"])
    health_events.sort(key=lambda e: e["ts_us"])

    serving = {}
    sgroup = snapshot.get(f"{ML_GROUP}.serving", {})
    for key, value in sgroup.get("counters", {}).items():
        name = key.partition("{")[0]
        servable = _parse_labels(key).get("servable", "?")
        serving.setdefault(servable, {})[name] = value
    from flink_ml_tpu.common.metrics import histogram_quantile
    for key, hist in sgroup.get("histograms", {}).items():
        name = key.partition("{")[0]
        servable = _parse_labels(key).get("servable", "?")
        row = serving.setdefault(servable, {})
        if name == "transformMs" and hist.get("count"):
            row["transformMs_p50"] = histogram_quantile(hist, 0.5)
            row["transformMs_p99"] = histogram_quantile(hist, 0.99)
    for key, value in sgroup.get("gauges", {}).items():
        name = key.partition("{")[0]
        servable = _parse_labels(key).get("servable", "?")
        serving.setdefault(servable, {})[name] = value

    divergences = {}
    hgroup = snapshot.get(f"{ML_GROUP}.health", {})
    for key, value in hgroup.get("counters", {}).items():
        if key.partition("{")[0] == "divergences":
            labels = _parse_labels(key)
            divergences[f"{labels.get('algo', '?')}/"
                        f"{labels.get('kind', '?')}"] = value

    return {"fits": fit_rows, "health_events": health_events,
            "serving": serving, "divergences": divergences}


def render_health(summary: dict) -> str:
    out = []
    fits = summary["fits"]
    out.append(f"{len(fits)} fit(s) with convergence telemetry, "
               f"{len(summary['health_events'])} health event(s)")
    for fit in fits:
        out.append("")
        out.append(f"fit {fit['algo']}  ({fit['epochs']} epoch sample(s))")
        out.append(f"  {'series':<14} {'first':>12} {'last':>12} "
                   f"{'min':>12} {'non-finite':>11}")
        for name, st in fit["series"].items():
            out.append(
                f"  {name:<14} {_fmtv(st['first']):>12} "
                f"{_fmtv(st['last']):>12} {_fmtv(st['min']):>12} "
                f"{st['nonfinite']:>11}")
    if summary["health_events"]:
        out.append("")
        out.append("health event timeline:")
        t0 = summary["health_events"][0]["ts_us"]
        for ev in summary["health_events"]:
            attrs = " ".join(f"{k}={v}" for k, v in ev["attrs"].items())
            out.append(f"  +{(ev['ts_us'] - t0) / 1000.0:>10.3f} ms  "
                       f"{HEALTH_EVENT}  {attrs}")
    if summary["divergences"]:
        out.append("")
        out.append("divergence counters:")
        for key, value in sorted(summary["divergences"].items()):
            out.append(f"  {key}: {value}")
    if summary["serving"]:
        out.append("")
        out.append("serving metrics:")
        for servable, row in sorted(summary["serving"].items()):
            out.append(f"  {servable}:")
            for name in ("transforms", "rowsTotal", "transformMs_p50",
                         "transformMs_p99", "predictionMin",
                         "predictionMean", "predictionMax",
                         "predictionFiniteFraction"):
                if name in row:
                    out.append(f"    {name}: {_fmtv(row[name])}")
    return "\n".join(out)


def _json_safe(obj):
    """Recursively replace non-finite floats with their string names so
    the structure serializes as STRICT JSON (the text format has no
    NaN/Infinity tokens)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj).replace("inf", "Infinity").replace(
            "nan", "NaN")
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def main(argv=None) -> int:
    """``flink-ml-tpu-trace health <dir>`` — model-health view of a
    trace directory (``--json`` is strict JSON: non-finite floats render
    as the strings "NaN"/"Infinity"/"-Infinity"). ``--check`` exits 3
    (the sweep's correctness class) when any ``ml.health`` event is
    present, 2 on unreadable/empty artifacts."""
    import argparse
    import json
    import sys

    from flink_ml_tpu.observability.exporters import (
        read_metrics,
        read_spans,
        resolve_trace_dir,
    )

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace health",
        description="Model-health view of a FLINK_ML_TPU_TRACE_DIR: "
                    "per-fit convergence, divergence events, serving "
                    "metrics.")
    parser.add_argument("trace_dir")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 3 when a health event is present, "
                             "2 on empty/unreadable artifacts")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    args = parser.parse_args(argv)

    try:
        args.trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        spans = read_spans(args.trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace health: cannot read "
              f"{args.trace_dir}: {e}", file=sys.stderr)
        return 2
    snapshot = read_metrics(args.trace_dir)
    if args.check and not spans and not snapshot:
        print(f"flink-ml-tpu-trace health: no artifacts in "
              f"{args.trace_dir}", file=sys.stderr)
        return 2
    summary = health_summary(spans, snapshot)
    from flink_ml_tpu.observability.exporters import pipe_guard

    with pipe_guard():  # `... | head` closing the pipe is not an error
        if args.json:
            # strict-JSON output: json.dumps would render float('nan')
            # as the bare non-standard `NaN` token — unparseable by jq
            # et al. exactly when a fit diverged, which is this view's
            # whole point. Non-finite floats become strings.
            print(json.dumps(_json_safe(summary), indent=2, default=str))
        else:
            print(render_health(summary))
    if args.check and summary["health_events"]:
        print(f"flink-ml-tpu-trace health: "
              f"{len(summary['health_events'])} health event(s) present",
              file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
