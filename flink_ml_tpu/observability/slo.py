"""SLO engine: declarative latency/error-rate objectives, multi-window
burn-rate evaluation, and the ``flink-ml-tpu-trace slo`` gate.

The serving seam (servable/api.py) records windowed latency histograms
and error counters into ``ml.serving`` (common/metrics.py
:class:`~flink_ml_tpu.common.metrics.WindowedHistogram` /
:class:`~flink_ml_tpu.common.metrics.WindowedCounter`); this module
turns them into verdicts:

- an :class:`SLO` pairs a metric selector with ONE objective — a
  latency quantile bound (``p99 of transformMs <= threshold_ms``), a
  max error ratio (``errors / (errors + transforms) <= max``), or a
  **drift** bound (the worst ``drift{servable=,feature=,stat=}`` gauge
  the drift evaluator records, observability/drift.py, must stay
  ``<= max_drift``; no gauges → ok, ``source: "missing"``) — over a
  primary ``window_s``;
- every SLO additionally evaluates **multi-window burn rates** (Google
  SRE style): the fraction of the error budget being consumed, per
  window — ``bad_fraction / budget`` where the budget is ``1 -
  quantile`` for latency and ``max_error_ratio`` for errors. A short
  window catches fast burns, a long one slow ones; each has its own
  ``max_burn_rate``;
- violations emit ``ml.slo`` instant events (tracing) and
  ``slo_violations{slo=...}`` counters in the ``ml.slo`` registry
  group, so the trace artifacts carry the verdict history.

Specs load from JSON (any Python) or TOML (Python 3.11+, stdlib
``tomllib``) — see docs/observability.md "Live telemetry & SLOs" for
the format — or fall back to :func:`default_slos`. Evaluation sources:

- **live** (the ``/slo`` endpoint, observability/server.py): sliding
  windows straight from the process registry's windowed metrics;
- **artifacts** (``flink-ml-tpu-trace slo <dir>``): the merged
  ``metrics-*.json`` snapshots are cumulative, so every objective
  evaluates the run-total distribution and is tagged
  ``source: "cumulative"`` — the windowed half needs the live endpoint;
- **fleet** (``scope: fleet`` on the SLO): windowed bucket slices from
  the live fleet beacons (observability/fleet.py) are summed bin-exactly
  across *alive* members BEFORE quantiles/burn rates, tagged
  ``source: "fleet[<n>]:<w>s"``; the verdict carries ``members`` /
  ``membersAlive`` / ``membersMissing`` (+ a ``perMember`` quantile
  table for latency kinds) and FAILS outright while any member is dead
  — a half-dead fleet must not report a healthy p99 from survivors
  alone.

CLI: ``flink-ml-tpu-trace slo <dir> [--spec F] [--check] [--json]
[--latest]`` — with ``--check`` exits :data:`EXIT_VIOLATION` (4) on any
violated SLO, :data:`EXIT_INVALID` (2) on broken artifacts or an
unreadable spec; consistent with ``diff`` (docs/observability.md exit
codes).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from flink_ml_tpu.common.metrics import (
    ML_GROUP,
    WindowedHistogram,
    histogram_quantile,
    metrics,
)
from flink_ml_tpu.observability import tracing

__all__ = [
    "EXIT_OK",
    "EXIT_INVALID",
    "EXIT_VIOLATION",
    "SLO_EVENT",
    "SLO_SPEC_ENV",
    "SLO",
    "default_slos",
    "active_slos",
    "load_specs",
    "evaluate_slos",
    "render_verdicts",
    "main",
]

EXIT_OK = 0
EXIT_INVALID = 2
#: the documented violation exit code — same class as ``diff --budget``
EXIT_VIOLATION = 4

#: instant-event name for SLO violations in the trace
SLO_EVENT = "ml.slo"

#: env var holding a spec file path; when set, the live ``/slo``
#: endpoint evaluates it instead of :func:`default_slos`
SLO_SPEC_ENV = "FLINK_ML_TPU_SLO_SPEC"

#: default multi-window burn-rate gates: (window_s, max_burn_rate) —
#: the SRE-handbook fast/slow pair scaled to a process-local horizon
DEFAULT_BURN_WINDOWS = ((60.0, 14.4), (300.0, 6.0))

_KINDS = ("latency", "error-rate", "drift", "quality")


@dataclasses.dataclass
class SLO:
    """One declarative objective over a metric family. Fields unused by
    the ``kind`` (e.g. ``threshold_ms`` for error-rate) are ignored.

    Kind ``drift`` reads the ``drift{servable=,feature=,stat=}`` gauges
    the drift evaluator records (observability/drift.py): the max gauge
    matching ``stat`` (+ any ``labels`` narrowing) must stay at or
    under ``max_drift``; with no matching gauges the objective is ok
    and tagged ``source: "missing"`` — an unpublished baseline must
    never fail an SLO. ``group`` defaults to ``ml.drift`` for this
    kind.

    Kind ``quality`` reads the ``quality{servable=,metric=}`` gauges
    the continuous-evaluation plane records
    (observability/evaluation.py): the WORST gauge matching ``metric``
    (higher-is-better — AUC by default) must stay at or above
    ``min_quality``, and with ``max_quality_delta`` set, each
    servable's live gauge must not fall more than that under its
    ``qualityBaseline`` twin. No matching gauges — no feedback joined
    yet, or a thin window — is ok with ``source: "missing"``: absence
    of ground truth never burns an error budget. ``group`` defaults to
    ``ml.quality`` for this kind."""

    name: str
    kind: str = "latency"   # "latency" | "error-rate" | "drift" | "quality"
    group: str = f"{ML_GROUP}.serving"
    histogram: str = "transformMs"   # latency source (ms histogram)
    total: str = "transforms"        # error-rate denominator counter
    errors: str = "errors"           # error-rate numerator counter
    labels: Optional[Dict[str, str]] = None  # None → every series
    quantile: float = 0.99
    threshold_ms: float = 500.0
    max_error_ratio: float = 0.01
    window_s: float = 60.0
    burn_windows: Tuple[Tuple[float, float], ...] = DEFAULT_BURN_WINDOWS
    stat: str = "psi"                # drift statistic: psi | js | ks
    max_drift: float = 0.2           # drift gauge bound
    metric: str = "auc"              # quality metric (higher-is-better)
    min_quality: float = 0.6         # quality gauge floor
    max_quality_delta: Optional[float] = None  # live-under-baseline bound
    scope: str = "process"           # "process" | "fleet"

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"SLO {self.name!r}: unknown kind {self.kind!r} "
                f"(expected one of {_KINDS})")
        if self.scope not in ("process", "fleet"):
            raise ValueError(
                f"SLO {self.name!r}: unknown scope {self.scope!r} "
                f"(expected 'process' or 'fleet')")
        if not 0.0 < float(self.quantile) < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: quantile must be in (0, 1)")
        if float(self.window_s) <= 0:
            raise ValueError(f"SLO {self.name!r}: window_s must be > 0")
        if self.kind == "drift":
            if self.stat not in ("psi", "js", "ks"):
                raise ValueError(
                    f"SLO {self.name!r}: drift stat must be psi|js|ks, "
                    f"got {self.stat!r}")
            if self.group == f"{ML_GROUP}.serving":
                # the drift gauges live in their own group; only the
                # untouched default is redirected — an explicit group
                # (a custom evaluator's) is honored
                self.group = f"{ML_GROUP}.drift"
        if self.kind == "quality":
            if self.max_quality_delta is not None \
                    and float(self.max_quality_delta) < 0:
                raise ValueError(
                    f"SLO {self.name!r}: max_quality_delta must be "
                    f">= 0")
            if self.group == f"{ML_GROUP}.serving":
                # same rule as drift: only the untouched default moves
                self.group = f"{ML_GROUP}.quality"
        self.burn_windows = tuple(
            (float(w), float(m)) for w, m in self.burn_windows)

    @classmethod
    def from_dict(cls, d: dict) -> "SLO":
        if not isinstance(d, dict) or "name" not in d:
            raise ValueError(f"SLO spec entry must be a mapping with a "
                             f"'name', got {d!r}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"SLO {d.get('name')!r}: unknown spec "
                             f"key(s) {sorted(unknown)}")
        return cls(**d)

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["burn_windows"] = [list(bw) for bw in self.burn_windows]
        return out


def default_slos() -> List[SLO]:
    """The out-of-the-box serving SLOs: p99 transform latency and the
    aggregate error ratio, each across every servable's series."""
    return [SLO(name="serving-latency-p99", kind="latency"),
            SLO(name="serving-error-rate", kind="error-rate")]


def load_specs(path: str) -> List[SLO]:
    """Parse an SLO spec file — JSON anywhere, TOML on Python 3.11+
    (stdlib ``tomllib``; no new dependency). The document is a
    ``{"slos": [...]}`` mapping (TOML: ``[[slos]]`` tables) or a bare
    JSON list. Raises ValueError on malformed specs."""
    with open(path, "rb") as f:
        raw = f.read()
    if path.endswith(".toml"):
        try:
            import tomllib
        except ImportError as e:  # Python 3.10: no stdlib TOML parser
            raise ValueError(
                "TOML SLO specs need Python 3.11+ (tomllib); "
                "use the JSON spelling instead") from e
        try:
            doc = tomllib.loads(raw.decode("utf-8"))
        except tomllib.TOMLDecodeError as e:
            raise ValueError(f"{path}: invalid TOML: {e}") from e
    else:
        try:
            doc = json.loads(raw.decode("utf-8"))
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: invalid JSON: {e}") from e
    items = doc.get("slos") if isinstance(doc, dict) else doc
    if not isinstance(items, list) or not items:
        raise ValueError(f"{path}: expected a non-empty 'slos' list")
    specs = [SLO.from_dict(d) for d in items]
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate SLO names in spec")
    return specs


def active_slos() -> List[SLO]:
    """The SLOs the live endpoint evaluates: ``FLINK_ML_TPU_SLO_SPEC``
    (a spec file path) when set, else :func:`default_slos`."""
    path = os.environ.get(SLO_SPEC_ENV)
    if path:
        return load_specs(path)
    return default_slos()


# -- series matching / combination -------------------------------------------

def _match_key(key: str, name: str,
               labels: Optional[Dict[str, str]]) -> bool:
    base, _, rest = key.partition("{")
    if base != name:
        return False
    if not labels:
        return True
    from flink_ml_tpu.observability.health import _parse_labels

    got = _parse_labels(rest[:-1] if rest else "")
    return all(got.get(k) == str(v) for k, v in labels.items())


def _combine(snaps: Sequence[dict]) -> dict:
    """Sum matching labeled histogram series into one snapshot (they
    must share a bucket layout — ``ml.serving transformMs`` does by
    construction; drift raises, surfacing as broken artifacts)."""
    buckets = tuple(float(b) for b in snaps[0].get("buckets", ()))
    out = {"buckets": list(buckets), "counts": [0] * len(buckets),
           "sum": 0.0, "count": 0}
    for s in snaps:
        if tuple(float(b) for b in s.get("buckets", ())) != buckets:
            raise ValueError(
                "mismatched bucket layouts across matching SLO series — "
                "narrow the SLO with labels")
        for i, c in enumerate(s.get("counts", ())):
            out["counts"][i] += int(c)
        out["sum"] += float(s.get("sum", 0.0))
        out["count"] += int(s.get("count", 0))
    return out


def _fraction_le(snap: dict, bound: float) -> float:
    """Fraction of observations <= ``bound`` (linear interpolation
    inside the winning bucket, same rule as histogram_quantile);
    observations past the last finite bucket count as above."""
    total = int(snap.get("count", 0))
    if total <= 0:
        return 1.0
    prev_b, prev_c = 0.0, 0
    for b, c in zip(snap.get("buckets", ()), snap.get("counts", ())):
        b = float(b)
        if bound <= b:
            if b <= prev_b:
                return c / total
            frac = (bound - prev_b) / (b - prev_b)
            return (prev_c + (c - prev_c) * frac) / total
        prev_b, prev_c = b, int(c)
    return prev_c / total


class _RegistrySource:
    """Live evaluation: sliding windows from the process registry's
    windowed metrics; plain series fall back to cumulative."""

    def __init__(self, registry):
        self._registry = registry

    def hist_window(self, group: str, name: str,
                    labels: Optional[Dict[str, str]], window_s: float):
        grp = self._registry.group(*group.split("."))
        keys = [k for k in grp.snapshot().get("histograms", {})
                if _match_key(k, name, labels)]
        snaps, sources = [], set()
        for key in keys:
            # a fully-rendered key passes through metric_key unchanged,
            # so histogram(key) returns the existing registered object
            h = grp.histogram(key)
            if isinstance(h, WindowedHistogram):
                snaps.append(h.window_snapshot(window_s))
                sources.add("windowed")
            else:
                snaps.append(h.snapshot())
                sources.add("cumulative")
        if not snaps:
            return None, "windowed"
        return _combine(snaps), ("windowed" if sources == {"windowed"}
                                 else "cumulative")

    def counter_window(self, group: str, name: str,
                       labels: Optional[Dict[str, str]],
                       window_s: float):
        grp = self._registry.group(*group.split("."))
        wcs = [wc for key, wc in grp.windowed_counter_items()
               if _match_key(key, name, labels)]
        if wcs:
            return (sum(wc.window_delta(window_s) for wc in wcs),
                    "windowed")
        counters = grp.snapshot().get("counters", {})
        vals = [int(v) for k, v in counters.items()
                if _match_key(k, name, labels)]
        if vals:
            return sum(vals), "cumulative"
        return 0, "none"

    def gauge_values(self, group: str, name: str,
                     labels: Optional[Dict[str, str]]):
        gauges = self._registry.group(
            *group.split(".")).snapshot().get("gauges", {})
        return [(k, float(v)) for k, v in gauges.items()
                if _match_key(k, name, labels)]


class _SnapshotSource:
    """Artifact evaluation: a merged registry snapshot is cumulative —
    window sizes are ignored and every value is tagged accordingly."""

    def __init__(self, snapshot: Dict[str, dict]):
        self._snap = snapshot or {}

    def hist_window(self, group, name, labels, window_s):
        hists = (self._snap.get(group) or {}).get("histograms", {})
        snaps = [h for k, h in hists.items()
                 if _match_key(k, name, labels)]
        if not snaps:
            return None, "cumulative"
        return _combine(snaps), "cumulative"

    def counter_window(self, group, name, labels, window_s):
        counters = (self._snap.get(group) or {}).get("counters", {})
        vals = [int(v) for k, v in counters.items()
                if _match_key(k, name, labels)]
        if vals:
            return sum(vals), "cumulative"
        return 0, "none"

    def gauge_values(self, group, name, labels):
        gauges = (self._snap.get(group) or {}).get("gauges", {})
        out = []
        for k, v in gauges.items():
            if not _match_key(k, name, labels):
                continue
            try:
                out.append((k, float(v)))
            except (TypeError, ValueError):
                continue  # non-numeric gauge: not comparable
        return out


class _FleetSource:
    """``scope: fleet`` evaluation: windowed bucket slices summed
    bin-exactly across the fleet's *alive* members
    (observability/fleet.py :class:`~FleetView`) BEFORE any quantile or
    burn rate — a half-dead fleet must not report a healthy p99 from
    survivors alone, so the members that did NOT contribute surface as
    ``membersMissing`` on the verdict (and a dead member fails it)."""

    def __init__(self, view):
        self.view = view

    def hist_window(self, group, name, labels, window_s):
        return self.view.hist_window(group, name, labels, window_s)

    def counter_window(self, group, name, labels, window_s):
        return self.view.counter_window(group, name, labels, window_s)

    def gauge_values(self, group, name, labels):
        return self.view.gauge_values(group, name, labels)


class _EmptyFleetSource:
    """A fleet-scope SLO with no fleet telemetry resolvable: every read
    answers 'no data' tagged ``fleet-missing`` — absence of a fleet
    plane is visible on the verdict, never a crash."""

    view = None

    def hist_window(self, group, name, labels, window_s):
        return None, "fleet-missing"

    def counter_window(self, group, name, labels, window_s):
        return 0, "fleet-missing"

    def gauge_values(self, group, name, labels):
        return []


def _make_fleet_source(fleet_view=None, fleet_dir: Optional[str] = None):
    """The ``scope: fleet`` source: an explicit view, a directory, or
    this process's own fleet-dir resolution (the ``/slo`` route path)."""
    if fleet_view is not None:
        return _FleetSource(fleet_view)
    from flink_ml_tpu.observability import fleet

    base = fleet_dir
    if base is not None:
        base = fleet.find_fleet_dir(base) or base
    else:
        base = fleet.fleet_dir()
    if not base:
        return _EmptyFleetSource()
    view = fleet.FleetView(base)
    if not view.members:
        return _EmptyFleetSource()
    return _FleetSource(view)


# -- evaluation ---------------------------------------------------------------

def _eval_latency(slo: SLO, source) -> List[dict]:
    objectives = []
    snap, src = source.hist_window(slo.group, slo.histogram, slo.labels,
                                   slo.window_s)
    n = int(snap["count"]) if snap else 0
    value = histogram_quantile(snap, slo.quantile) if snap else \
        float("nan")
    ok = not (n > 0 and value > slo.threshold_ms)
    objectives.append({
        "objective": "latency-quantile", "window_s": slo.window_s,
        "quantile": slo.quantile,
        "value_ms": None if math.isnan(value) else round(value, 3),
        "threshold_ms": slo.threshold_ms, "samples": n, "ok": ok,
        "source": src})
    budget = max(1.0 - slo.quantile, 1e-9)
    for window_s, max_burn in slo.burn_windows:
        snap, src = source.hist_window(slo.group, slo.histogram,
                                       slo.labels, window_s)
        n = int(snap["count"]) if snap else 0
        bad = (1.0 - _fraction_le(snap, slo.threshold_ms)) if n else 0.0
        burn = bad / budget
        objectives.append({
            "objective": "latency-burn", "window_s": window_s,
            "bad_fraction": round(bad, 6),
            "budget_fraction": round(budget, 6),
            "burn_rate": round(burn, 3), "max_burn_rate": max_burn,
            "samples": n, "ok": n == 0 or burn <= max_burn,
            "source": src})
    return objectives


def _eval_error_rate(slo: SLO, source) -> List[dict]:
    objectives = []
    windows = [(slo.window_s, None)] + list(slo.burn_windows)
    for window_s, max_burn in windows:
        errors, esrc = source.counter_window(slo.group, slo.errors,
                                             slo.labels, window_s)
        total, tsrc = source.counter_window(slo.group, slo.total,
                                            slo.labels, window_s)
        requests = int(errors) + int(total)
        ratio = (errors / requests) if requests else 0.0
        if esrc.startswith("fleet") or tsrc.startswith("fleet"):
            # fleet-scope reads keep their member-count attribution
            src = tsrc if tsrc.startswith("fleet") else esrc
        else:
            src = ("windowed" if {esrc, tsrc} <= {"windowed", "none"}
                   else "cumulative")
        if max_burn is None:  # the primary objective
            objectives.append({
                "objective": "error-ratio", "window_s": window_s,
                "errors": int(errors), "requests": requests,
                "value": round(ratio, 6),
                "max_error_ratio": slo.max_error_ratio,
                "ok": requests == 0 or ratio <= slo.max_error_ratio,
                "source": src})
        else:
            budget = max(slo.max_error_ratio, 1e-9)
            burn = ratio / budget
            objectives.append({
                "objective": "error-burn", "window_s": window_s,
                "bad_fraction": round(ratio, 6),
                "budget_fraction": round(budget, 6),
                "burn_rate": round(burn, 3), "max_burn_rate": max_burn,
                "samples": requests,
                "ok": requests == 0 or burn <= max_burn,
                "source": src})
    return objectives


def _eval_drift(slo: SLO, source) -> List[dict]:
    """The ``drift`` objective: the worst matching
    ``drift{servable=,feature=,stat=}`` gauge (observability/drift.py
    records them on every evaluation) must not exceed ``max_drift``.
    No matching gauges — no baseline published, or no evaluation yet —
    is ok with ``source: "missing"``: drift absence of evidence never
    burns an error budget."""
    labels = dict(slo.labels or {})
    labels["stat"] = slo.stat
    gauges = source.gauge_values(slo.group, "drift", labels)
    finite = [(k, v) for k, v in gauges if math.isfinite(v)]
    if not finite:
        return [{"objective": "drift-stat", "stat": slo.stat,
                 "value": None, "max_drift": slo.max_drift,
                 "series": 0, "worst": None, "ok": True,
                 "source": "missing"}]
    worst_key, worst = max(finite, key=lambda kv: kv[1])
    return [{"objective": "drift-stat", "stat": slo.stat,
             "value": round(worst, 6), "max_drift": slo.max_drift,
             "series": len(finite), "worst": worst_key,
             "ok": worst <= slo.max_drift, "source": "gauge"}]


def _eval_quality(slo: SLO, source) -> List[dict]:
    """The ``quality`` objective: the worst matching
    ``quality{servable=,metric=}`` gauge (observability/evaluation.py
    records them once the joined-label floor is met) must stay at or
    above ``min_quality``; with ``max_quality_delta``, each servable's
    live gauge is also held within that delta under its
    ``qualityBaseline`` twin. No matching gauges — no feedback joined,
    or a thin window — is ok with ``source: "missing"``: absence of
    ground truth never burns an error budget."""
    from flink_ml_tpu.observability.health import _parse_labels

    labels = dict(slo.labels or {})
    labels["metric"] = slo.metric
    gauges = source.gauge_values(slo.group, "quality", labels)
    finite = [(k, v) for k, v in gauges if math.isfinite(v)]
    if not finite:
        return [{"objective": "quality-metric", "metric": slo.metric,
                 "value": None, "min_quality": slo.min_quality,
                 "series": 0, "worst": None, "ok": True,
                 "source": "missing"}]
    worst_key, worst = min(finite, key=lambda kv: kv[1])
    objectives = [{"objective": "quality-metric", "metric": slo.metric,
                   "value": round(worst, 6),
                   "min_quality": slo.min_quality,
                   "series": len(finite), "worst": worst_key,
                   "ok": worst >= slo.min_quality, "source": "gauge"}]
    if slo.max_quality_delta is None:
        return objectives
    base_gauges = source.gauge_values(slo.group, "qualityBaseline",
                                      labels)
    def _series_key(key: str):
        # "quality{metric=auc,servable=X}" — fleet-scope reads append
        # "@member", so pair live/baseline by (servable, member tail)
        _, _, rest = key.partition("{")
        body, _, tail = rest.partition("}")
        return _parse_labels(body).get("servable"), tail

    by_servable = {}
    for k, v in base_gauges:
        if not math.isfinite(v):
            continue
        by_servable[_series_key(k)] = v
    worst_delta, worst_pair = None, None
    for k, v in finite:
        base = by_servable.get(_series_key(k))
        if base is None:
            continue
        delta = base - v
        if worst_delta is None or delta > worst_delta:
            worst_delta, worst_pair = delta, k
    if worst_delta is None:
        # live gauges with no baseline twin: the delta objective has
        # nothing to anchor on — a publishing gap, not a regression
        objectives.append({
            "objective": "quality-delta", "metric": slo.metric,
            "value": None,
            "max_quality_delta": slo.max_quality_delta,
            "worst": None, "ok": True, "source": "missing"})
    else:
        objectives.append({
            "objective": "quality-delta", "metric": slo.metric,
            "value": round(worst_delta, 6),
            "max_quality_delta": slo.max_quality_delta,
            "worst": worst_pair,
            "ok": worst_delta <= slo.max_quality_delta,
            "source": "gauge"})
    return objectives


def evaluate_slos(slos: Optional[Sequence[SLO]] = None, registry=None,
                  snapshot: Optional[Dict[str, dict]] = None,
                  emit: bool = False, fleet_view=None,
                  fleet_dir: Optional[str] = None) -> List[dict]:
    """Evaluate ``slos`` (default: :func:`active_slos`) against either a
    live ``registry`` (default: the process registry — sliding windows)
    or an artifact ``snapshot`` (cumulative). SLOs declaring
    ``scope: fleet`` instead read live fleet beacons — an explicit
    ``fleet_view`` (:class:`~flink_ml_tpu.observability.fleet.FleetView`),
    a ``fleet_dir``, or this process's own fleet-dir resolution — and
    their verdicts carry fleet bookkeeping: ``members`` /
    ``membersAlive`` / ``membersMissing`` plus a ``perMember`` quantile
    table, and FAIL whenever a member is dead even if the survivors'
    aggregate meets the objective. With ``emit``, every violated SLO
    lands an ``ml.slo`` trace event plus a ``slo_violations{slo=...}``
    counter in the ``ml.slo`` group of the process registry. Returns
    one verdict dict per SLO."""
    if slos is None:
        slos = active_slos()
    if snapshot is not None:
        source = _SnapshotSource(snapshot)
    else:
        source = _RegistrySource(metrics if registry is None
                                 else registry)
    fleet_source = None
    verdicts = []
    for slo in slos:
        src = source
        if slo.scope == "fleet":
            if fleet_source is None:
                fleet_source = _make_fleet_source(fleet_view, fleet_dir)
            src = fleet_source
        if slo.kind == "latency":
            objectives = _eval_latency(slo, src)
        elif slo.kind == "drift":
            objectives = _eval_drift(slo, src)
        elif slo.kind == "quality":
            objectives = _eval_quality(slo, src)
        else:
            objectives = _eval_error_rate(slo, src)
        ok = all(o["ok"] for o in objectives)
        verdict = {"slo": slo.name, "kind": slo.kind, "ok": ok,
                   "objectives": objectives}
        if slo.scope == "fleet":
            verdict["scope"] = "fleet"
            view = getattr(src, "view", None)
            if view is None:
                verdict.update(members=0, membersAlive=0,
                               membersMissing=[], fleet="missing")
            else:
                membership = view.membership()
                missing = view.members_missing()
                dead = [row["member"] for row in membership
                        if row["state"] == "dead"]
                verdict.update(
                    members=len(membership),
                    membersAlive=sum(1 for row in membership
                                     if row["state"] == "alive"),
                    membersMissing=missing)
                if slo.kind == "latency":
                    verdict["perMember"] = {
                        m: round(q, 3) for m, q in
                        view.per_member_quantile(
                            slo.group, slo.histogram, slo.labels,
                            slo.window_s, slo.quantile).items()}
                if dead:
                    # survivors meeting the bound is NOT a healthy
                    # fleet: a dead member fails the verdict outright
                    verdict["ok"] = ok = False
                    verdict["membersDead"] = dead
        verdicts.append(verdict)
        if emit and not ok:
            failing = [o["objective"] for o in objectives
                       if not o["ok"]]
            metrics.group(ML_GROUP, "slo").counter(
                "slo_violations", labels={"slo": slo.name})
            tracing.tracer.event(SLO_EVENT, slo=slo.name, ok=False,
                                 failing=",".join(failing))
            try:
                # flight recorder (observability/flightrecorder.py):
                # freeze the span ring + windowed metrics that explain
                # the violation before they rotate away — debounced,
                # capped, no-op without an armed trace dir, and
                # re-entrancy-latched (building a bundle evaluates
                # SLOs itself, non-emitting)
                from flink_ml_tpu.observability import flightrecorder

                flightrecorder.record_incident(
                    "slo", slo=slo.name, failing=",".join(failing))
            except Exception:  # noqa: BLE001 — recording must never
                # break the evaluation that detected the violation
                pass
    return verdicts


# -- rendering / CLI ----------------------------------------------------------

def render_verdicts(verdicts: List[dict]) -> str:
    bad = sum(1 for v in verdicts if not v["ok"])
    out = [f"{len(verdicts)} SLO(s), {bad} violated"]
    for v in verdicts:
        out.append("")
        out.append(f"SLO {v['slo']} ({v['kind']})  "
                   f"[{'ok' if v['ok'] else 'VIOLATED'}]")
        if v.get("scope") == "fleet":
            if v.get("fleet") == "missing":
                out.append("  fleet: no telemetry (no beacons resolve)")
            else:
                missing = v.get("membersMissing") or []
                dead = v.get("membersDead") or []
                line = (f"  fleet: {v.get('membersAlive', 0)}/"
                        f"{v.get('members', 0)} member(s) alive")
                if missing:
                    line += f", missing: {', '.join(missing)}"
                if dead:
                    line += f", DEAD: {', '.join(dead)}"
                out.append(line)
                per = v.get("perMember") or {}
                if per:
                    out.append("  per-member: " + "  ".join(
                        f"{m}={q:g}ms" for m, q in sorted(per.items())))
        for o in v["objectives"]:
            if o["objective"] == "drift-stat":
                val = "-" if o["value"] is None else f"{o['value']:g}"
                worst = f" worst {o['worst']}" if o.get("worst") else ""
                flag = "ok" if o["ok"] else "VIOLATED"
                out.append(
                    f"  {o['objective']:<17} "
                    f"{'(' + o['source'] + ')':<26} "
                    f"{o['stat']} {val} (<= {o['max_drift']:g}, "
                    f"{o['series']} series){worst}  [{flag}]")
                continue
            if o["objective"] == "quality-metric":
                val = "-" if o["value"] is None else f"{o['value']:g}"
                worst = f" worst {o['worst']}" if o.get("worst") else ""
                flag = "ok" if o["ok"] else "VIOLATED"
                out.append(
                    f"  {o['objective']:<17} "
                    f"{'(' + o['source'] + ')':<26} "
                    f"{o['metric']} {val} (>= {o['min_quality']:g}, "
                    f"{o['series']} series){worst}  [{flag}]")
                continue
            if o["objective"] == "quality-delta":
                val = "-" if o["value"] is None else f"{o['value']:g}"
                worst = f" worst {o['worst']}" if o.get("worst") else ""
                flag = "ok" if o["ok"] else "VIOLATED"
                out.append(
                    f"  {o['objective']:<17} "
                    f"{'(' + o['source'] + ')':<26} "
                    f"{o['metric']} under baseline by {val} "
                    f"(<= {o['max_quality_delta']:g}){worst}  "
                    f"[{flag}]")
                continue
            window = f"window {o['window_s']:g}s ({o['source']})"
            if o["objective"] == "latency-quantile":
                val = "-" if o["value_ms"] is None else \
                    f"{o['value_ms']:g} ms"
                detail = (f"p{o['quantile'] * 100:g} {val} "
                          f"(<= {o['threshold_ms']:g} ms, "
                          f"{o['samples']} sample(s))")
            elif o["objective"] == "error-ratio":
                detail = (f"ratio {o['value']:g} "
                          f"(<= {o['max_error_ratio']:g}, "
                          f"{o['errors']}/{o['requests']} request(s))")
            else:
                detail = (f"burn {o['burn_rate']:g}x "
                          f"(max {o['max_burn_rate']:g}x, bad "
                          f"{o['bad_fraction']:g} of budget "
                          f"{o['budget_fraction']:g})")
            flag = "ok" if o["ok"] else "VIOLATED"
            out.append(f"  {o['objective']:<17} {window:<26} {detail}"
                       f"  [{flag}]")
    return "\n".join(out)


def main(argv=None) -> int:
    """``flink-ml-tpu-trace slo <dir>`` — evaluate SLOs against the
    metrics artifacts of a trace dir (cumulative; the windowed view
    lives on the ``/slo`` endpoint of a running process). ``--check``
    exits 4 on any violated SLO, 2 on broken artifacts/spec."""
    import argparse
    import sys

    from flink_ml_tpu.observability.exporters import (
        pipe_guard,
        read_metrics,
        resolve_trace_dir,
    )

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace slo",
        description="SLO verdicts from a FLINK_ML_TPU_TRACE_DIR's "
                    "metrics artifacts (latency quantiles, error "
                    "ratios, burn rates).")
    parser.add_argument("trace_dir")
    parser.add_argument("--spec", metavar="FILE",
                        help="SLO spec file (JSON, or TOML on Python "
                             "3.11+); default: the built-in serving "
                             "SLOs")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 4 when any SLO is violated, 2 on "
                             "broken artifacts")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    parser.add_argument("--fleet", metavar="DIR", default=None,
                        help="fleet beacon dir for 'scope: fleet' "
                             "SLOs (default: TRACE_DIR's fleet/ "
                             "subdir)")
    args = parser.parse_args(argv)

    try:
        trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        snapshot = read_metrics(trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace slo: cannot read {args.trace_dir}: "
              f"{e}", file=sys.stderr)
        return EXIT_INVALID
    try:
        slos = load_specs(args.spec) if args.spec else default_slos()
    except (OSError, ValueError) as e:
        print(f"flink-ml-tpu-trace slo: {e}", file=sys.stderr)
        return EXIT_INVALID
    if not snapshot and not any(s.scope == "fleet" for s in slos):
        # a fleet-scope spec evaluates from beacons, not metrics
        # artifacts — only the artifact path needs them
        print(f"flink-ml-tpu-trace slo: no metrics-*.json artifacts in "
              f"{trace_dir}", file=sys.stderr)
        return EXIT_INVALID
    try:
        verdicts = evaluate_slos(
            slos, snapshot=snapshot,
            fleet_dir=args.fleet if args.fleet else trace_dir)
    except (OSError, ValueError) as e:
        print(f"flink-ml-tpu-trace slo: {e}", file=sys.stderr)
        return EXIT_INVALID

    with pipe_guard():
        if args.json:
            print(json.dumps({"trace_dir": trace_dir,
                              "source": "cumulative",
                              "verdicts": verdicts}, indent=2,
                             default=str))
        else:
            print(render_verdicts(verdicts))
    violated = [v["slo"] for v in verdicts if not v["ok"]]
    if args.check and violated:
        print(f"flink-ml-tpu-trace slo: {len(violated)} violated "
              f"SLO(s): {', '.join(violated)}", file=sys.stderr)
        return EXIT_VIOLATION
    return EXIT_OK


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
