"""Device profiling & efficiency plane: capture, attribution, efficiency.

Spans time the *host*; ``capture_cost`` records the XLA cost model's
FLOPs/bytes (compilestats.py). Neither measures how fast the device
actually ran. This module closes the loop in three layers:

- **Capture.** :func:`profile_window` wraps programmatic
  ``jax.profiler.start_trace/stop_trace`` behind the process-wide
  single-trace claim shared with ``common.metrics.profile`` — driver
  only, one window at a time, never raising into the workload. Three
  arming paths: the :data:`CAPTURE_ENV` env var profiles the next
  traced fit (:func:`maybe_profile_fit`, api/stage.py) or the next N
  batcher ticks (:func:`batch_tick`, serving/batcher.py); the live
  ``/profilez?ms=`` route (observability/server.py) calls
  :func:`capture_now`; and the flight recorder grabs a short bounded
  profile into the incident bundle (:func:`capture_incident_profile`).
  ``CAPTURE_ENV=0`` is the kill-switch disabling every path.

- **Attribution.** A stdlib-only parser for the profiler's
  Chrome-format ``*.trace.json.gz`` artifacts
  (:func:`parse_profile_dir`) folds device-lane events into per-op and
  per-jitted-fn device-time tables, joined to spans via the ``fn=``
  labels ``instrumented_jit`` already emits. The result lands as
  ``ml.deviceop selfMs{fn=,op=}`` histograms plus a ``profile.json``
  artifact beside spans/metrics. Profiles without device lanes (CPU CI)
  degrade gracefully to ``source: host-fallback`` — host ops are still
  attributed, but nothing downstream pretends they are device time.

- **Efficiency.** :func:`efficiency_report` joins measured device ms
  against ``capture_cost``'s ``programFlops``/``programBytes`` into
  achieved FLOP/s, achieved bytes/s, and roofline utilization per fn
  (``ml.efficiency`` gauges), classifying each fn compute- vs
  bandwidth-bound against :data:`PEAK_FLOPS_ENV`/:data:`PEAK_BW_ENV`.
  Surfaced as ``flink-ml-tpu-trace efficiency <dir> [--json|--check
  --min-util]`` with the diff/slo exit-code contract (0 ok — including
  an honest host-fallback, 2 broken artifacts, 4 below the floor) and
  as per-fn rows in ``mltrace diff``.

Boot-to-ready phase telemetry rides here too: :func:`boot_phase` wraps
the cold-start ladder (distributed init → mesh build → warmup compile →
registry adopt → gate open) in ``boot.*`` spans + ``ml.boot
phaseMs{phase=}`` histograms, and :func:`mark_ready` latches
``bootToReadyMs`` — carried in fleet beacons and ``mltrace fleet``.
"""

from __future__ import annotations

import argparse
import contextlib
import gzip
import json
import os
import re
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

from flink_ml_tpu.common import metrics as metrics_mod
from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import tracing
from flink_ml_tpu.observability.compilestats import (
    COMPILE_BUCKETS,
    DEVICE_GROUP,
    _backend_ready,
)

#: registry subgroup names: ml.deviceop / ml.efficiency / ml.boot
DEVICEOP_GROUP = "deviceop"
EFFICIENCY_GROUP = "efficiency"
BOOT_GROUP = "boot"

#: env var: "1" arms the next traced fit / next N batcher ticks for
#: capture; "0" is the kill-switch disabling EVERY capture path
#: (/profilez and incident capture included); unset leaves on-demand
#: and incident capture available but arms nothing
CAPTURE_ENV = "FLINK_ML_TPU_PROFILE_CAPTURE"
#: env var: batcher ticks one armed capture spans (default 3)
TICKS_ENV = "FLINK_ML_TPU_PROFILE_TICKS"
DEFAULT_TICKS = 3
#: env var: incident-bundle profile length in ms (default 200; 0 disables)
INCIDENT_MS_ENV = "FLINK_ML_TPU_INCIDENT_PROFILE_MS"
DEFAULT_INCIDENT_MS = 200
#: env var: upper bound the /profilez route clamps requests to
PROFILEZ_MAX_MS_ENV = "FLINK_ML_TPU_PROFILEZ_MAX_MS"
DEFAULT_PROFILEZ_MAX_MS = 2000
#: env vars: hardware peaks the roofline measures against — defaults
#: are one TPU v5e chip (197 TFLOP/s bf16, 819 GB/s HBM)
PEAK_FLOPS_ENV = "FLINK_ML_TPU_PEAK_FLOPS"
DEFAULT_PEAK_FLOPS = 1.97e14
PEAK_BW_ENV = "FLINK_ML_TPU_PEAK_BW"
DEFAULT_PEAK_BW = 8.19e11

#: the attribution artifact written beside spans-*/metrics-* files
PROFILE_ARTIFACT = "profile.json"

#: exit codes — the diff/slo contract (docs/observability.md)
EXIT_OK = 0
EXIT_INVALID = 2
EXIT_BELOW_FLOOR = 4

# module state: arming latches, live tick capture, boot latches — all
# guarded by _lock (short holds only; jax/profiler calls stay outside)
_lock = make_lock("observability.profiling")
_owner_pid = os.getpid()
_fit_consumed = False
_tick_consumed = False
_tick_handle: Optional["CaptureHandle"] = None
_tick_remaining = 0
_boot_t0: Optional[float] = None
_boot_ready_ms: Optional[float] = None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def capture_disabled() -> bool:
    """The kill-switch: ``CAPTURE_ENV=0`` turns every capture path off."""
    return os.environ.get(CAPTURE_ENV, "") == "0"


def _capture_armed() -> bool:
    return os.environ.get(CAPTURE_ENV, "") == "1"


def peak_flops() -> float:
    return _env_float(PEAK_FLOPS_ENV, DEFAULT_PEAK_FLOPS)


def peak_bw() -> float:
    return _env_float(PEAK_BW_ENV, DEFAULT_PEAK_BW)


# -- capture ------------------------------------------------------------------
def _profiler_start(log_dir: str) -> None:
    """Seam over jax.profiler.start_trace — tests monkeypatch this to a
    fake that drops a fixture trace file, so capture-path coverage does
    not depend on the CI host's profiler producing device lanes."""
    import jax

    jax.profiler.start_trace(log_dir)


def _profiler_stop() -> None:
    """Seam over jax.profiler.stop_trace (see :func:`_profiler_start`)."""
    import jax

    jax.profiler.stop_trace()


class CaptureHandle:
    """One in-flight capture: where the raw trace lands (``dir``), where
    ``profile.json`` is published (``artifact_dir``), and — after the
    window closes — the parsed attribution ``report`` (None when the
    capture produced nothing parseable)."""

    def __init__(self, label: str, dir: str, artifact_dir: str):
        self.label = label
        self.dir = dir
        self.artifact_dir = artifact_dir
        self.report: Optional[dict] = None


def _begin_capture(label: str, out_dir: Optional[str] = None,
                   artifact_dir: Optional[str] = None
                   ) -> Optional[CaptureHandle]:
    """Claim the profiler and start a trace. Returns None (refusing,
    never raising) when capture is killed, this is not the driver
    process, another trace is active, or the profiler fails to start."""
    if capture_disabled():
        return None
    if os.getpid() != _owner_pid:
        return None  # forked children never profile (reseed_child)
    safe = re.sub(r"[^A-Za-z0-9_.-]+", "-", label) or "capture"
    if out_dir is None:
        trace_dir = tracing.tracer.trace_dir
        if trace_dir:
            from flink_ml_tpu.observability.exporters import artifact_suffix

            out_dir = os.path.join(
                trace_dir, f"profile-{safe}-{artifact_suffix()}")
            artifact_dir = artifact_dir or trace_dir
        else:
            out_dir = tempfile.mkdtemp(prefix=f"flink-ml-tpu-{safe}-")
    artifact_dir = artifact_dir or out_dir
    if not metrics_mod.claim_profiler():
        return None  # one trace at a time — shared with metrics.profile()
    try:
        os.makedirs(out_dir, exist_ok=True)
        _profiler_start(out_dir)
    except Exception:  # noqa: BLE001 — capture must not sink the workload
        metrics_mod.release_profiler()
        return None
    return CaptureHandle(label, out_dir, artifact_dir)


def _finish_capture(handle: CaptureHandle) -> Optional[dict]:
    """Stop the trace, release the claim, parse + publish attribution.
    Best-effort end to end: a torn capture leaves no artifact and no
    exception in the caller."""
    try:
        _profiler_stop()
    except Exception:  # noqa: BLE001 — a failed stop must still release
        metrics_mod.release_profiler()
        return None
    metrics_mod.release_profiler()
    try:
        report = parse_profile_dir(handle.dir)
    except ProfileParseError:
        return None
    report["label"] = handle.label
    try:
        write_profile_artifact(handle.artifact_dir, report)
    except OSError:
        pass  # the in-registry histograms below are still worth having
    _record_report(report)
    handle.report = report
    return report


def _record_report(report: dict) -> None:
    """Fold a parsed report into the live registry: ``ml.deviceop``
    self-time histograms always; ``ml.efficiency`` gauges only when the
    report carries real device lanes (host-fallback must not claim
    utilization)."""
    grp = metrics.group(ML_GROUP, DEVICEOP_GROUP)
    for row in report.get("ops", []):
        grp.histogram("selfMs", buckets=COMPILE_BUCKETS,
                      labels={"fn": row["fn"], "op": row["op"]}
                      ).observe(row["selfMs"])
    if report.get("source") != "device":
        return
    try:
        eff = efficiency_report(None, profile=report,
                                snapshot=metrics.snapshot())
    except ProfileParseError:
        return
    grp = metrics.group(ML_GROUP, EFFICIENCY_GROUP)
    for row in eff["fns"]:
        labels = {"fn": row["fn"]}
        for field in ("achievedFlops", "achievedBw", "utilization"):
            if row.get(field) is not None:
                grp.gauge(field, row[field], labels=labels)


@contextlib.contextmanager
def profile_window(label: str, out_dir: Optional[str] = None,
                   artifact_dir: Optional[str] = None):
    """Capture a device profile around a region. Yields a
    :class:`CaptureHandle` (its ``report`` is filled in after the block
    exits) or None when capture was refused — killed, non-driver
    process, or another trace already active. Never raises into the
    workload; the region body runs either way."""
    handle = _begin_capture(label, out_dir=out_dir, artifact_dir=artifact_dir)
    try:
        yield handle
    finally:
        if handle is not None:
            _finish_capture(handle)


def capture_now(ms: int) -> Optional[dict]:
    """The ``/profilez?ms=`` body: a bounded wall-clock capture window.
    Returns ``{"label", "dir", "ms", "report"}`` on success (``report``
    None when the capture parsed to nothing) or None when refused —
    the route answers 409 then."""
    if capture_disabled():
        return None
    max_ms = max(1, _env_int(PROFILEZ_MAX_MS_ENV, DEFAULT_PROFILEZ_MAX_MS))
    ms = max(1, min(int(ms), max_ms))
    with profile_window(f"profilez-{ms}ms") as handle:
        if handle is None:
            return None
        time.sleep(ms / 1000.0)
    return {"label": handle.label, "dir": handle.dir, "ms": ms,
            "report": handle.report}


def capture_incident_profile(bundle_dir: str) -> bool:
    """Flight-recorder hook: grab a short bounded device profile into an
    incident bundle (raw trace under ``<bundle>/profile/``, attribution
    at ``<bundle>/profile.json``). Refuses — returning False, never
    raising or blocking on backend init — when capture is killed,
    :data:`INCIDENT_MS_ENV` is 0, or no jax backend is live yet."""
    if capture_disabled():
        return False
    ms = _env_int(INCIDENT_MS_ENV, DEFAULT_INCIDENT_MS)
    if ms <= 0:
        return False
    if not _backend_ready():
        return False  # never initialize a backend from telemetry
    ms = min(ms, DEFAULT_PROFILEZ_MAX_MS)
    out = os.path.join(bundle_dir, "profile")
    with profile_window("incident", out_dir=out,
                        artifact_dir=bundle_dir) as handle:
        if handle is None:
            return False
        time.sleep(ms / 1000.0)
    return True


def _ticks() -> int:
    return max(1, _env_int(TICKS_ENV, DEFAULT_TICKS))


def batch_tick() -> None:
    """Per-dispatch hook (serving/batcher.py): when :data:`CAPTURE_ENV`
    armed this process, start a capture at the next tick and stop it
    after N ticks — once per process (reset with :func:`reset`). The
    unarmed steady state costs one env read."""
    global _tick_handle, _tick_remaining, _tick_consumed
    if _tick_handle is None and not _capture_armed():
        return
    handle = None
    start = False
    with _lock:
        if _tick_handle is not None:
            _tick_remaining -= 1
            if _tick_remaining <= 0:
                handle, _tick_handle = _tick_handle, None
        elif _capture_armed() and not _tick_consumed:
            _tick_consumed = True
            start = True
    if handle is not None:
        _finish_capture(handle)
        return
    if start:
        n = _ticks()
        new = _begin_capture(f"batcher-{n}ticks")
        if new is not None:
            with _lock:
                _tick_handle = new
                _tick_remaining = n


@contextlib.contextmanager
def maybe_profile_fit(region: str):
    """Arm-next-fit seam (api/stage.py ``_profiled``): with
    :data:`CAPTURE_ENV` armed, wrap the next traced fit/transform in a
    :func:`profile_window` — one-shot per process."""
    global _fit_consumed
    fire = False
    if _capture_armed():
        with _lock:
            if not _fit_consumed:
                _fit_consumed = True
                fire = True
    if not fire:
        yield None
        return
    with profile_window(f"fit-{region}") as handle:
        yield handle


def reset() -> None:
    """Re-arm the one-shot fit/tick latches (tests)."""
    global _fit_consumed, _tick_consumed, _tick_handle, _tick_remaining
    with _lock:
        _fit_consumed = False
        _tick_consumed = False
        _tick_handle = None
        _tick_remaining = 0


def reseed_child() -> None:
    """Fork boundary (common/hostpool.py ``_child_main``): children
    never profile — the driver owns the single jax.profiler slot — and
    the inherited lock may have been held at fork time, so replace it
    rather than acquire it (the common/metrics reseed pattern)."""
    global _lock, _owner_pid, _tick_handle, _tick_remaining
    _lock = make_lock("observability.profiling")
    _owner_pid = -1
    _tick_handle = None
    _tick_remaining = 0


# -- attribution --------------------------------------------------------------
class ProfileParseError(ValueError):
    """A profile artifact that cannot be read/parsed — the exit-2 class."""


_JIT_NAME = re.compile(r"^jit_([A-Za-z0-9_]+)")


def find_trace_file(profile_dir: str) -> Optional[str]:
    """The newest ``*.trace.json.gz`` under ``profile_dir`` (the
    profiler nests them under ``plugins/profile/<run>/``)."""
    newest, newest_m = None, -1.0
    for root, _dirs, files in os.walk(profile_dir):
        for name in files:
            if not name.endswith(".trace.json.gz"):
                continue
            path = os.path.join(root, name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            if mtime >= newest_m:
                newest, newest_m = path, mtime
    return newest


def _fn_from_args(args: dict) -> str:
    """The owning jitted fn of an op event, from the hierarchical names
    XLA attaches (``jit_<fn>/...``); 'unknown' when unattributed."""
    for key in ("name", "long_name", "tf_op"):
        val = args.get(key)
        if isinstance(val, str):
            m = _JIT_NAME.match(val)
            if m:
                return m.group(1)
    return "unknown"


def parse_trace_file(path: str) -> dict:
    """Fold one Chrome-format ``*.trace.json.gz`` into per-op and
    per-fn device-time tables (see module doc). Device lanes are the
    trace processes whose ``process_name`` metadata names the TPU; with
    none present (CPU CI) every complete event is folded instead and
    the report says so (``source: host-fallback``)."""
    try:
        with gzip.open(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8"))
    except (OSError, EOFError, ValueError) as exc:
        raise ProfileParseError(f"unreadable profile trace {path}: {exc}")
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("traceEvents"), list):
        raise ProfileParseError(
            f"{path}: not a Chrome-format trace (no traceEvents list)")
    events = doc["traceEvents"]
    device_pids = set()
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "M" \
                or ev.get("name") != "process_name":
            continue
        args = ev.get("args")
        if isinstance(args, dict) and "TPU" in str(args.get("name", "")):
            device_pids.add(ev.get("pid"))
    source = "device" if device_pids else "host-fallback"
    fn_ms: Dict[str, float] = {}
    fn_count: Dict[str, int] = {}
    op_ms: Dict[Tuple[str, str], float] = {}
    op_count: Dict[Tuple[str, str], int] = {}
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        try:
            dur_ms = float(ev.get("dur", 0.0)) / 1000.0  # trace dur is µs
        except (TypeError, ValueError):
            continue
        if dur_ms <= 0:
            continue
        name = str(ev.get("name", ""))
        args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
        m = _JIT_NAME.match(name)
        if m:
            # a module-level event: the whole jitted program's lane slice
            fn = m.group(1)
            fn_ms[fn] = fn_ms.get(fn, 0.0) + dur_ms
            fn_count[fn] = fn_count.get(fn, 0) + 1
        else:
            fn = _fn_from_args(args)
            key = (name, fn)
            op_ms[key] = op_ms.get(key, 0.0) + dur_ms
            op_count[key] = op_count.get(key, 0) + 1
    # fns with no module-level event still get a device-time row from
    # the sum of their attributed ops (both shapes appear in the wild)
    fns = {fn: {"fn": fn, "deviceMs": round(ms, 6),
                "count": fn_count[fn]} for fn, ms in fn_ms.items()}
    for (op, fn), ms in op_ms.items():
        if fn == "unknown" or fn in fn_ms:
            continue
        row = fns.setdefault(fn, {"fn": fn, "deviceMs": 0.0, "count": 0})
        row["deviceMs"] = round(row["deviceMs"] + ms, 6)
        row["count"] += op_count[(op, fn)]
    ops = [{"op": op, "fn": fn, "selfMs": round(ms, 6),
            "count": op_count[(op, fn)]}
           for (op, fn), ms in op_ms.items()]
    ops.sort(key=lambda r: (-r["selfMs"], r["op"], r["fn"]))
    fn_rows = sorted(fns.values(),
                     key=lambda r: (-r["deviceMs"], r["fn"]))
    total = sum(r["deviceMs"] for r in fn_rows) if fn_rows else \
        sum(r["selfMs"] for r in ops)
    return {"source": source, "totalMs": round(total, 6),
            "ops": ops, "fns": fn_rows}


def parse_profile_dir(profile_dir: str) -> dict:
    """Parse the newest trace file under ``profile_dir``; raises
    :class:`ProfileParseError` when there is none or it is torn."""
    trace_file = find_trace_file(profile_dir)
    if trace_file is None:
        raise ProfileParseError(
            f"no *.trace.json.gz under {profile_dir}")
    report = parse_trace_file(trace_file)
    report["traceFile"] = os.path.relpath(trace_file, profile_dir)
    return report


def write_profile_artifact(trace_dir: str, report: dict) -> str:
    """Publish ``profile.json`` atomically beside the trace artifacts."""
    path = os.path.join(trace_dir, PROFILE_ARTIFACT)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def read_profile(trace_dir: str) -> dict:
    """Load ``profile.json`` from a trace dir; raises
    :class:`ProfileParseError` (the exit-2 class) when missing/torn."""
    path = os.path.join(trace_dir, PROFILE_ARTIFACT)
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        raise ProfileParseError(f"no {PROFILE_ARTIFACT} in {trace_dir}")
    except (OSError, ValueError) as exc:
        raise ProfileParseError(f"unreadable {path}: {exc}")
    if not isinstance(doc, dict) or not isinstance(doc.get("fns"), list) \
            or "source" not in doc:
        raise ProfileParseError(f"{path}: not a profile attribution artifact")
    return doc


# -- efficiency ---------------------------------------------------------------
_COST_KEY = re.compile(
    r'^(programFlops|programBytes)\{fn="((?:[^"\\]|\\.)*)"\}$')


def _device_costs(snapshot: Optional[dict]) -> Dict[str, Dict[str, float]]:
    """``fn → {programFlops, programBytes}`` from an ``ml.device``
    gauge snapshot (compilestats.capture_cost's series)."""
    gauges = ((snapshot or {}).get(f"{ML_GROUP}.{DEVICE_GROUP}") or {}
              ).get("gauges", {})
    out: Dict[str, Dict[str, float]] = {}
    for key, val in gauges.items():
        m = _COST_KEY.match(key)
        if m is None:
            continue
        try:
            out.setdefault(m.group(2), {})[m.group(1)] = float(val)
        except (TypeError, ValueError):
            continue
    return out


def efficiency_report(trace_dir: Optional[str],
                      profile: Optional[dict] = None,
                      snapshot: Optional[dict] = None,
                      pf: Optional[float] = None,
                      pb: Optional[float] = None) -> dict:
    """Join a profile's measured per-fn device ms with the XLA cost
    model's FLOPs/bytes into achieved rates + roofline utilization.
    Utilization measures against the binding roof — the peak FLOP/s for
    compute-bound fns, the bandwidth roof scaled by arithmetic
    intensity for bandwidth-bound ones. On ``host-fallback`` profiles
    every achieved/utilization field is None: host ms against device
    peaks would be a lie. Raises :class:`ProfileParseError` when the
    artifacts are missing/torn."""
    if profile is None:
        profile = read_profile(trace_dir)
    if snapshot is None:
        from flink_ml_tpu.observability.exporters import read_metrics

        snapshot = read_metrics(trace_dir)
    pf = pf if pf else peak_flops()
    pb = pb if pb else peak_bw()
    costs = _device_costs(snapshot)
    measured = profile.get("source") == "device"
    rows: List[dict] = []
    for fn_row in profile.get("fns", []):
        fn = fn_row["fn"]
        ms = float(fn_row.get("deviceMs", 0.0))
        cost = costs.get(fn, {})
        flops = cost.get("programFlops")
        nbytes = cost.get("programBytes")
        row = {"fn": fn, "deviceMs": ms, "programFlops": flops,
               "programBytes": nbytes, "achievedFlops": None,
               "achievedBw": None, "utilization": None, "bound": None}
        if measured and ms > 0:
            secs = ms / 1000.0
            if flops:
                row["achievedFlops"] = flops / secs
            if nbytes:
                row["achievedBw"] = nbytes / secs
            if flops and nbytes:
                intensity = flops / nbytes
                if intensity >= pf / pb:
                    row["bound"] = "compute"
                    row["utilization"] = (flops / secs) / pf
                else:
                    row["bound"] = "bandwidth"
                    row["utilization"] = (flops / secs) / (pb * intensity)
            elif flops:
                row["bound"] = "compute"
                row["utilization"] = (flops / secs) / pf
        rows.append(row)
    return {"source": profile.get("source"), "peakFlops": pf, "peakBw": pb,
            "ridge": pf / pb, "fns": rows}


def _fmt(val, pattern: str = "{:.3g}") -> str:
    return "—" if val is None else pattern.format(val)


def render_efficiency(report: dict) -> str:
    """The human rendering: one roofline header + one row per fn."""
    lines = [
        "source: {}  peaks {:.3g} FLOP/s / {:.3g} B/s  "
        "ridge {:.4g} FLOP/B".format(report["source"], report["peakFlops"],
                                     report["peakBw"], report["ridge"]),
        "{:<24} {:>10} {:>14} {:>12} {:>8}  {}".format(
            "fn", "deviceMs", "achievedFlops", "achievedBw", "util",
            "bound"),
    ]
    for row in report["fns"]:
        util = row["utilization"]
        lines.append("{:<24} {:>10.3f} {:>14} {:>12} {:>8}  {}".format(
            row["fn"], row["deviceMs"], _fmt(row["achievedFlops"]),
            _fmt(row["achievedBw"]),
            "—" if util is None else f"{util * 100.0:.1f}%",
            row["bound"] or "—"))
    if not report["fns"]:
        lines.append("(no per-fn device time attributed)")
    if report["source"] != "device":
        lines.append("host-fallback profile: no device lanes — achieved "
                     "rates and utilization are not claimed")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """``flink-ml-tpu-trace efficiency <dir> [--json|--check
    --min-util F]`` — exit 0 ok (including honest host-fallback), 2 on
    missing/torn artifacts, 4 when any measured fn's utilization sits
    below the floor."""
    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace efficiency",
        description="Measured device time vs XLA cost model: achieved "
                    "FLOPs/bandwidth and roofline utilization per fn.")
    parser.add_argument("dir", help="trace dir holding profile.json "
                                    "and metrics-*.json")
    parser.add_argument("--latest", action="store_true",
                        help="treat DIR as a root; use its newest trace dir")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable report on stdout")
    parser.add_argument("--check", action="store_true",
                        help="gate: exit 4 when a measured fn's "
                             "utilization is below --min-util")
    parser.add_argument("--min-util", type=float, default=0.0,
                        metavar="F",
                        help="utilization floor as a fraction (0.4 = 40%%)")
    parser.add_argument("--peak-flops", type=float, default=None,
                        help=f"override {PEAK_FLOPS_ENV}")
    parser.add_argument("--peak-bw", type=float, default=None,
                        help=f"override {PEAK_BW_ENV}")
    args = parser.parse_args(argv)

    from flink_ml_tpu.observability.exporters import resolve_trace_dir

    try:
        trace_dir = resolve_trace_dir(args.dir, latest=args.latest)
        report = efficiency_report(trace_dir, pf=args.peak_flops,
                                   pb=args.peak_bw)
    except (ProfileParseError, OSError) as exc:
        print(f"efficiency: {exc}", file=sys.stderr)
        return EXIT_INVALID
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_efficiency(report))
    if args.check:
        low = [r for r in report["fns"]
               if r["utilization"] is not None
               and r["utilization"] < args.min_util]
        if low:
            for row in low:
                print("efficiency: {} utilization {:.1f}% below floor "
                      "{:.1f}%".format(row["fn"],
                                       row["utilization"] * 100.0,
                                       args.min_util * 100.0),
                      file=sys.stderr)
            return EXIT_BELOW_FLOOR
    return EXIT_OK


# -- boot-to-ready phase telemetry --------------------------------------------
#: the cold-start ladder, in boot order (docs/observability.md)
BOOT_PHASES = ("distributed-init", "mesh-build", "warmup-compile",
               "registry-adopt", "gate-open")


@contextlib.contextmanager
def boot_phase(phase: str):
    """Time one boot phase: a ``boot.<phase>`` span plus an ``ml.boot
    phaseMs{phase=}`` observation. The first call starts the
    boot-to-ready clock; after :func:`mark_ready` latches, a no-op —
    steady-state re-adopts/re-warms must not pollute boot telemetry."""
    global _boot_t0
    with _lock:
        live = _boot_ready_ms is None
        if live and _boot_t0 is None:
            _boot_t0 = time.monotonic()
    if not live:
        yield
        return
    span = tracing.tracer.span(f"boot.{phase}", phase=phase) \
        if tracing.tracer.active else contextlib.nullcontext()
    start = time.perf_counter()
    with span:
        try:
            yield
        finally:
            elapsed_ms = (time.perf_counter() - start) * 1000.0
            metrics.group(ML_GROUP, BOOT_GROUP).histogram(
                "phaseMs", buckets=COMPILE_BUCKETS,
                labels={"phase": phase}).observe(elapsed_ms)


def mark_ready() -> None:
    """Latch boot completion (first call wins): the gate is open and the
    process serves/fits. Records the ``bootToReadyMs`` gauge fleet
    beacons carry and a ``boot.ready`` event."""
    global _boot_ready_ms
    with _lock:
        if _boot_ready_ms is not None:
            return
        _boot_ready_ms = 0.0 if _boot_t0 is None else \
            (time.monotonic() - _boot_t0) * 1000.0
        ready_ms = _boot_ready_ms
    metrics.group(ML_GROUP, BOOT_GROUP).gauge("bootToReadyMs", ready_ms)
    tracing.event("boot.ready", bootToReadyMs=round(ready_ms, 3))


def boot_to_ready_ms() -> Optional[float]:
    """The latched boot-to-ready duration; None before :func:`mark_ready`
    (the fleet beacon's per-member field)."""
    with _lock:
        return _boot_ready_ms


def reset_boot() -> None:
    """Clear the boot latches (tests)."""
    global _boot_t0, _boot_ready_ms
    with _lock:
        _boot_t0 = None
        _boot_ready_ms = None


# -- bench provenance ---------------------------------------------------------
def provenance(trace_dir: Optional[str] = None) -> dict:
    """Bench-row provenance: the hottest measured fn's utilization and
    achieved FLOP/s from the trace dir's profile artifact. Never
    raises; every field None when there is no artifact or the profile
    is host-fallback (the honest CPU answer)."""
    out = {"profileSource": None, "utilization": None,
           "achievedFlops": None}
    try:
        d = trace_dir or tracing.tracer.trace_dir
        if not d:
            return out
        report = efficiency_report(d)
        out["profileSource"] = report["source"]
        rows = [r for r in report["fns"]
                if r.get("utilization") is not None]
        if rows:
            top = max(rows, key=lambda r: r["deviceMs"])
            out["utilization"] = top["utilization"]
            out["achievedFlops"] = top["achievedFlops"]
    except Exception:  # noqa: BLE001 — provenance must never sink a bench row
        pass
    return out


if __name__ == "__main__":
    sys.exit(main())
