"""``flink-ml-tpu-trace locks``: the lock watchdog's artifact view.

A lockcheck-armed run (``FLINK_ML_TPU_LOCKCHECK=1``, common/locks.py)
dumps one ``locks-<suffix>.json`` per process beside its metrics
snapshots — the acquisition-order graph, detected cycles, per-lock
hold-time stats and long-hold records. This subcommand merges every
dump in a trace dir into one report:

- per-lock table: acquires, mean/max hold, long-hold count;
- the acquisition-order edge list (outer → inner, with counts);
- cycles: those each process detected live, plus any cycle that only
  appears in the MERGED graph — two processes each acquiring in a
  consistent-but-opposite order is the same latent deadlock, just not
  yet co-resident in one process;
- the ``ml.lock`` event timeline from the spans (cycle / long-hold
  instants, in order).

Exit codes follow the established contract: with ``--check``, 4 when
any cycle or long-hold was recorded (a potential deadlock or a stalled
hot path is a gate failure), 2 when the dir holds no lock telemetry at
all (the armed smoke did not actually run armed — broken artifacts),
0 clean. Without ``--check`` it always renders and exits 0/2.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

from flink_ml_tpu.common.locks import LOCKS_GLOB
from flink_ml_tpu.observability.exporters import (
    pipe_guard,
    read_spans,
    resolve_trace_dir,
)


def read_lock_dumps(trace_dir: str) -> List[dict]:
    """Every parseable ``locks-*.json`` in ``trace_dir`` (torn files
    are skipped — an armed run that crashed mid-dump must still
    report)."""
    out = []
    for path in sorted(glob.glob(os.path.join(trace_dir, LOCKS_GLOB))):
        try:
            with open(path, "r", encoding="utf-8") as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return out


def merge_dumps(dumps: List[dict]) -> dict:
    """One cross-process view: edges/acquires/long-holds sum, hold
    stats fold, cycles union (deduped by their edge set)."""
    edges: Dict[Tuple[str, str], int] = {}
    acquires: Dict[str, int] = {}
    holds: Dict[str, dict] = {}
    cycles: List[List[str]] = []
    cycle_keys = set()
    long_holds: List[dict] = []
    long_hold_total = 0
    threshold = None
    for dump in dumps:
        threshold = dump.get("threshold_ms", threshold)
        for a, b, n in dump.get("edges", ()):
            edges[(a, b)] = edges.get((a, b), 0) + int(n)
        for name, n in dump.get("acquires", {}).items():
            acquires[name] = acquires.get(name, 0) + int(n)
        for name, rec in dump.get("holds", {}).items():
            cur = holds.get(name)
            if cur is None:
                holds[name] = {"sum": float(rec.get("sum", 0.0)),
                               "count": int(rec.get("count", 0)),
                               "max_ms": float(rec.get("max_ms", 0.0))}
            else:
                cur["sum"] += float(rec.get("sum", 0.0))
                cur["count"] += int(rec.get("count", 0))
                cur["max_ms"] = max(cur["max_ms"],
                                    float(rec.get("max_ms", 0.0)))
        for path in dump.get("cycles", ()):
            sig = frozenset(zip(path, path[1:]))
            if sig not in cycle_keys:
                cycle_keys.add(sig)
                cycles.append(list(path))
        long_holds.extend(dump.get("long_holds", ()))
        long_hold_total += int(dump.get("long_hold_total", 0))
    # cycles visible only in the MERGED graph (cross-process hazard)
    for cycle in _graph_cycles(edges):
        sig = frozenset(zip(cycle, cycle[1:]))
        if sig not in cycle_keys:
            cycle_keys.add(sig)
            cycles.append(cycle)
    return {"threshold_ms": threshold, "edges": edges,
            "acquires": acquires, "holds": holds, "cycles": cycles,
            "long_holds": long_holds,
            "long_hold_total": long_hold_total}


def _graph_cycles(edges: Dict[Tuple[str, str], int]) -> List[List[str]]:
    """Simple cycles in the merged order graph (each reported once,
    from its lexicographically-smallest node)."""
    succ: Dict[str, List[str]] = {}
    for (a, b) in edges:
        succ.setdefault(a, []).append(b)
    out: List[List[str]] = []
    seen_sigs = set()
    for start in sorted(succ):
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(succ.get(node, ())):
                if nxt == start:
                    cycle = path + [start]
                    if min(cycle) != start:
                        continue  # reported from its smallest node
                    sig = frozenset(zip(cycle, cycle[1:]))
                    if sig not in seen_sigs:
                        seen_sigs.add(sig)
                        out.append(cycle)
                elif nxt not in path and nxt > start:
                    stack.append((nxt, path + [nxt]))
    return out


def lock_events(spans: List[dict]) -> List[dict]:
    """``ml.lock`` / ``ml.thread`` instants from the span records, in
    time order — the when/where of each cycle, long hold and thread
    crash."""
    out = []
    for sp in spans:
        for ev in sp.get("events", ()):
            if ev.get("name") in ("ml.lock", "ml.thread"):
                out.append({"ts_us": ev.get("ts_us", 0),
                            "name": ev["name"],
                            "attrs": ev.get("attrs", {})})
    out.sort(key=lambda r: r["ts_us"])
    return out


def report(trace_dir: str) -> Optional[dict]:
    """The merged lock report for ``trace_dir``; None when the dir holds
    no lock telemetry (no dumps and no ml.lock events)."""
    dumps = read_lock_dumps(trace_dir)
    try:
        spans = read_spans(trace_dir)
    except OSError:
        spans = []
    events = lock_events(spans)
    if not dumps and not events:
        return None
    merged = merge_dumps(dumps)
    return {
        "processes": len(dumps),
        "threshold_ms": merged["threshold_ms"],
        "locks": {
            name: {
                "acquires": merged["acquires"].get(name, 0),
                "mean_hold_ms": round(rec["sum"] / rec["count"], 3)
                if rec["count"] else 0.0,
                "max_hold_ms": round(rec["max_ms"], 3),
            }
            for name, rec in sorted(merged["holds"].items())
        },
        "edges": [{"outer": a, "inner": b, "count": n}
                  for (a, b), n in sorted(merged["edges"].items())],
        "cycles": merged["cycles"],
        "long_holds": merged["long_holds"],
        "long_hold_total": merged["long_hold_total"],
        "events": events,
    }


def render(rep: dict) -> str:
    out = [f"lock watchdog: {rep['processes']} process dump(s), "
           f"long-hold threshold "
           f"{rep['threshold_ms'] if rep['threshold_ms'] is not None else '?'} ms"]
    if rep["locks"]:
        out.append("")
        out.append(f"  {'lock':<36} {'acquires':>9} {'mean ms':>9} "
                   f"{'max ms':>9}")
        for name, row in rep["locks"].items():
            out.append(f"  {name:<36} {row['acquires']:>9} "
                       f"{row['mean_hold_ms']:>9.3f} "
                       f"{row['max_hold_ms']:>9.3f}")
    if rep["edges"]:
        out.append("")
        out.append("acquisition order (outer -> inner):")
        for e in rep["edges"]:
            out.append(f"  {e['outer']} -> {e['inner']}  x{e['count']}")
    if rep["cycles"]:
        out.append("")
        out.append("CYCLES (potential deadlocks):")
        for cycle in rep["cycles"]:
            out.append("  " + " -> ".join(cycle))
    if rep["long_hold_total"]:
        out.append("")
        out.append(f"long holds: {rep['long_hold_total']} over threshold")
        for rec in rep["long_holds"][:10]:
            out.append(f"  {rec.get('lock')}: {rec.get('hold_ms')} ms")
    if rep["events"]:
        out.append("")
        out.append("event timeline:")
        t0 = rep["events"][0]["ts_us"]
        for ev in rep["events"]:
            attrs = " ".join(f"{k}={v}"
                             for k, v in ev.get("attrs", {}).items())
            out.append(f"  +{(ev['ts_us'] - t0) / 1000.0:>10.3f} ms  "
                       f"{ev['name']}  {attrs}".rstrip())
    if not rep["cycles"] and not rep["long_hold_total"]:
        out.append("")
        out.append("no cycles, no long holds — lock discipline held")
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace locks",
        description="Merged lock-watchdog view of a trace dir "
                    "(FLINK_ML_TPU_LOCKCHECK-armed run).")
    parser.add_argument("trace_dir")
    parser.add_argument("--json", action="store_true")
    parser.add_argument("--check", action="store_true",
                        help="exit 4 on any recorded cycle or long "
                             "hold, 2 when the dir has no lock "
                             "telemetry at all")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    args = parser.parse_args(argv)

    try:
        trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
    except OSError as e:
        print(f"locks: {e}", file=sys.stderr)
        return 2
    rep = report(trace_dir)
    if rep is None:
        print(f"locks: no lock telemetry in {trace_dir} — was the run "
              f"armed with FLINK_ML_TPU_LOCKCHECK=1?", file=sys.stderr)
        return 2
    with pipe_guard():
        if args.json:
            print(json.dumps(rep, indent=2, default=str))
        else:
            print(render(rep))
    if args.check and (rep["cycles"] or rep["long_hold_total"]):
        print(f"locks: {len(rep['cycles'])} cycle(s), "
              f"{rep['long_hold_total']} long hold(s) — failing the "
              f"gate", file=sys.stderr)
        return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())
