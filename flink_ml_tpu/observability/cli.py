"""``flink-ml-tpu-trace``: inspect a trace directory from artifacts alone.

A failed or slow run leaves ``spans-*.jsonl`` + ``metrics-*.json`` under
its ``FLINK_ML_TPU_TRACE_DIR``; this CLI answers "where did the time go,
and did it recompile/retry/checkpoint more than it should?" without
rerunning anything:

    flink-ml-tpu-trace TRACE_DIR                 # summary (text)
    flink-ml-tpu-trace summary TRACE_DIR --json  # summary (machine)
    flink-ml-tpu-trace TRACE_DIR --format json   # same, legacy spelling
    flink-ml-tpu-trace TRACE_DIR --chrome t.json # Perfetto-loadable trace
    flink-ml-tpu-trace TRACE_DIR --prometheus    # metrics text exposition
    flink-ml-tpu-trace TRACE_DIR --check         # exit 2 on empty/invalid
    flink-ml-tpu-trace diff A B --budget 20      # regression gate (exit 4)
    flink-ml-tpu-trace health TRACE_DIR --check  # model health (exit 3)
    flink-ml-tpu-trace shards TRACE_DIR --check  # per-device mesh view
    flink-ml-tpu-trace slo TRACE_DIR --check     # SLO verdicts (exit 4)
    flink-ml-tpu-trace drift TRACE_DIR --check   # drift verdicts (exit 4)
    flink-ml-tpu-trace quality TRACE_DIR --check # quality verdicts (exit 4)
    flink-ml-tpu-trace controller TRACE_DIR --check  # ops loop (exit 4)
    flink-ml-tpu-trace path TRACE_DIR --check --budget 50  # critical path
    flink-ml-tpu-trace incident TRACE_DIR --check  # flight recorder (exit 4)
    flink-ml-tpu-trace locks TRACE_DIR --check   # lock watchdog (exit 4)
    flink-ml-tpu-trace fleet DIR --check         # fleet membership (exit 4)
    flink-ml-tpu-trace efficiency DIR --check --min-util 0.4  # roofline (exit 4)
    flink-ml-tpu-trace ROOT --latest             # newest trace dir under ROOT

Sections: top spans by self-time (time in a span minus its children —
where work actually happened), per-epoch breakdown (host/device split,
checkpoints per epoch), and the checkpoint/retry timeline (saves,
restores, quarantines, supervisor restarts, host-pool timeouts) in
chronological order. The ``diff`` subcommand (observability/diff.py)
compares two trace dirs or metrics snapshots — span self-time deltas,
histogram-quantile deltas, compile-count deltas — and with ``--budget``
exits 4 on a regression: CI's and the unattended TPU sweep's perf gate.
The ``health`` subcommand (observability/health.py) renders the
model-health view — per-fit convergence tables, the ml.health
divergence timeline, serving metrics — and with ``--check`` exits 3
when any health event is present: the divergence gate for CI and
unattended sweeps. The ``shards`` subcommand (observability/shards.py)
renders the per-device mesh view — topology, per-shard rows/ready/skew
table, collective structure — and with ``--check`` exits 2 when the
trace recorded no multi-device telemetry: the CI gate proving the mesh
lane really ran multi-device. The ``slo`` subcommand
(observability/slo.py) evaluates declarative latency/error-rate SLOs
against the metrics artifacts and with ``--check`` exits 4 on a
violation — the serving twin of the ``diff`` perf gate; the live,
windowed verdicts come from the ``/slo`` endpoint of a running process
(observability/server.py). The ``drift`` subcommand
(observability/drift.py) compares the live sketch artifacts against
their training-time baselines (PSI / Jensen-Shannon distance / KS per
feature and for predictions) and with ``--check`` exits 4 when any
servable drifted, 2 on missing/broken artifacts — a servable published
without a baseline reports ``source: missing`` and never fails the
gate; the live verdicts come from the ``/drift`` endpoint. The
``quality`` subcommand (observability/evaluation.py) judges the
continuous-evaluation artifacts — AUC / logloss / accuracy /
calibration derived from feedback-joined quality sketches — against
the live AUC floor and each servable's training-time quality baseline,
and with ``--check`` exits 4 when any servable degraded, 2 on
missing/broken artifacts; a thin window (too few joined labels) is
insufficient evidence, never a verdict, and the live verdicts come
from the ``/quality`` endpoint. The
``controller`` subcommand (serving/controller.py, docs/ops.md) renders
the ops-controller timeline — triggers, state transitions, cycle
outcomes, rollbacks — and with ``--check`` exits 4 unless every
controller ended healthy (no failed cycles, final state ``watching``),
2 on missing telemetry: the gate of the chaos-armed ops smoke. The
``path`` subcommand (observability/path.py) reconstructs the span DAG
(parent links + the explicit ``follows_from`` handoff links) and
attributes each serving request's wall time to named segments — queue
wait, padding, the pipeline handoff, device dispatch, result fetch —
plus the per-epoch host/device split; ``--check`` exits 2 with no
reconstructable requests and, with ``--budget PCT``, 4 when the
queue-wait share exceeds the budget. The ``incident`` subcommand
(observability/flightrecorder.py) renders the flight recorder's
``incident-<seq>/`` bundles — the triggering event plus the span ring
that preceded it — and with ``--check`` exits 4 while any
unacknowledged incident exists (``--ack`` marks them reviewed). The
``locks`` subcommand (observability/lockstats.py) merges the lock
watchdog's ``locks-*.json`` dumps (``FLINK_ML_TPU_LOCKCHECK``-armed
runs, common/locks.py) — per-lock hold stats, the acquisition-order
graph, detected cycles (including cycles visible only across processes)
— and with ``--check`` exits 4 on any cycle or long hold, 2 when the
dir holds no lock telemetry at all. The ``fleet`` subcommand
(observability/fleet.py) merges the live ``fleet-*.json`` beacons every
process of a multi-process runtime writes — membership with
alive/stale/dead classification by beacon age, bin-exact fleet-level
windowed quantiles, per-replica load rows — and with ``--check`` exits
4 on a dead member or a violated fleet-scope SLO, 2 when the dir holds
no fleet telemetry at all. The ``efficiency`` subcommand
(observability/profiling.py) joins a captured device profile's measured
per-fn device time (``profile.json``) with the XLA cost model's
FLOPs/bytes into achieved FLOP/s, achieved bandwidth and roofline
utilization per jitted fn — with ``--check --min-util F`` exits 4 when
any measured fn sits below the floor, 2 on missing/torn artifacts, and
0 on an honest ``source: host-fallback`` CPU profile (which claims no
utilization at all). Every
subcommand accepts ``--latest``:
treat the positional dir as a root and resolve the newest trace dir
under it (exporters.resolve_trace_dir) — no more hand-globbing.

Every subcommand's stdout rendering runs under the shared
``exporters.pipe_guard`` — ``... | head`` closing the pipe is normal
CLI usage, never an error or a stack trace.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

from flink_ml_tpu.observability.diff import aggregate_self_time
from flink_ml_tpu.observability.exporters import (
    pipe_guard,
    prometheus_text,
    read_metrics,
    read_spans,
    write_chrome_trace,
)

#: events that belong on the failure/recovery timeline
TIMELINE_EVENTS = ("supervisor.restart", "supervisor.recovered",
                   "checkpoint.quarantine", "hostpool.timeout",
                   "elastic.worker-lost", "elastic.relaunch",
                   "elastic.participation", "elastic.chaos")


def _ms(us) -> float:
    return round((us or 0) / 1000.0, 3)


def summarize(spans: List[dict]) -> dict:
    """Structured summary of a span list (the CLI's JSON output)."""
    by_id = {sp["id"]: sp for sp in spans if sp.get("id")}
    children: Dict[str, List[dict]] = {}
    for sp in spans:
        parent = sp.get("parent")
        if parent in by_id:
            children.setdefault(parent, []).append(sp)

    # -- top spans by aggregate self-time, grouped by name -------------------
    agg = aggregate_self_time(spans)
    top = [{"name": name, "count": row["count"],
            "total_ms": _ms(row["total_us"]),
            "self_ms": _ms(row["self_us"])}
           for name, row in agg.items()]
    top.sort(key=lambda r: -r["self_ms"])

    # -- per-epoch breakdown -------------------------------------------------
    epochs = []
    for sp in spans:
        if sp.get("name") not in ("epoch", "segment"):
            continue
        attrs = sp.get("attrs", {})
        ckpts = sum(1 for c in children.get(sp.get("id"), ())
                    if str(c.get("name", "")).startswith("checkpoint."))
        row = {"kind": sp["name"],
               "epoch": attrs.get("epoch", attrs.get("epoch_to")),
               "ms": _ms(sp.get("dur_us")),
               "checkpoints": ckpts}
        for key in ("host_ms", "device_ms", "rounds", "epoch_from",
                    "epoch_to"):
            if key in attrs:
                row[key] = attrs[key]
        epochs.append(row)
    epochs.sort(key=lambda r: (r["epoch"] is None, r["epoch"]))

    # -- checkpoint / retry timeline -----------------------------------------
    timeline = []
    for sp in spans:
        if str(sp.get("name", "")).startswith("checkpoint."):
            timeline.append({"ts_us": sp.get("ts_us", 0),
                             "what": sp["name"],
                             "ms": _ms(sp.get("dur_us")),
                             "attrs": sp.get("attrs", {})})
        for ev in sp.get("events", ()):
            if ev.get("name") in TIMELINE_EVENTS:
                timeline.append({"ts_us": ev.get("ts_us", 0),
                                 "what": ev["name"],
                                 "attrs": ev.get("attrs", {})})
    timeline.sort(key=lambda r: r["ts_us"])

    roots = [sp for sp in spans if sp.get("parent") not in by_id]
    # multi-process attribution: span records from a jax.distributed run
    # carry a ``process`` label (tracing.py) because pids alone collide
    # across hosts — count spans per process so a merged trace says who
    # ran what
    per_process: Dict[str, int] = {}
    for sp in spans:
        if "process" in sp:
            key = str(sp["process"])
            per_process[key] = per_process.get(key, 0) + 1
    return {"spans": len(spans),
            "traces": len({sp.get("trace") for sp in spans}),
            "roots": [{"name": sp.get("name"),
                       "ms": _ms(sp.get("dur_us"))} for sp in roots],
            "top_self_time": top,
            "epochs": epochs,
            "timeline": timeline,
            **({"processes": per_process} if per_process else {})}


def render_summary(summary: dict, top_n: int = 15) -> str:
    out = [f"{summary['spans']} span(s) across "
           f"{summary['traces']} trace(s)"]
    if summary.get("processes"):
        # numeric order: the keys are stringified process indices, and
        # p10 must not sort before p2
        parts = ", ".join(
            f"p{k}: {v}" for k, v in sorted(
                summary["processes"].items(),
                key=lambda kv: (not kv[0].isdigit(), int(kv[0])
                                if kv[0].isdigit() else 0, kv[0])))
        out.append(f"  processes: {parts} span(s)")
    for root in summary["roots"]:
        out.append(f"  root: {root['name']}  {root['ms']} ms")

    out.append("")
    out.append("top spans by self-time:")
    out.append(f"  {'name':<32} {'count':>6} {'total ms':>12} "
               f"{'self ms':>12}")
    for row in summary["top_self_time"][:top_n]:
        out.append(f"  {row['name']:<32} {row['count']:>6} "
                   f"{row['total_ms']:>12.3f} {row['self_ms']:>12.3f}")

    if summary["epochs"]:
        out.append("")
        out.append("per-epoch breakdown:")
        for row in summary["epochs"]:
            extra = "".join(
                f"  {k}={row[k]}" for k in
                ("host_ms", "device_ms", "rounds") if k in row)
            out.append(f"  {row['kind']} {row['epoch']}: "
                       f"{row['ms']} ms  checkpoints={row['checkpoints']}"
                       f"{extra}")

    if summary["timeline"]:
        out.append("")
        out.append("checkpoint/retry timeline:")
        t0 = summary["timeline"][0]["ts_us"]
        for row in summary["timeline"]:
            attrs = " ".join(f"{k}={v}"
                             for k, v in row.get("attrs", {}).items())
            ms = f" {row['ms']} ms" if "ms" in row else ""
            out.append(f"  +{_ms(row['ts_us'] - t0):>10.3f} ms  "
                       f"{row['what']}{ms}  {attrs}".rstrip())
    return "\n".join(out)


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "diff":
        # the regression gate lives in its own module; dispatch before
        # argparse so `diff` never collides with a dir named "diff"
        # (use ./diff to summarize such a directory)
        from flink_ml_tpu.observability.diff import main as diff_main

        return diff_main(argv[1:])
    if argv and argv[0] == "health":
        # model-health view (observability/health.py); same dispatch
        # rule — use ./health to summarize a directory named "health"
        from flink_ml_tpu.observability.health import main as health_main

        return health_main(argv[1:])
    if argv and argv[0] == "shards":
        # per-device mesh view (observability/shards.py); same dispatch
        # rule — use ./shards to summarize a directory named "shards"
        from flink_ml_tpu.observability.shards import main as shards_main

        return shards_main(argv[1:])
    if argv and argv[0] == "slo":
        # SLO verdicts (observability/slo.py); same dispatch rule —
        # use ./slo to summarize a directory named "slo"
        from flink_ml_tpu.observability.slo import main as slo_main

        return slo_main(argv[1:])
    if argv and argv[0] == "drift":
        # drift verdicts (observability/drift.py); same dispatch rule —
        # use ./drift to summarize a directory named "drift"
        from flink_ml_tpu.observability.drift import main as drift_main

        return drift_main(argv[1:])
    if argv and argv[0] == "quality":
        # continuous-evaluation verdicts (observability/evaluation.py);
        # same dispatch rule — ./quality summarizes such a directory
        from flink_ml_tpu.observability.evaluation import (
            main as quality_main,
        )

        return quality_main(argv[1:])
    if argv and argv[0] == "controller":
        # ops-controller timeline (serving/controller.py); same
        # dispatch rule — ./controller summarizes such a directory
        from flink_ml_tpu.serving.controller import (
            main as controller_main,
        )

        return controller_main(argv[1:])
    if argv and argv[0] == "path":
        # critical-path view (observability/path.py); same dispatch
        # rule — use ./path to summarize a directory named "path"
        from flink_ml_tpu.observability.path import main as path_main

        return path_main(argv[1:])
    if argv and argv[0] == "incident":
        # flight-recorder bundles (observability/flightrecorder.py);
        # same dispatch rule — ./incident summarizes such a directory
        from flink_ml_tpu.observability.flightrecorder import (
            main as incident_main,
        )

        return incident_main(argv[1:])
    if argv and argv[0] == "locks":
        # lock-watchdog view (observability/lockstats.py); same
        # dispatch rule — use ./locks to summarize such a directory
        from flink_ml_tpu.observability.lockstats import (
            main as locks_main,
        )

        return locks_main(argv[1:])
    if argv and argv[0] == "fleet":
        # live fleet membership + aggregates (observability/fleet.py);
        # same dispatch rule — use ./fleet to summarize such a directory
        from flink_ml_tpu.observability.fleet import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "efficiency":
        # measured device time vs XLA cost model
        # (observability/profiling.py); same dispatch rule — use
        # ./efficiency to summarize a directory named "efficiency"
        from flink_ml_tpu.observability.profiling import (
            main as efficiency_main,
        )

        return efficiency_main(argv[1:])
    if argv and argv[0] == "summary":
        # explicit subcommand spelling for the default view, so
        # unattended consumers can write `summary --json` without
        # knowing the bare-positional legacy form
        argv = argv[1:]
    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace",
        description="Summarize a FLINK_ML_TPU_TRACE_DIR trace directory "
                    "(or `diff A B [--budget PCT]` two of them).")
    parser.add_argument("trace_dir")
    parser.add_argument("--chrome", metavar="OUT_JSON",
                        help="also export a Chrome/Perfetto trace")
    parser.add_argument("--prometheus", action="store_true",
                        help="print the merged metrics snapshot in "
                             "Prometheus text exposition format")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--json", action="store_true",
                        help="shorthand for --format json (machine-"
                             "readable summary for unattended sweeps)")
    parser.add_argument("--top", type=int, default=15,
                        help="rows in the self-time table")
    parser.add_argument("--check", action="store_true",
                        help="exit 2 when the trace has no spans (CI "
                             "smoke gate)")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    args = parser.parse_args(argv)

    try:
        from flink_ml_tpu.observability.exporters import (
            resolve_trace_dir,
        )

        args.trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        spans = read_spans(args.trace_dir)
    except OSError as e:
        print(f"flink-ml-tpu-trace: cannot read {args.trace_dir}: {e}",
              file=sys.stderr)
        return 2
    if args.check and not spans:
        print(f"flink-ml-tpu-trace: no spans in {args.trace_dir}",
              file=sys.stderr)
        return 2

    if args.chrome:
        n = write_chrome_trace(args.trace_dir, args.chrome)
        print(f"wrote {n} span(s) to {args.chrome}", file=sys.stderr)

    if args.prometheus:
        snap = read_metrics(args.trace_dir)
        if not snap:
            print("flink-ml-tpu-trace: no metric samples in "
                  f"{args.trace_dir} — either no metrics-*.json snapshot "
                  "was written (one lands when an outermost stage span "
                  "closes) or the traced run recorded no metrics",
                  file=sys.stderr)
        with pipe_guard():
            print(prometheus_text(snap), end="")
        return 0

    summary = summarize(spans)
    with pipe_guard():
        if args.json or args.format == "json":
            print(json.dumps(summary, indent=2, default=str))
        else:
            print(render_summary(summary, top_n=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
