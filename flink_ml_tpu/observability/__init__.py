"""Unified observability: span tracing, metric export, run inspection,
compile & device telemetry.

See docs/observability.md. Arm with ``FLINK_ML_TPU_TRACE_DIR=<dir>``
(spans + metric snapshots stream there as JSON artifacts) and inspect
with ``flink-ml-tpu-trace <dir>``; compare/gate two runs with
``flink-ml-tpu-trace diff A B --budget <pct>``. Composes with the
``FLINK_ML_TPU_PROFILE_DIR`` jax.profiler hook (common/metrics.py)
rather than replacing it. Compile telemetry (``compilestats``) records
XLA compile counts/durations, recompile storms, per-program FLOP/byte
cost and HBM watermarks into the same artifact set. Model-health
telemetry (``health``) adds convergence series, device-side non-finite
sentinels, divergence events and serving-path metrics — inspect with
``flink-ml-tpu-trace health <dir>``. Drift detection (``drift``)
captures training-time distribution baselines at fit time, sketches
live serving traffic with mergeable streaming sketches, and compares
the two (PSI / Jensen-Shannon / KS) per model version — inspect with
``flink-ml-tpu-trace drift <dir>`` or the live ``/drift`` route.
Causal tracing (``tracing.TraceContext``) carries span context across
threads, the host-pool fork, the multi-process launcher and the
ops-controller cycle; ``flink-ml-tpu-trace path <dir>`` attributes
per-request wall time along the span DAG, and the flight recorder
(``flightrecorder``) dumps ``incident-<seq>/`` evidence bundles on SLO
violations, divergence, drift and rollbacks — inspect with
``flink-ml-tpu-trace incident <dir>``. Device profiling (``profiling``)
captures bounded ``jax.profiler`` windows (env-armed fits/batcher
ticks, the live ``/profilez`` route, anomaly-triggered incident
bundles), attributes per-op/per-fn measured device time into
``profile.json``, and joins it with the XLA cost model into achieved
FLOPs + roofline utilization — inspect with ``flink-ml-tpu-trace
efficiency <dir>``; boot-to-ready phase telemetry (``boot.*`` spans,
``bootToReadyMs``) rides in the same module.
"""

from flink_ml_tpu.observability.compilestats import (
    aot_compile,
    capture_cost,
    compile_stats,
    compile_totals,
    instrumented_jit,
    sample_memory,
)
from flink_ml_tpu.observability.health import (
    CONVERGENCE_EVENT,
    HEALTH_EVENT,
    ConvergenceListener,
    check_fit,
    convergence_row,
    finite_sentinel,
    guard_final_state,
    observe_serving,
    summarize_values,
)
from flink_ml_tpu.observability.drift import (
    DRIFT_EVENT,
    DriftBaseline,
    SketchGroup,
    StreamingSketch,
    capture_fit_baseline,
    compare_sketches,
    drift_report,
    install_baseline,
    observe_transform,
)
from flink_ml_tpu.observability.exporters import (
    chrome_trace,
    dump_metrics,
    latest_trace_dir,
    prometheus_text,
    read_metrics,
    read_spans,
    resolve_trace_dir,
    write_chrome_trace,
)
from flink_ml_tpu.observability.slo import (
    SLO,
    SLO_EVENT,
    SLO_SPEC_ENV,
    default_slos,
    evaluate_slos,
    load_specs,
)
from flink_ml_tpu.observability.server import (
    METRICS_PORT_ENV,
    TelemetryServer,
    maybe_start,
)
from flink_ml_tpu.observability.meshstats import (
    SKEW_EVENT,
    detect_skew,
    ensure_mesh_recorded,
    mesh_snapshot,
    observe_shard_ready,
    read_mesh,
    record_input_health,
    record_shard_rows,
)
from flink_ml_tpu.observability.flightrecorder import (
    INCIDENT_EVENT,
    acknowledge,
    read_incidents,
    record_incident,
)
from flink_ml_tpu.observability.path import analyze_paths
from flink_ml_tpu.observability.profiling import (
    CAPTURE_ENV,
    boot_phase,
    boot_to_ready_ms,
    capture_now,
    efficiency_report,
    mark_ready,
    parse_profile_dir,
    profile_window,
    read_profile,
)
from flink_ml_tpu.observability.tracing import (
    TRACE_DIR_ENV,
    TRACE_PARENT_ENV,
    Span,
    TraceContext,
    Tracer,
    current_context,
    event,
    fresh_context,
    span,
    tracer,
)

__all__ = [
    "CONVERGENCE_EVENT",
    "DRIFT_EVENT",
    "DriftBaseline",
    "HEALTH_EVENT",
    "SketchGroup",
    "StreamingSketch",
    "capture_fit_baseline",
    "compare_sketches",
    "drift_report",
    "install_baseline",
    "observe_transform",
    "INCIDENT_EVENT",
    "METRICS_PORT_ENV",
    "SKEW_EVENT",
    "SLO",
    "SLO_EVENT",
    "SLO_SPEC_ENV",
    "TRACE_DIR_ENV",
    "TRACE_PARENT_ENV",
    "ConvergenceListener",
    "Span",
    "TraceContext",
    "TelemetryServer",
    "Tracer",
    "CAPTURE_ENV",
    "acknowledge",
    "analyze_paths",
    "boot_phase",
    "boot_to_ready_ms",
    "capture_now",
    "current_context",
    "efficiency_report",
    "fresh_context",
    "mark_ready",
    "parse_profile_dir",
    "profile_window",
    "read_profile",
    "read_incidents",
    "record_incident",
    "aot_compile",
    "check_fit",
    "convergence_row",
    "finite_sentinel",
    "guard_final_state",
    "observe_serving",
    "summarize_values",
    "capture_cost",
    "chrome_trace",
    "compile_stats",
    "compile_totals",
    "default_slos",
    "detect_skew",
    "dump_metrics",
    "ensure_mesh_recorded",
    "evaluate_slos",
    "event",
    "instrumented_jit",
    "latest_trace_dir",
    "load_specs",
    "maybe_start",
    "mesh_snapshot",
    "observe_shard_ready",
    "prometheus_text",
    "read_mesh",
    "read_metrics",
    "read_spans",
    "record_input_health",
    "record_shard_rows",
    "resolve_trace_dir",
    "sample_memory",
    "span",
    "tracer",
    "write_chrome_trace",
]
