"""Unified observability: span tracing, metric export, run inspection.

See docs/observability.md. Arm with ``FLINK_ML_TPU_TRACE_DIR=<dir>``
(spans + metric snapshots stream there as JSON artifacts) and inspect
with ``flink-ml-tpu-trace <dir>``; composes with the
``FLINK_ML_TPU_PROFILE_DIR`` jax.profiler hook (common/metrics.py)
rather than replacing it.
"""

from flink_ml_tpu.observability.exporters import (
    chrome_trace,
    dump_metrics,
    prometheus_text,
    read_metrics,
    read_spans,
    write_chrome_trace,
)
from flink_ml_tpu.observability.tracing import (
    TRACE_DIR_ENV,
    Span,
    Tracer,
    event,
    span,
    tracer,
)

__all__ = [
    "TRACE_DIR_ENV",
    "Span",
    "Tracer",
    "chrome_trace",
    "dump_metrics",
    "event",
    "prometheus_text",
    "read_metrics",
    "read_spans",
    "span",
    "tracer",
    "write_chrome_trace",
]
