"""Anomaly-triggered flight recorder: when something degrades, hand the
operator the evidence — not a dashboard snapshot taken after the fact.

The tracer already keeps a bounded ring of recently closed spans
(tracing.Tracer.recent, capacity ``FLINK_ML_TPU_TRACE_RING``) and the
metrics registry holds the live counters/gauges/windows. This module is
the dump valve: :func:`record_incident` freezes both — plus the SLO,
drift and controller state that explain *why* — into an
``incident-<seq>/`` bundle under the armed trace dir the moment an
anomaly fires, BEFORE the ring rotates the explanation away.

Wired triggers (each calls :func:`record_incident` with its own kind):

==============  ============================================================
kind            fired by
==============  ============================================================
``slo``         a violated SLO during an emitting evaluation
                (observability/slo.py — the ``/slo`` scrape, the ops
                controller's watch step)
``divergence``  a model-health divergence classification — the
                ``ml.health`` event that precedes the terminal
                :class:`~flink_ml_tpu.resilience.policy.NonFiniteState`
                (observability/health.py)
``drift``       a drift verdict crossing its threshold during an
                emitting evaluation (observability/drift.py)
``rollback``    :meth:`~flink_ml_tpu.serving.registry.ModelRegistry
                .rollback` — the ops loop demoted a serving version
==============  ============================================================

Bundle layout (everything best-effort: a bundle with a missing optional
file is still evidence; a recorder failure must never worsen the
incident it records)::

    incident-000/
      incident.json        seq, kind, trigger attrs, ts, acknowledged
      spans-recent.jsonl   the span ring at trigger time (the evidence)
      metrics.json         full registry snapshot (cumulative)
      windows.json         windowed ml.serving views (recent p99s/rates)
      slo.json             SLO verdicts at trigger time (non-emitting)
      drift.json           drift report at trigger time (non-emitting)
      controller.json      /controller provider state, when registered
      mesh.json            copied from the trace dir when present
      profile/             a short bounded device profile of the anomaly's
      profile.json         aftermath + its per-op attribution, when a jax
                           backend is live (observability/profiling.py;
                           length ``FLINK_ML_TPU_INCIDENT_PROFILE_MS``,
                           default 200, 0 disables)

Bundles are **debounced** (``FLINK_ML_TPU_INCIDENT_DEBOUNCE_S``,
default 30 — one incident usually fires several triggers in a burst:
the SLO violation, the drift verdict AND the rollback it caused) and
**capped** (``FLINK_ML_TPU_INCIDENT_MAX``, default 8) per process;
suppressed triggers are counted (``ml.incident suppressed{reason=}``)
so a quiet recorder is distinguishable from a disarmed one. Without an
armed trace dir there is nowhere durable to dump — the trigger counts
(``skipped{reason="no-trace-dir"}``) and nothing is written.

Inspect with ``flink-ml-tpu-trace incident <dir> [--json|--check]``:
renders each bundle's trigger and the preceding-span timeline; with
``--check`` exits :data:`EXIT_UNACKED` (4) while any unacknowledged
incident exists (``--ack`` marks them reviewed), 2 on unreadable
artifacts — the CI smoke's gate (docs/observability.md).
"""

from __future__ import annotations

import glob
import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.common.metrics import ML_GROUP, metrics
from flink_ml_tpu.observability import tracing

__all__ = [
    "DEBOUNCE_ENV", "MAX_ENV", "RECORDER_ENV", "INCIDENT_EVENT",
    "INCIDENT_PREFIX", "EXIT_OK", "EXIT_INVALID", "EXIT_UNACKED",
    "record_incident", "read_incidents", "acknowledge", "reset",
    "main",
]

#: ``0`` disables the recorder outright (the triggers stay compiled in;
#: one env read decides)
RECORDER_ENV = "FLINK_ML_TPU_FLIGHT_RECORDER"
#: minimum seconds between bundles (default 30): one degradation fires
#: many triggers — the first bundle carries the evidence
DEBOUNCE_ENV = "FLINK_ML_TPU_INCIDENT_DEBOUNCE_S"
#: bundle cap per process (default 8): a flapping SLO must not fill the
#: disk with near-identical bundles
MAX_ENV = "FLINK_ML_TPU_INCIDENT_MAX"

#: instant-event name stamped when a bundle lands
INCIDENT_EVENT = "ml.incident"

INCIDENT_PREFIX = "incident-"
INCIDENT_FILE = "incident.json"

EXIT_OK = 0
EXIT_INVALID = 2
#: the CLI's --check exit while an unacknowledged incident exists —
#: same violation class as slo/drift/controller's 4
EXIT_UNACKED = 4

_lock = make_lock("observability.flightrecorder")
_seq = 0
_last_ts: Optional[float] = None
# re-entrancy latch: building a bundle evaluates SLOs/drift, which can
# themselves trigger — the recorder must never recurse into itself
_recording = threading.local()


def _enabled() -> bool:
    return os.environ.get(RECORDER_ENV, "").strip() != "0"


def _debounce_s() -> float:
    raw = os.environ.get(DEBOUNCE_ENV)
    if raw:
        try:
            return max(0.0, float(raw))
        except ValueError:
            pass
    return 30.0


def _max_incidents() -> int:
    raw = os.environ.get(MAX_ENV)
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            pass
    return 8


def _group():
    return metrics.group(ML_GROUP, "incident")


def _suppress(reason: str) -> None:
    try:
        _group().counter("suppressed", labels={"reason": reason})
    except Exception:  # noqa: BLE001 — accounting only
        pass


def reset() -> None:
    """Forget the per-process debounce/sequence state (tests; also the
    right call after re-pointing the trace dir at a fresh run)."""
    global _seq, _last_ts
    with _lock:
        _seq = 0
        _last_ts = None


def _write_json(path: str, payload) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, default=str)


def _windowed_views() -> Dict[str, dict]:
    """Recent windowed views of the serving seam — the "what did the
    last minute look like" half a cumulative snapshot cannot answer."""
    out: Dict[str, dict] = {}
    grp = metrics.group(ML_GROUP, "serving")
    from flink_ml_tpu.common.metrics import (
        WindowedHistogram,
        histogram_quantile,
    )

    for key in list(grp.snapshot().get("histograms", {})):
        h = grp.histogram(key)
        if not isinstance(h, WindowedHistogram):
            continue
        snap = h.window_snapshot(60.0)
        out[key] = {
            "window_s": 60.0,
            "count": snap.get("count", 0),
            "p50_ms": histogram_quantile(snap, 0.5),
            "p99_ms": histogram_quantile(snap, 0.99),
        }
    for key, wc in grp.windowed_counter_items():
        out[key] = {"window_s": 60.0,
                    "delta": wc.window_delta(60.0),
                    "rate_per_s": wc.window_rate(60.0)}
    return out


def record_incident(kind: str, **attrs) -> Optional[str]:
    """Dump an incident bundle for an anomaly of ``kind``; returns the
    bundle path (None when disabled, debounced, capped, undumpable or
    re-entered). ``attrs`` are the triggering event's own attributes —
    they land verbatim in ``incident.json`` so the bundle names its
    cause. Never raises: the recorder must not worsen the incident."""
    if not _enabled():
        return None
    if getattr(_recording, "active", False):
        return None
    trace_dir = tracing.tracer.trace_dir
    if not trace_dir:
        _suppress("no-trace-dir")
        return None
    global _seq, _last_ts
    with _lock:
        now = time.monotonic()
        if _last_ts is not None and now - _last_ts < _debounce_s():
            _suppress("debounced")
            return None
        if _seq >= _max_incidents():
            _suppress("capped")
            return None
        _seq += 1  # the per-process cap counts THIS process's bundles
        _last_ts = now
    _recording.active = True
    try:
        return _dump(trace_dir, kind, attrs)
    except Exception:  # noqa: BLE001 — see docstring
        import logging

        logging.getLogger(__name__).warning(
            "flight recorder failed to dump incident (kind=%s)", kind,
            exc_info=True)
        return None
    finally:
        _recording.active = False


def _next_seq(trace_dir: str) -> int:
    """One past the highest bundle index already on disk — the dir may
    hold bundles from a PREVIOUS run of the same trace dir (or another
    process sharing it); a restarting process must extend the series,
    not collide with incident-000 and lose its evidence."""
    top = -1
    for path in glob.glob(os.path.join(trace_dir,
                                       INCIDENT_PREFIX + "*")):
        name = os.path.basename(path)
        if name.endswith(".tmp"):
            continue
        try:
            top = max(top, int(name[len(INCIDENT_PREFIX):]))
        except ValueError:
            continue
    return top + 1


def _dump(trace_dir: str, kind: str, attrs: dict) -> str:
    seq = _next_seq(trace_dir)
    final = os.path.join(trace_dir, f"{INCIDENT_PREFIX}{seq:03d}")
    tmp = final + ".tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)

    # the spans that ran up to the trigger: the ring, oldest first.
    # deque iteration can race a concurrent append (RuntimeError) —
    # retry, the /spans/recent idiom
    spans: List[dict] = []
    for _ in range(8):
        try:
            spans = list(tracing.tracer.recent)
            break
        except RuntimeError:
            continue
    with open(os.path.join(tmp, "spans-recent.jsonl"), "w",
              encoding="utf-8") as f:
        for rec in spans:
            f.write(json.dumps(rec, default=str) + "\n")

    dropped = tracing.tracer.mirror_dropped()
    _write_json(os.path.join(tmp, "metrics.json"), metrics.snapshot())
    try:
        _write_json(os.path.join(tmp, "windows.json"),
                    _windowed_views())
    except Exception:  # noqa: BLE001 — optional evidence
        pass
    try:
        from flink_ml_tpu.observability import slo

        _write_json(os.path.join(tmp, "slo.json"),
                    slo.evaluate_slos(slo.active_slos(), emit=False))
    except Exception:  # noqa: BLE001 — optional evidence
        pass
    try:
        from flink_ml_tpu.observability import drift
        from flink_ml_tpu.observability.health import _json_safe

        _write_json(os.path.join(tmp, "drift.json"),
                    _json_safe(drift.drift_report(emit=False)))
    except Exception:  # noqa: BLE001 — optional evidence
        pass
    try:
        from flink_ml_tpu.observability import server
        from flink_ml_tpu.observability.health import _json_safe

        provider = server.get_controller_status()
        if provider is not None:
            _write_json(os.path.join(tmp, "controller.json"),
                        _json_safe(provider()))
    except Exception:  # noqa: BLE001 — optional evidence
        pass
    mesh_src = os.path.join(trace_dir, "mesh.json")
    if os.path.isfile(mesh_src):
        try:
            shutil.copyfile(mesh_src, os.path.join(tmp, "mesh.json"))
        except OSError:
            pass
    # a short bounded device profile of the anomaly's aftermath — raw
    # trace under profile/, attribution at profile.json. profiling
    # refuses on its own (kill-switch, non-driver, backend not live,
    # another trace active) rather than block the dump
    profiled = False
    try:
        from flink_ml_tpu.observability import profiling

        profiled = profiling.capture_incident_profile(tmp)
    except Exception:  # noqa: BLE001 — optional evidence
        pass

    from flink_ml_tpu.observability.exporters import safe_process_label

    meta = {
        "seq": seq,
        "kind": kind,
        "ts_us": time.time_ns() // 1000,
        "attrs": dict(attrs),
        "pid": os.getpid(),
        "process": safe_process_label(),
        "spans": len(spans),
        # cumulative ring evictions say how long the process has been
        # up; evidence_truncated answers the question that matters for
        # THIS bundle — was the ring full, i.e. did older spans of the
        # incident's window rotate out before the dump
        "dropped_spans": dropped,
        "ring_capacity": tracing.tracer.recent.maxlen,
        "evidence_truncated": (
            tracing.tracer.recent.maxlen is not None
            and len(spans) >= tracing.tracer.recent.maxlen),
        "device_profile": profiled,
        "acknowledged": False,
    }
    _write_json(os.path.join(tmp, INCIDENT_FILE), meta)
    # atomic publish: readers (the CLI, an artifact uploader racing the
    # serving process) never see a half-written bundle. Another process
    # sharing the trace dir may have claimed the index between the scan
    # and here — step past it (meta rewritten to match the dir name)
    # instead of discarding the evidence
    for _ in range(8):
        try:
            os.replace(tmp, final)
            break
        except OSError:
            meta["seq"] = seq = _next_seq(trace_dir)
            final = os.path.join(trace_dir,
                                 f"{INCIDENT_PREFIX}{seq:03d}")
            _write_json(os.path.join(tmp, INCIDENT_FILE), meta)
    else:
        raise OSError(f"could not publish incident bundle into "
                      f"{trace_dir}")
    try:
        _group().counter("recorded", labels={"kind": kind})
    except Exception:  # noqa: BLE001 — accounting only
        pass
    tracing.tracer.event(INCIDENT_EVENT, kind=kind, seq=seq,
                         bundle=os.path.basename(final))
    return final


# -- reading / acknowledging --------------------------------------------------

def read_incidents(trace_dir: str,
                   include_spans: bool = True) -> List[dict]:
    """All incident bundles under ``trace_dir``, sequence order; each
    row is the bundle's ``incident.json`` plus ``dir`` (the bundle
    path) and ``recent_spans`` (the preceding-span evidence).
    ``include_spans=False`` skips parsing the span files — callers that
    only list bundles (the live ``/incidents`` route, the CLI's
    ``--json``) must not re-read up to cap x ring-capacity span lines
    per scrape; the meta's own ``spans`` count still reports how much
    evidence each bundle holds."""
    rows: List[dict] = []
    for path in sorted(glob.glob(
            os.path.join(trace_dir, INCIDENT_PREFIX + "*"))):
        if not os.path.isdir(path) or path.endswith(".tmp"):
            continue
        meta_path = os.path.join(path, INCIDENT_FILE)
        try:
            with open(meta_path, "r", encoding="utf-8") as f:
                meta = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue  # a torn bundle must not sink the readable ones
        spans: List[dict] = []
        spans_path = os.path.join(path, "spans-recent.jsonl")
        if include_spans and os.path.isfile(spans_path):
            with open(spans_path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        spans.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        meta["dir"] = path
        meta["recent_spans"] = spans
        rows.append(meta)
    rows.sort(key=lambda r: r.get("seq", 0))
    return rows


def acknowledge(trace_dir: str, seq: Optional[int] = None) -> int:
    """Mark incidents reviewed (all, or just ``seq``): flips
    ``acknowledged`` in each bundle's ``incident.json`` so ``--check``
    stops exiting 4 for it. Returns the number acknowledged."""
    n = 0
    for row in read_incidents(trace_dir, include_spans=False):
        if seq is not None and row.get("seq") != seq:
            continue
        if row.get("acknowledged"):
            continue
        meta = {k: v for k, v in row.items()
                if k not in ("dir", "recent_spans")}
        meta["acknowledged"] = True
        _write_json(os.path.join(row["dir"], INCIDENT_FILE), meta)
        n += 1
    return n


# -- rendering / CLI ----------------------------------------------------------

def render_incidents(rows: List[dict], spans_tail: int = 12) -> str:
    if not rows:
        return "no incident bundles"
    unacked = sum(1 for r in rows if not r.get("acknowledged"))
    out = [f"{len(rows)} incident bundle(s), {unacked} unacknowledged"]
    for row in rows:
        out.append("")
        attrs = " ".join(f"{k}={v}"
                         for k, v in row.get("attrs", {}).items())
        flag = "" if row.get("acknowledged") else "  [UNACKNOWLEDGED]"
        out.append(f"incident {row.get('seq'):>3}  "
                   f"kind={row.get('kind')}  {attrs}{flag}".rstrip())
        spans = row.get("recent_spans", [])
        if spans:
            ts0 = row.get("ts_us", 0)
            out.append(f"  preceding spans ({len(spans)} ringed, "
                       f"last {min(spans_tail, len(spans))}):")
            for sp in spans[-spans_tail:]:
                dt_ms = (sp.get("ts_us", 0) - ts0) / 1000.0
                dur = (sp.get("dur_us") or 0) / 1000.0
                out.append(f"    {dt_ms:>12.3f} ms  "
                           f"{sp.get('name', '?'):<28} "
                           f"{dur:.3f} ms  trace={sp.get('trace')}")
    return "\n".join(out)


def main(argv=None) -> int:
    """``flink-ml-tpu-trace incident <dir>`` — render incident bundles;
    ``--check`` exits :data:`EXIT_UNACKED` (4) while any unacknowledged
    incident exists (0 when clean — no bundles IS the healthy state),
    :data:`EXIT_INVALID` (2) on an unreadable dir; ``--ack [SEQ]``
    acknowledges (all, or one) first."""
    import argparse
    import sys

    from flink_ml_tpu.observability.exporters import (
        pipe_guard,
        resolve_trace_dir,
    )

    parser = argparse.ArgumentParser(
        prog="flink-ml-tpu-trace incident",
        description="Flight-recorder incident bundles of a "
                    "FLINK_ML_TPU_TRACE_DIR (docs/observability.md "
                    "\"Causal tracing, critical path & incidents\").")
    parser.add_argument("trace_dir")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--check", action="store_true",
                        help="exit 4 while any unacknowledged incident "
                             "exists (clean dir exits 0), 2 on an "
                             "unreadable dir")
    parser.add_argument("--ack", nargs="?", const=-1, type=int,
                        default=None, metavar="SEQ",
                        help="acknowledge incidents (all, or just SEQ) "
                             "before rendering/checking")
    parser.add_argument("--latest", action="store_true",
                        help="treat TRACE_DIR as a root and pick the "
                             "newest trace dir under it")
    args = parser.parse_args(argv)

    try:
        trace_dir = resolve_trace_dir(args.trace_dir, args.latest)
        if not os.path.isdir(trace_dir):
            raise FileNotFoundError(trace_dir)
        if args.ack is not None:
            n = acknowledge(trace_dir,
                            None if args.ack == -1 else args.ack)
            print(f"acknowledged {n} incident(s)", file=sys.stderr)
        # the text render shows the preceding-span timeline; the JSON
        # listing reports the meta's own span count without re-parsing
        # every bundle's evidence
        rows = read_incidents(trace_dir, include_spans=not args.json)
    except OSError as e:
        print(f"flink-ml-tpu-trace incident: cannot read "
              f"{args.trace_dir}: {e}", file=sys.stderr)
        return EXIT_INVALID
    with pipe_guard():
        if args.json:
            slim = [{k: v for k, v in r.items() if k != "recent_spans"}
                    | {"recent_spans": r.get("spans", 0)}
                    for r in rows]
            print(json.dumps({"trace_dir": trace_dir,
                              "incidents": slim}, indent=2,
                             default=str))
        else:
            print(render_incidents(rows))
    unacked = [r for r in rows if not r.get("acknowledged")]
    if args.check and unacked:
        print(f"flink-ml-tpu-trace incident: "
              f"{len(unacked)} unacknowledged incident(s) in "
              f"{trace_dir}", file=sys.stderr)
        return EXIT_UNACKED
    return EXIT_OK


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
