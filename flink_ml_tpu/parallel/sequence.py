"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no sequence models (SURVEY.md §5: no attention anywhere);
this module is the TPU-native long-context capability the framework adds so
sequence workloads scale the same way the rest of the framework does —
shard_map over a mesh axis with XLA collectives over ICI.

Two standard strategies (cf. the public ring-attention / DeepSpeed-Ulysses
literature):

- :func:`ring_attention` — shard the sequence over the ``seq`` axis; K/V
  blocks rotate around the ring via ``ppermute`` while each shard folds one
  block per step into an online-softmax accumulator (numerically exact, at
  no point does any device hold the full sequence). Memory per device is
  O(L/P); supports causal masking via global position offsets.
- :func:`ulysses_attention` — ``all_to_all`` re-shards from
  sequence-parallel to head-parallel, runs full attention on H/P heads
  locally, and re-shards back. One collective pair instead of P ppermutes;
  requires heads % axis_size == 0.

Both are drop-in jnp functions for use inside ``shard_map`` bodies; tests
validate exactness against single-device full attention on the virtual
8-device CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flink_ml_tpu.parallel.shardmap import shard_map
from flink_ml_tpu.parallel.shardmap import axis_size as _axis_size

SEQ_AXIS = "seq"


def _block_scores(q, k_blk, scale, mask):
    """(H, L, M) attention scores of local q against one K block."""
    scores = jnp.einsum("lhd,mhd->hlm", q, k_blk) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    return scores


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS, causal: bool = False):
    """Exact attention over a sequence sharded on ``axis_name``.

    Args: q, k, v — per-shard blocks of shape (L_local, H, Dh).
    Returns the per-shard output block (L_local, H, Dh).

    Per step: fold the resident K/V block into an online-softmax state
    (running max m, denominator l, weighted sum o), then rotate K/V one hop
    around the ring (``ppermute``) — compute and communication overlap
    naturally under XLA async collectives.
    """
    axis_size = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    l_local, num_heads, d_head = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d_head, q.dtype))

    q_pos = my_idx * l_local + jnp.arange(l_local)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def fold(i, m, l, o, k_blk, v_blk):
        """Fold one resident K/V block into the online-softmax state."""
        # the resident block originated at shard (my_idx - i) mod P
        src = (my_idx - i) % axis_size
        mask = None
        if causal:
            k_pos = src * l_local + jnp.arange(l_local)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, :, :]
        scores = _block_scores(q, k_blk, scale, mask)           # (H, L, M)

        blk_max = jnp.max(scores, axis=-1)                      # (H, L)
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked rows (m_new == -inf)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
        probs = jnp.exp(scores - m_safe[:, :, None])
        probs = jnp.where(jnp.isfinite(scores), probs, 0.0)
        l_new = l * correction + jnp.sum(probs, axis=-1)
        o_new = (o * correction[:, :, None]
                 + jnp.einsum("hlm,mhd->hld", probs, v_blk))
        return m_new, l_new, o_new

    def step(i, state):
        m, l, o, k_blk, v_blk = state
        m, l, o = fold(i, m, l, o, k_blk, v_blk)
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return m, l, o, k_next, v_next

    m0 = jnp.full((num_heads, l_local), -jnp.inf, q.dtype)
    l0 = jnp.zeros((num_heads, l_local), q.dtype)
    o0 = jnp.zeros((num_heads, l_local, d_head), q.dtype)
    # rotate P-1 times; the last resident block folds outside the loop so
    # no discarded final ppermute pair is issued
    m, l, o, k_last, v_last = jax.lax.fori_loop(
        0, axis_size - 1, step, (m0, l0, o0, k, v))
    m, l, o = fold(axis_size - 1, m, l, o, k_last, v_last)
    out = o / jnp.maximum(l, 1e-30)[:, :, None]
    return jnp.transpose(out, (1, 0, 2))  # back to (L, H, Dh)


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS,
                      causal: bool = False):
    """Sequence→head re-sharding attention (DeepSpeed-Ulysses pattern).

    Args: q, k, v — per-shard (L_local, H, Dh) with H divisible by the axis
    size. all_to_all gathers the full sequence while scattering heads, runs
    dense attention on H/P heads, then re-shards back to sequence parallel.
    """
    axis_size = _axis_size(axis_name)

    def to_head_parallel(x):
        # (L_local, H, Dh) → (L_global, H/P, Dh)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                                  tiled=True)

    def to_seq_parallel(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = (to_head_parallel(t) for t in (q, k, v))
    l_global = qh.shape[0]
    scale = 1.0 / jnp.sqrt(jnp.asarray(qh.shape[-1], q.dtype))
    scores = jnp.einsum("lhd,mhd->hlm", qh, kh) * scale
    if causal:
        pos = jnp.arange(l_global)
        scores = jnp.where(pos[None, :, None] >= pos[None, None, :],
                           scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hlm,mhd->lhd", probs, vh)
    return to_seq_parallel(out)


def full_attention(q, k, v, causal: bool = False):
    """Single-device reference implementation (test oracle): (L, H, Dh)."""
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    scores = jnp.einsum("lhd,mhd->hlm", q, k) * scale
    if causal:
        pos = jnp.arange(q.shape[0])
        scores = jnp.where(pos[None, :, None] >= pos[None, None, :],
                           scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("hlm,mhd->lhd", probs, v)


@functools.lru_cache(maxsize=16)
def _build_sharded_attention(mesh: Mesh, kind: str, causal: bool,
                             axis_name: str):
    fn = ring_attention if kind == "ring" else ulysses_attention

    def per_shard(q, k, v):
        return fn(q, k, v, axis_name=axis_name, causal=causal)

    spec = P(axis_name, None, None)
    return jax.jit(shard_map(per_shard, mesh=mesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False))


def sharded_attention(mesh: Mesh, q, k, v, kind: str = "ring",
                      causal: bool = False, axis_name: str = None):
    """Host-level entry: q/k/v are global (L, H, Dh) arrays; the sequence
    dim is sharded over the mesh's sequence axis and attention runs with
    the chosen strategy."""
    if axis_name is None:
        axis_name = (SEQ_AXIS if SEQ_AXIS in mesh.axis_names
                     else mesh.axis_names[0])
    if kind not in ("ring", "ulysses"):
        raise ValueError(f"unknown attention kind {kind!r}")
    axis_size = mesh.shape[axis_name]
    if q.shape[0] % axis_size:
        raise ValueError(
            f"sequence length {q.shape[0]} must be divisible by the "
            f"{axis_name!r} axis size {axis_size} (pad the sequence)")
    if kind == "ulysses" and q.shape[1] % axis_size:
        raise ValueError(
            f"ulysses attention needs heads ({q.shape[1]}) divisible by the "
            f"{axis_name!r} axis size {axis_size}; use kind='ring' instead")
    program = _build_sharded_attention(mesh, kind, causal, axis_name)
    return program(q, k, v)
