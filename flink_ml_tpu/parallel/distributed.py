"""Multi-process training runtime: jax.distributed meshes + the launcher.

One fit, many processes. ``parallel/mapreduce.py`` and
``parallel/update_sharding.py`` were built as THE SPMD seams; this module
drives them across the process boundary: a ``jax.distributed``-initialized
runtime where every process contributes its local devices to ONE global
mesh, and the existing ``map_shards``/``MapReduceProgram`` programs run
over it unchanged — the reference's "add TaskManagers, keep the job"
story, with SPMD lockstep replacing the coordinator RPC.

Three pieces:

- :func:`init_distributed` — env-mappable, idempotent cluster join. The
  same call works as code (explicit coordinator/num_processes/process_id),
  as environment (``FLINK_ML_TPU_COORDINATOR`` et al. — what the launcher
  sets), or as a no-op in a plain single-process run. Composes with
  ``mesh.init_distributed`` (the probe layer) rather than replacing it.
- :func:`build_mesh` — the global mesh. Multi-process runtimes get a
  ``(dcn, data)`` mesh with the process axis OUTERMOST (devices grouped
  by owning process), so the inter-process fabric is an explicit named
  axis: the hierarchical reduce (collective.py) and the hybrid-mesh
  programs address it, and ``data_axes(mesh)`` returns ``("dcn",
  "data")`` so every existing fit shards and reduces over both axes with
  zero algorithm changes. Single-process runtimes get the plain flat
  mesh — ``build_mesh`` is safe to call unconditionally.
- :func:`launch` — the CI launcher: N CPU processes, each with
  ``--xla_force_host_platform_device_count=L`` local devices (the PR 6
  simulation precedent, now one mesh ACROSS processes instead of inside
  one), a free localhost coordinator port, and the env mapping below.
  ``python -m flink_ml_tpu.parallel.distributed -n 2 -d 4 -- prog.py``
  runs ``prog.py`` in every process; per-process trace/metrics artifacts
  land in one shared trace dir and merge at read time (the hostpool
  ``spans-*.jsonl`` idiom extended with process labels —
  observability/exporters.py).

Env mapping (set by the launcher, readable by any entry point):

======================================  =====================================
``FLINK_ML_TPU_COORDINATOR``            coordinator ``host:port``
``FLINK_ML_TPU_NUM_PROCESSES``          total process count
``FLINK_ML_TPU_PROCESS_ID``             this process's index (0-based)
``FLINK_ML_TPU_LOCAL_DEVICES``          simulated local device count (CPU)
======================================  =====================================
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence

#: env mapping (docs/distributed.md "Multi-process meshes")
COORDINATOR_ENV = "FLINK_ML_TPU_COORDINATOR"
NUM_PROCESSES_ENV = "FLINK_ML_TPU_NUM_PROCESSES"
PROCESS_ID_ENV = "FLINK_ML_TPU_PROCESS_ID"
LOCAL_DEVICES_ENV = "FLINK_ML_TPU_LOCAL_DEVICES"

__all__ = [
    "COORDINATOR_ENV", "NUM_PROCESSES_ENV", "PROCESS_ID_ENV",
    "LOCAL_DEVICES_ENV", "init_distributed", "init_from_env",
    "process_count", "process_index", "process_label", "build_mesh",
    "launch", "main",
]


def _env_int(name: str) -> Optional[int]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "%s=%r is not an integer; ignoring it", name, raw)
        return None


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_devices: Optional[int] = None,
                     **kwargs) -> bool:
    """Join (or confirm) the multi-process JAX runtime. Idempotent: an
    already-joined runtime, a single-process configuration, and a repeat
    call are all safe no-ops. Returns True when the process is part of a
    live multi-process runtime afterwards.

    Arguments default to the env mapping above (what :func:`launch`
    sets), so entry points call ``init_distributed()`` unconditionally —
    exactly like ``mesh.init_distributed``, which this wraps: the probe,
    the already-initialized check, and the auto-detection fallback all
    live there; this layer adds the env mapping, the simulated
    local-device count and the CPU cross-process transport.

    ``local_devices`` (or ``FLINK_ML_TPU_LOCAL_DEVICES``) forces that
    many host-platform devices per process — only honored when jax has
    not initialized its backends yet (the launcher sets it in the child
    env, before the child imports jax, which is the supported order).
    """
    if coordinator is None:
        coordinator = os.environ.get(COORDINATOR_ENV) or None
    if num_processes is None:
        num_processes = _env_int(NUM_PROCESSES_ENV)
    if process_id is None:
        process_id = _env_int(PROCESS_ID_ENV)
    if local_devices is None:
        local_devices = _env_int(LOCAL_DEVICES_ENV)

    if local_devices and "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{int(local_devices)}").strip()

    if coordinator is None and num_processes is None:
        # nothing configured: stay single-process without touching the
        # auto-detection path (mesh.init_distributed would probe cluster
        # metadata; unconfigured library users should not pay that)
        return False

    import jax

    if coordinator is not None and (num_processes or 1) > 1:
        # multi-process CPU needs a cross-process collective transport;
        # gloo ships with jaxlib and this must be set before backend init
        # (harmless + ignored on TPU runtimes, where ICI/DCN is native)
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover — option absent on this line
            pass

    from flink_ml_tpu.observability import profiling
    from flink_ml_tpu.parallel import mesh as _mesh

    # the distributed-init rung of the boot ladder (ml.boot
    # phaseMs{phase="distributed-init"}, observability/profiling.py)
    with profiling.boot_phase("distributed-init"):
        return _mesh.init_distributed(coordinator_address=coordinator,
                                      num_processes=num_processes,
                                      process_id=process_id, **kwargs)


def init_from_env() -> bool:
    """:func:`init_distributed` with every argument from the env mapping
    — the one-liner for scripts launched by :func:`launch`."""
    return init_distributed()


def _jax_if_loaded():
    """The jax module when something already imported it, else None —
    artifact-labeling helpers must never be the thing that initializes a
    backend (exporters run in the trace CLI too)."""
    return sys.modules.get("jax")


def process_count() -> int:
    """Total processes in the runtime: the env mapping when the
    launcher set it (authoritative even before jax initializes — a
    child must label its artifacts correctly from the first span), else
    jax's count when jax is already loaded, else 1."""
    env = _env_int(NUM_PROCESSES_ENV)
    if env is not None:
        return env
    jax = _jax_if_loaded()
    if jax is not None:
        try:
            return int(jax.process_count())
        except Exception:
            pass
    return 1


def process_index() -> int:
    """This process's 0-based index (same sources as
    :func:`process_count`)."""
    env = _env_int(PROCESS_ID_ENV)
    if env is not None:
        return env
    jax = _jax_if_loaded()
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    return 0


def process_label() -> Optional[int]:
    """The index to label artifacts with, or None in a single-process
    runtime — the seam tracing/exporters use to name ``spans-p<k>-*``
    files and stamp ``process=`` onto records: two hosts can share a
    pid, so pid-only artifact names silently collide when a trace dir is
    shared across processes."""
    if process_count() > 1:
        return process_index()
    return None


def build_mesh(local_axis: Optional[int] = None):
    """The global mesh for this runtime.

    Multi-process: a ``(dcn, data)`` mesh — the process axis (named
    ``DCN_AXIS``: it IS the slow inter-host fabric) outermost with one
    row per process, devices grouped by their owning process in
    process-index order, the fast intra-process axis inside. Existing
    programs consume it through ``data_axes``/``data_pspec`` exactly
    like a hybrid multi-slice mesh, and the hierarchical reduce
    (collective.py) uses the axis split to keep the heavy legs local.

    Single-process: the plain flat data mesh (``create_mesh()``), so
    callers invoke this unconditionally.

    ``local_axis`` overrides the per-process device count (must divide
    evenly); default is every process's full local complement.
    """
    import numpy as np

    import jax

    from flink_ml_tpu.observability import profiling
    from flink_ml_tpu.parallel.mesh import (
        DATA_AXIS, DCN_AXIS, create_mesh)

    # the mesh-build rung of the boot ladder — on a cold runtime the
    # first jax.devices() call below pays backend/client init
    with profiling.boot_phase("mesh-build"):
        if jax.process_count() <= 1:
            return create_mesh()
        devices = sorted(
            jax.devices(),
            key=lambda d: (int(getattr(d, "process_index", 0)),
                           int(d.id)))
        n_proc = jax.process_count()
        per_proc = len(devices) // n_proc
        if local_axis is not None:
            if per_proc % int(local_axis):
                raise ValueError(
                    f"local_axis={local_axis} does not divide the "
                    f"{per_proc} devices each process contributes")
            per_proc = int(local_axis)
        arr = np.asarray(devices).reshape(n_proc, per_proc)
        from jax.sharding import Mesh

        return Mesh(arr, (DCN_AXIS, DATA_AXIS))


# -- the CI launcher ----------------------------------------------------------

def _free_port() -> int:
    import socket

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch(argv: Sequence[str], num_processes: int, local_devices: int = 1,
           env: Optional[dict] = None, timeout: float = 900.0,
           coordinator_port: Optional[int] = None,
           child_grace_s: float = 30.0) -> List[dict]:
    """Run ``argv`` as ``num_processes`` coordinated CPU processes.

    Each child gets the env mapping (coordinator on a free localhost
    port, its process id, the simulated local device count),
    ``JAX_PLATFORMS=cpu`` and the host-platform XLA flag — the child
    entry point just calls :func:`init_from_env` (or
    ``init_distributed()``) before building its mesh. Children run
    concurrently (they must: the distributed service blocks until every
    process joins); output is captured per process.

    Returns one record per process: ``{"process", "returncode",
    "exitOrder", "stdout", "stderr"}``, in process order —
    ``exitOrder`` is the poll-observed exit sequence (0 = first to
    exit, None when the launcher never saw it exit before draining),
    which lets an elastic driver name the FIRST signal death (the true
    victim) rather than a grace-killed survivor. Raises nothing on a child
    failure — the caller owns the verdict (the bench gates on it) — but
    a TimeoutExpired kills the whole group (a wedged coordinator must
    not hang CI forever).

    ``child_grace_s`` is the per-child liveness deadline: once ANY
    child exits nonzero, its surviving siblings get this many seconds
    to finish before the group is killed and the records (with the real
    failing rc) are returned. Without it a crashed child's exit code
    was held hostage by a wedged sibling until the FULL ``timeout`` —
    a lost worker wedges the whole lockstep group mid-collective, so
    that was the common case, not the corner. The killed survivors
    report their signal rc (e.g. ``-9``); the caller still owns the
    verdict."""
    port = coordinator_port or _free_port()
    base = dict(os.environ)
    base.update(env or {})
    base["JAX_PLATFORMS"] = "cpu"
    base[COORDINATOR_ENV] = f"127.0.0.1:{port}"
    base[NUM_PROCESSES_ENV] = str(int(num_processes))
    base[LOCAL_DEVICES_ENV] = str(int(local_devices))
    # causal stitching (docs/observability.md "Causal tracing"): every
    # child inherits ONE trace context through the env — the launcher's
    # current span when it has one, else a fresh trace-only context —
    # so each process's root spans join the SAME trace and the merged
    # spans-p<k>-*.jsonl artifacts stitch into one causal run instead
    # of N disconnected per-process traces. An explicitly provided
    # parent (env= or the surrounding environment) wins.
    from flink_ml_tpu.observability import tracing

    if not base.get(tracing.TRACE_PARENT_ENV):
        ctx = (tracing.tracer.current_context()
               or tracing.fresh_context())
        base[tracing.TRACE_PARENT_ENV] = ctx.to_header()
    flags = base.get("XLA_FLAGS", "")
    # strip any inherited device-count flag: the child's count must be
    # the launcher's, not the parent test env's
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    base["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{int(local_devices)}").strip()

    procs = []
    for pid in range(int(num_processes)):
        child_env = dict(base)
        child_env[PROCESS_ID_ENV] = str(pid)
        procs.append(subprocess.Popen(
            list(argv), env=child_env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))

    # drain EVERY child concurrently: the children run one collective
    # program in lockstep, so a single child blocked on a full stdout
    # pipe (communicate() drains sequentially) would stall the whole
    # group mid-psum until the timeout killed it
    collected = [None] * len(procs)

    def drain(i, proc):
        collected[i] = proc.communicate()

    threads = [threading.Thread(target=drain, args=(i, p), daemon=True)
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    grace_deadline = None  # armed by the first nonzero child exit
    exit_order = [None] * len(procs)  # poll-observed exit sequence
    exit_seq = 0
    while True:
        alive = [t for t in threads if t.is_alive()]
        if not alive:
            break
        now = time.monotonic()
        for i, p in enumerate(procs):
            if exit_order[i] is None and p.poll() is not None:
                exit_order[i] = exit_seq
                exit_seq += 1
        if now >= deadline:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            for t in threads:
                t.join(10.0)
            raise subprocess.TimeoutExpired(list(argv), timeout)
        if grace_deadline is None:
            if any(p.poll() is not None and p.returncode != 0
                   for p in procs):
                grace_deadline = now + max(float(child_grace_s), 0.0)
        elif now >= grace_deadline:
            # per-child liveness deadline tripped: a crashed child's rc
            # must not be held hostage by a wedged sibling until the
            # full group timeout — kill the survivors and report
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            for t in threads:
                t.join(10.0)
            break
        alive[0].join(0.05)
    records = []
    for pid, (proc, got) in enumerate(zip(procs, collected)):
        out, err = got if got is not None else ("", "")
        records.append({"process": pid, "returncode": proc.returncode,
                        "exitOrder": exit_order[pid],
                        "stdout": out, "stderr": err})
    return records


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m flink_ml_tpu.parallel.distributed -n 2 -d 4 --
    script.py args...`` — exit 0 iff every process exited 0; each
    child's output is replayed prefixed with its process index."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="flink_ml_tpu.parallel.distributed",
        description="multi-process CPU launcher (docs/distributed.md)")
    parser.add_argument("-n", "--processes", type=int, default=2)
    parser.add_argument("-d", "--local-devices", type=int, default=1)
    parser.add_argument("--timeout", type=float, default=900.0)
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="program to run (prefix with -- to separate)")
    args = parser.parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        # only the FIRST "--" separates launcher args from the command;
        # later ones belong to the child program's own argv
        command = command[1:]
    if not command:
        parser.error("no command given")
    if command[0].endswith(".py"):
        command = [sys.executable] + command
    results = launch(command, args.processes, args.local_devices,
                     timeout=args.timeout)
    rc = 0
    for rec in results:
        for stream, text in (("out", rec["stdout"]),
                             ("err", rec["stderr"])):
            for line in (text or "").splitlines():
                print(f"[p{rec['process']}:{stream}] {line}",
                      file=sys.stderr if stream == "err" else sys.stdout)
        rc = rc or rec["returncode"]
    return rc


if __name__ == "__main__":
    sys.exit(main())
