"""Collectives + sharding helpers.

Ref parity (flink-ml-core):
- ``all_reduce_sum`` ≙ AllReduceImpl.allReduceSum (AllReduceImpl.java:71-102):
  the reference hand-rolls reduce-scatter + all-gather out of 4 KB chunks and
  TCP shuffles; here it is a single XLA ``psum`` lowered to an ICI all-reduce.
- ``broadcast_from`` / ``replicate`` ≙ BroadcastUtils.withBroadcastStream
  (BroadcastUtils.java:65): broadcast variables become replicated shardings —
  XLA inserts the all-gather; no caching/blocking operator is needed.
- ``termination_vote`` ≙ SharedProgressAligner.EpochStatus.isTerminated
  (SharedProgressAligner.java:277-292): the coordinator's "all subtasks
  reported, zero records this round" vote becomes a psum of per-shard counts.

The in-axis functions are for use inside ``shard_map``/``pjit`` bodies; the
host-level helpers (``shard_batch``) place host arrays onto the mesh.

Telemetry (docs/observability.md "Distributed telemetry"): the in-axis
collectives are the named seams of every SPMD program, so each records
its payload into ``ml.collective`` at TRACE time — op count and payload
bytes labeled ``{op=,axis=,devices=}``. That is per *compiled program
structure*, not per executed step (the compiled body contains no Python;
JL107's whole point), which is exactly the right meaning here: it
answers "what collectives does this program issue, over which axes, at
what sizes". Runtime timing comes from the host-level helpers below,
which ARE host boundaries: each records an ``ml.collective
opMs{op=,devices=}`` histogram and, when tracing is armed, a
``collective.host`` span.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_ml_tpu.parallel.mesh import DATA_AXIS
from flink_ml_tpu.parallel.shardmap import axis_size  # noqa: F401 — re-export

#: byte-shaped histogram bounds for collective payloads (the default
#: buckets are latency-shaped)
PAYLOAD_BUCKETS = (256.0, 4096.0, 65536.0, 1048576.0, 16777216.0,
                   268435456.0, 4294967296.0)

#: env var: force the hierarchical two-level reduce on ("1") or off
#: ("0"); unset/other = auto (on when the runtime spans processes).
#: Read at program TRACE time: already-compiled (lru-cached) fit
#: programs keep the structure they were traced with, so set it before
#: the first fit — the multihost bench runs each mode in its own
#: process for exactly this reason.
HIER_ENV = "FLINK_ML_TPU_HIER_REDUCE"


def _collective_group():
    from flink_ml_tpu.common.metrics import ML_GROUP, metrics

    return metrics.group(ML_GROUP, "collective")


def _payload_bytes(x) -> int:
    """Static per-shard payload of a traced operand (shape/dtype are
    trace-time constants even when the values are tracers)."""
    shape = jnp.shape(x)
    return int(np.prod(shape, dtype=np.int64)) * jnp.result_type(x).itemsize


def _note_traced(op: str, x, axis_name) -> None:
    """Trace-time accounting of one in-axis collective site: op count +
    payload bytes into ``ml.collective``, and an instant event on the
    open span (the fit/transform span is open while its program traces).
    Never raises — telemetry must not sink a trace."""
    try:
        axes = ((axis_name,) if isinstance(axis_name, str)
                else tuple(axis_name))
        devices = axis_size(axes[0]) if len(axes) == 1 else int(
            np.prod([axis_size(a) for a in axes]))
        nbytes = _payload_bytes(x)
        labels = {"op": op, "axis": ",".join(str(a) for a in axes),
                  "devices": str(devices)}
        group = _collective_group()
        group.counter("tracedOps", labels=labels)
        group.histogram("payloadBytes", buckets=PAYLOAD_BUCKETS,
                        labels=labels).observe(nbytes)
        from flink_ml_tpu.observability import tracing

        if tracing.tracer.current() is not None:
            tracing.tracer.event("ml.collective.traced", op=op,
                                 axis=labels["axis"], devices=devices,
                                 payload_bytes=nbytes)
    except Exception:
        pass


def _note_level(op: str, level: str, x, axes) -> None:
    """Trace-time per-LEVEL payload accounting of the two-level reduce
    topology (``ml.collective levelPayloadBytes{op=,level=,axis=}``):
    ``level="inter"`` bytes cross the slow outer fabric (DCN / the
    inter-process network), ``level="intra"`` bytes stay on the fast
    local axis. The multihost bench gates on the inter sum — the
    hierarchical decomposition must record strictly fewer inter bytes
    than the flat psum it replaces. Never raises."""
    try:
        labels = {"op": op, "level": level,
                  "axis": ",".join(str(a) for a in axes)}
        group = _collective_group()
        group.counter("levelOps", labels=labels)
        group.histogram("levelPayloadBytes", buckets=PAYLOAD_BUCKETS,
                        labels=labels).observe(_payload_bytes(x))
    except Exception:
        pass


def hier_reduce_forced() -> Optional[bool]:
    """The ``FLINK_ML_TPU_HIER_REDUCE`` override: True/False when the
    env forces the hierarchical or flat path, None for auto."""
    raw = os.environ.get(HIER_ENV, "").strip().lower()
    if raw in ("1", "true", "on", "yes"):
        return True
    if raw in ("0", "false", "off", "no"):
        return False
    return None


def _hier_active(axes) -> bool:
    """Whether :func:`all_reduce_sum` over these axes decomposes into
    the two-level reduce: needs a (slow, fast) axis split to exploit,
    then the env override decides, else auto — hierarchical exactly when
    the runtime spans processes (a single-process hybrid mesh's "dcn"
    axis rides the same ICI as its data axis, so the flat psum is
    already optimal there; tests force the path via the env)."""
    if len(axes) < 2:
        return False
    forced = hier_reduce_forced()
    if forced is not None:
        return forced
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def _hier_psum(x, axes):
    """The two-level tree reduce (arXiv:1903.06701 — reduce near the
    data, cross the slow fabric at 1/N width): reduce_scatter over the
    fast inner axes (each local shard owns a ``1/local_N`` slice of the
    local sum), all-reduce the slices over the slow outer axis — the
    ONLY inter-level traffic, ``1/local_N`` of the flat psum's payload —
    then all_gather the fresh slices back over the fast axes. Equals the
    flat psum up to float reassociation (pinned in
    tests/test_multiprocess.py)."""
    outer, inner = axes[0], axes[1:]
    inner_ax = inner[0] if len(inner) == 1 else inner
    local_n = int(np.prod([axis_size(a) for a in inner]))
    if local_n <= 1 or jnp.ndim(x) == 0:
        # no fast axis to scatter over / a scalar: the split degenerates
        _note_traced("psum", x, axes)
        _note_level("psum", "inter", x, axes)
        return jax.lax.psum(x, axes)
    n0 = x.shape[0]
    pad = (-n0) % local_n
    xp = (jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
          if pad else x)
    _note_traced("psum_scatter", xp, inner_ax)
    _note_level("reduce_scatter", "intra", xp, axes)
    part = jax.lax.psum_scatter(xp, inner_ax, scatter_dimension=0,
                                tiled=True)
    _note_traced("psum", part, outer)
    _note_level("psum", "inter", part, axes)
    part = jax.lax.psum(part, outer)
    _note_traced("all_gather", part, inner_ax)
    _note_level("all_gather", "intra", part, axes)
    full = jax.lax.all_gather(part, inner_ax, axis=0, tiled=True)
    return full[:n0] if pad else full


# -- in-axis collectives (inside shard_map / with named axes) ---------------

def all_reduce_sum(x, axis_name=DATA_AXIS):
    """Sum across the mesh axis (ref: AllReduceImpl.java:54 allReduceSum).

    ``axis_name`` may be a tuple of axes — e.g. ``("dcn", "data")`` on a
    hybrid multi-slice or multi-process mesh. When the runtime spans
    processes (or ``FLINK_ML_TPU_HIER_REDUCE=1`` forces it), the tuple
    form lowers through the explicit two-level tree reduce
    (:func:`_hier_psum`) so the inter-process fabric carries
    ``1/local_N`` of the payload; otherwise one fused ``psum`` (XLA
    decomposes it over ICI/DCN on real hardware).
    """
    axes = ((axis_name,) if isinstance(axis_name, str)
            else tuple(axis_name))
    if _hier_active(axes):
        return _hier_psum(x, axes)
    _note_traced("psum", x, axis_name)
    if len(axes) > 1:
        # flat reduce over a mesh with a slow outer axis: the FULL
        # payload crosses the inter level — the comparison baseline the
        # hierarchical path's accounting is gated against
        _note_level("psum", "inter", x, axes)
    return jax.lax.psum(x, axis_name)


def renormalized_sum(x, include, axis_name=DATA_AXIS):
    """Partial-participation all-reduce (JiT aggregation,
    arXiv:2208.09740): every shard still executes the collective (SPMD
    lockstep — a shard cannot skip a psum), but a shard whose ``include``
    is 0 contributes zero, and the sum is rescaled by
    ``n_shards / participants`` so the expected update stays unbiased —
    dropping shard k for one round scales the survivors up instead of
    silently shrinking the step. ``include`` is this shard's 0/1 scalar,
    decided on HOST from the *previous* round's readiness timings
    (parallel/elastic.py:round_participation — the actuator guarantees
    at least one participant; the ``maximum(…, 1)`` below only keeps a
    pathological all-dropped round finite). With every shard included
    the result is bit-identical to :func:`all_reduce_sum` (``include``
    multiplies by exactly 1 and the scale is exactly 1)."""
    axes = ((axis_name,) if isinstance(axis_name, str)
            else tuple(axis_name))
    n_shards = int(np.prod([axis_size(a) for a in axes]))
    dtype = jnp.result_type(x)
    if not jnp.issubdtype(dtype, jnp.inexact):
        dtype = jnp.float32
    inc = jnp.asarray(include).astype(dtype)
    total = all_reduce_sum(x * inc, axis_name)
    participants = all_reduce_sum(inc, axis_name)
    scale = n_shards / jnp.maximum(participants, jnp.asarray(1, dtype))
    return total * scale


def all_reduce_mean(x, axis_name: str = DATA_AXIS):
    _note_traced("pmean", x, axis_name)
    return jax.lax.pmean(x, axis_name)


def all_reduce_max(x, axis_name: str = DATA_AXIS):
    _note_traced("pmax", x, axis_name)
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name: str = DATA_AXIS, axis: int = 0, tiled: bool = True):
    _note_traced("all_gather", x, axis_name)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name=DATA_AXIS):
    """Sum across the mesh axis, each shard keeping only its own
    ``1/N`` slice of dim 0 — the first half of the cross-replica sharded
    weight update (arXiv:2004.13336): per-replica update FLOPs and
    optimizer-state traffic scale down with the mesh instead of every
    replica reducing (and then updating) the full vector. Dim 0 must be
    a multiple of the total shard count (pad with zeros — a zero
    gradient is inert through every update rule in this framework); the
    slice order matches :func:`shard_index`, so ``all_gather`` of the
    per-shard slices reconstructs the full reduction.

    ``axis_name`` may be a tuple of axes (hybrid dcn×data meshes); XLA
    then scatters over the flattened axis order, keeping the heavy leg
    on ICI like the hierarchical all-reduce.
    """
    _note_traced("psum_scatter", x, axis_name)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=0,
                                tiled=True)


def shard_index(axis_name=DATA_AXIS):
    """This shard's position along the (possibly tuple of) data axes —
    the named seam over ``jax.lax.axis_index`` (jaxlint JL108 keeps raw
    index queries out of fit programs). Matches the slice order of
    :func:`reduce_scatter`/:func:`all_gather`."""
    return jax.lax.axis_index(axis_name)


def broadcast_from(x, src: int = 0, axis_name: str = DATA_AXIS):
    """Broadcast shard ``src``'s value to all shards (ref: .broadcast() edges).

    Implemented as a masked psum so it stays a single ICI collective.
    """
    _note_traced("broadcast", x, axis_name)
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def termination_vote(local_count, axis_name: str = DATA_AXIS):
    """True iff the global count is zero — the reference coordinator's
    termination rule (SharedProgressAligner.java:277-292) as one psum."""
    _note_traced("termination_vote", local_count, axis_name)
    total = jax.lax.psum(local_count, axis_name)
    return total == 0


def local_valid_mask(axes, local_n: int, n_valid, dtype=jnp.float32):
    """Inside shard_map: 1 for rows whose GLOBAL index is < ``n_valid`` —
    the padding mask for ``shard_batch``'s zero-padded batches, derived
    on-device from one scalar instead of shipping an (n,) mask array."""
    shard = jax.lax.axis_index(axes)
    global_idx = shard * local_n + jnp.arange(local_n)
    return (global_idx < n_valid).astype(dtype)


# -- host-level placement ----------------------------------------------------

class _HostOp:
    """Time one host-boundary collective/placement op into
    ``ml.collective opMs{op=,devices=}`` (+ payload bytes), with a
    ``collective.host`` span when tracing is armed. Also the seam that
    records the mesh topology: a host placement op is proof the mesh is
    in use."""

    __slots__ = ("op", "mesh", "nbytes", "_t0", "_span_cm", "_span")

    def __init__(self, op: str, mesh: Mesh, nbytes: int = 0):
        self.op = op
        self.mesh = mesh
        self.nbytes = int(nbytes)
        self._span_cm = None
        self._span = None

    def __enter__(self):
        from flink_ml_tpu.observability import meshstats, tracing

        try:  # an unwritable trace dir must not sink the data path
            meshstats.ensure_mesh_recorded(self.mesh)
        except Exception:
            pass
        if tracing.tracer.enabled:
            self._span_cm = tracing.tracer.span(
                "collective.host", op=self.op,
                devices=self.mesh.devices.size,
                payload_bytes=self.nbytes)
            self._span = self._span_cm.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        ms = (time.perf_counter() - self._t0) * 1000.0
        labels = {"op": self.op, "devices": str(self.mesh.devices.size)}
        group = _collective_group()
        group.histogram("opMs", labels=labels).observe(ms)
        if self.nbytes:
            group.histogram("payloadBytes", buckets=PAYLOAD_BUCKETS,
                            labels=labels).observe(self.nbytes)
        if self._span_cm is not None:
            self._span_cm.__exit__(*exc)
        return False


def row_major_format(sharding, ndim: int):
    """The sharding pinned to a ROW-MAJOR device layout. Every producer of
    batch-dim-sharded device arrays (datagen, the prepare programs,
    device_put placements) emits this layout so consumers never pay a
    relayout: the r3 LR trace showed a 14.4 ms full-input copy
    (f32[10M,100]{1,0} copy of a {0,1} parameter) purely because the
    datagen program's compiler-chosen output layout was column-major
    while the fit wanted row-major. Random generation has no layout
    preference, so pinning the producer is free.

    API skew: the pair is spelled ``Format(Layout(major_to_minor),
    sharding)`` on new JAX and ``Layout(DeviceLocalLayout(major_to_minor),
    sharding)`` on the 0.4.x line — same object either way."""
    try:
        from jax.experimental.layout import Format, Layout

        return Format(Layout(major_to_minor=tuple(range(ndim))), sharding)
    except ImportError:
        from jax.experimental.layout import DeviceLocalLayout, Layout

        return Layout(DeviceLocalLayout(major_to_minor=tuple(range(ndim))),
                      sharding)


def _dim0_layout(mesh: Mesh, axis_name, ndim: int):
    """The shared dim-0-sharded placement recipe: (shard count, sharding)
    for an ndim-rank array row-sharded over the given data axes."""
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    n_shards = int(np.prod([mesh.shape[a] for a in axes]))
    dim0 = axes[0] if len(axes) == 1 else axes
    sharding = NamedSharding(mesh, P(dim0, *([None] * (ndim - 1))))
    return n_shards, sharding


def shard_batch(mesh: Mesh, array, axis_name: str = DATA_AXIS):
    """Place a host array on the mesh, sharded on dim 0 (the batch dim).

    Equivalent of the reference scattering a global batch over subtasks
    (DataStreamUtils.generateBatchData / partitionCustom). Pads dim 0 up to a
    multiple of the axis size with zeros; callers track true counts (padding
    contributes zero weight to every reduction in this framework).
    Returns (device_array, original_length).
    """
    array = np.asarray(array)
    n_shards, sharding = _dim0_layout(mesh, axis_name, array.ndim)
    n = array.shape[0]
    rem = (-n) % n_shards
    if rem:
        pad = np.zeros((rem,) + array.shape[1:], dtype=array.dtype)
        array = np.concatenate([array, pad], axis=0)
    with _HostOp("shard_batch", mesh, array.nbytes):
        return jax.device_put(array, sharding), n


def replicate(mesh: Mesh, tree):
    """Replicate a pytree across the whole mesh (broadcast-variable parity)."""
    sharding = NamedSharding(mesh, P())
    nbytes = sum(getattr(leaf, "nbytes", 0)
                 for leaf in jax.tree_util.tree_leaves(tree))
    with _HostOp("replicate", mesh, nbytes):
        return jax.device_put(tree, sharding)


@functools.lru_cache(maxsize=128)
def _prepare_program(rem: int, dtype_name: str, sharding, ndim: int):
    """Compiled cast+pad+reshard for device-resident inputs — keyed so
    repeated fits at the same shapes reuse one program. Output layout
    pinned row-major (see row_major_format)."""
    dtype = jnp.dtype(dtype_name)

    def prep(a):
        a = a.astype(dtype)
        if rem:
            a = jnp.pad(a, ((0, rem),) + ((0, 0),) * (a.ndim - 1))
        return a

    return jax.jit(prep, out_shardings=row_major_format(sharding, ndim))


def ensure_on_mesh(mesh: Mesh, array, axis_name=DATA_AXIS, dtype=None):
    """Device-aware :func:`shard_batch`: a host array is cast and placed via
    ``shard_batch``; an already-device ``jax.Array`` is cast/padded/resharded
    ON device (no host round-trip). This is the residency contract that makes
    datagen→fit chains and repeated fits transfer-free — the data-cache role
    of the reference (ListStateWithCache.java:54) where the cached shard
    simply stays in HBM. Returns (device_array, original_row_count)."""
    if not isinstance(array, jax.Array):
        arr = np.asarray(array)
        if dtype is not None and arr.dtype != np.dtype(dtype):
            arr = arr.astype(dtype)
        return shard_batch(mesh, arr, axis_name)
    n = array.shape[0]
    n_shards, sharding = _dim0_layout(mesh, axis_name, array.ndim)
    rem = (-n) % n_shards
    want = jnp.dtype(dtype) if dtype is not None else array.dtype
    with _HostOp("ensure_on_mesh", mesh, array.nbytes):
        if rem == 0 and array.dtype == want:
            # device_put with a matching placement is a no-op; a mismatched
            # one is a device-to-device reshard/relayout — still no PCIe leg,
            # and normalizing the layout HERE (once) spares every consumer
            # program its own full-input relayout copy (r3 trace: 14.4 ms)
            return jax.device_put(
                array, row_major_format(sharding, array.ndim)), n
        return _prepare_program(rem, want.name, sharding,
                                array.ndim)(array), n


@functools.lru_cache(maxsize=128)
def _ones_program(padded: int, dtype_name: str, sharding):
    dtype = jnp.dtype(dtype_name)

    def make(n):
        return (jnp.arange(padded) < n).astype(dtype)

    return jax.jit(make, out_shardings=sharding)


def ones_on_mesh(mesh: Mesh, n: int, axis_name=DATA_AXIS,
                 dtype=jnp.float32):
    """A length-``n`` ones vector (zero-padded to the shard multiple),
    generated directly sharded ON device — the default sample-weight column
    without a host allocation or transfer. ``n`` is a traced argument, so
    one compiled program per padded length serves all true counts."""
    n_shards, sharding = _dim0_layout(mesh, axis_name, 1)
    padded = n + ((-n) % n_shards)
    return _ones_program(padded, jnp.dtype(dtype).name, sharding)(
        jnp.int32(n))
