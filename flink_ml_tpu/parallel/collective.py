"""Collectives + sharding helpers.

Ref parity (flink-ml-core):
- ``all_reduce_sum`` ≙ AllReduceImpl.allReduceSum (AllReduceImpl.java:71-102):
  the reference hand-rolls reduce-scatter + all-gather out of 4 KB chunks and
  TCP shuffles; here it is a single XLA ``psum`` lowered to an ICI all-reduce.
- ``broadcast_from`` / ``replicate`` ≙ BroadcastUtils.withBroadcastStream
  (BroadcastUtils.java:65): broadcast variables become replicated shardings —
  XLA inserts the all-gather; no caching/blocking operator is needed.
- ``termination_vote`` ≙ SharedProgressAligner.EpochStatus.isTerminated
  (SharedProgressAligner.java:277-292): the coordinator's "all subtasks
  reported, zero records this round" vote becomes a psum of per-shard counts.

The in-axis functions are for use inside ``shard_map``/``pjit`` bodies; the
host-level helpers (``shard_batch``) place host arrays onto the mesh.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flink_ml_tpu.parallel.mesh import DATA_AXIS


# -- in-axis collectives (inside shard_map / with named axes) ---------------

def all_reduce_sum(x, axis_name=DATA_AXIS):
    """Sum across the mesh axis (ref: AllReduceImpl.java:54 allReduceSum).

    ``axis_name`` may be a tuple of axes — e.g. ``("dcn", "data")`` on a
    hybrid multi-slice mesh — in which case XLA emits the hierarchical
    all-reduce (in-slice over ICI, one cross-slice DCN exchange).
    """
    return jax.lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str = DATA_AXIS):
    return jax.lax.pmean(x, axis_name)


def all_reduce_max(x, axis_name: str = DATA_AXIS):
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name: str = DATA_AXIS, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def broadcast_from(x, src: int = 0, axis_name: str = DATA_AXIS):
    """Broadcast shard ``src``'s value to all shards (ref: .broadcast() edges).

    Implemented as a masked psum so it stays a single ICI collective.
    """
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def termination_vote(local_count, axis_name: str = DATA_AXIS):
    """True iff the global count is zero — the reference coordinator's
    termination rule (SharedProgressAligner.java:277-292) as one psum."""
    total = jax.lax.psum(local_count, axis_name)
    return total == 0


def local_valid_mask(axes, local_n: int, n_valid, dtype=jnp.float32):
    """Inside shard_map: 1 for rows whose GLOBAL index is < ``n_valid`` —
    the padding mask for ``shard_batch``'s zero-padded batches, derived
    on-device from one scalar instead of shipping an (n,) mask array."""
    shard = jax.lax.axis_index(axes)
    global_idx = shard * local_n + jnp.arange(local_n)
    return (global_idx < n_valid).astype(dtype)


# -- host-level placement ----------------------------------------------------

def shard_batch(mesh: Mesh, array, axis_name: str = DATA_AXIS):
    """Place a host array on the mesh, sharded on dim 0 (the batch dim).

    Equivalent of the reference scattering a global batch over subtasks
    (DataStreamUtils.generateBatchData / partitionCustom). Pads dim 0 up to a
    multiple of the axis size with zeros; callers track true counts (padding
    contributes zero weight to every reduction in this framework).
    Returns (device_array, original_length).
    """
    array = np.asarray(array)
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    n_shards = int(np.prod([mesh.shape[a] for a in axes]))
    n = array.shape[0]
    rem = (-n) % n_shards
    if rem:
        pad = np.zeros((rem,) + array.shape[1:], dtype=array.dtype)
        array = np.concatenate([array, pad], axis=0)
    dim0 = axes[0] if len(axes) == 1 else axes
    spec = P(dim0, *([None] * (array.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.device_put(array, sharding), n


def replicate(mesh: Mesh, tree):
    """Replicate a pytree across the whole mesh (broadcast-variable parity)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)
