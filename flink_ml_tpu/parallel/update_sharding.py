"""Cross-replica sharding of the weight update + optimizer state.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336): in plain data parallelism every replica
all-reduces the full gradient and then applies the identical full update
— per-replica update FLOPs and optimizer-state memory do NOT scale down
with the mesh. The sharded formulation splits the update across the
replicas instead::

        per-shard partial gradient  g_i            (full length, padded)
                   │ reduce_scatter                 1/N slice per replica
                   ▼
        g_slice ──▶ apply_fn(g_slice, param_slice, opt_state_slice)
                   │                │ opt-state slices STAY sharded
                   │ all_gather     ▼ (1/N memory per replica)
                   ▼
        fresh replicated params    new opt-state slices

Per-replica optimizer memory (FTRL's z/n accumulators, momentum) and
update FLOPs scale as ``1/N``; the wire cost is the same as the
all-reduce it replaces (reduce-scatter + all-gather IS the all-reduce,
split around the update). Built entirely from the named primitives in
``parallel/mapreduce.py`` so every leg records ``ml.collective``
accounting.

Enabling: the fit families (SGD programs, KMeans lloyd, FTRL) read
:func:`enabled` — set ``FLINK_ML_TPU_UPDATE_SHARDING=1``. Default off:
replicated and sharded fits agree only up to float reassociation (the
reduce-scatter sums in a different order than the fused psum), and the
replicated path is the long-standing numerics oracle. Parity is pinned
by tests/test_mapreduce.py at mesh sizes {1, 2, 8} and benchmarked by
scripts/mapreduce_bench.py (BENCH_mapreduce.json: per-replica
optimizer-state bytes must shrink ~1/N).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from flink_ml_tpu.parallel import mapreduce as mr

#: env var: arm the cross-replica sharded update in every fit family
ENV = "FLINK_ML_TPU_UPDATE_SHARDING"

__all__ = [
    "ENV", "enabled", "padded_len", "pad_leading", "owned_slice",
    "sharded_apply", "place_opt_state", "record_state_bytes",
    "last_state_bytes", "provenance",
]


def enabled() -> bool:
    """True when ``FLINK_ML_TPU_UPDATE_SHARDING`` arms the sharded
    update (accepted truthy spellings: 1/true/on/yes)."""
    return os.environ.get(ENV, "").strip().lower() in (
        "1", "true", "on", "yes")


def padded_len(n: int, n_shards: int) -> int:
    """``n`` rounded up to a multiple of the shard count — the dim-0
    length reduce-scatter needs. Zero-padding is inert through every
    update rule here (zero gradient → zero update; FTRL's
    soft-threshold keeps a zero coordinate exactly zero)."""
    n_shards = max(int(n_shards), 1)
    return int(n) + (-int(n)) % n_shards


def pad_leading(x, target: int):
    """``x`` zero-padded along dim 0 up to ``target`` (trace-safe: the
    pad width is a static Python int)."""
    import jax.numpy as jnp

    pad = int(target) - x.shape[0]
    if pad <= 0:
        return x
    return jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))


def owned_slice(x, axes=None):
    """Inside a map body: this replica's ``1/N`` slice of a replicated
    array (dim 0 must be a multiple of the shard count). The slice
    order matches :func:`mapreduce.reduce_scatter`, so the slice pairs
    with the scattered gradient it will be updated by."""
    import jax

    axes = axes if axes is not None else mr.DATA_AXIS
    n = mr.shard_count(axes)
    chunk = x.shape[0] // n
    start = mr.shard_index(axes) * chunk
    return jax.lax.dynamic_slice_in_dim(x, start, chunk, axis=0)


def sharded_apply(axes, grads, params, opt_state, apply_fn):
    """ONE cross-replica sharded update step, inside a map body.

    - ``grads``: pytree of per-shard partial gradients, full length with
      dim 0 padded to the shard multiple (:func:`padded_len`).
    - ``params``: pytree of REPLICATED parameter arrays (same padded
      dim 0) — each replica updates only its own slice.
    - ``opt_state``: pytree of already-SHARDED optimizer-state slices
      (each replica's ``1/N`` rows — FTRL z/n, momentum), or ``None``.
      They stay sharded: this is where the ``1/N`` memory comes from.
    - ``apply_fn(grad_slices, param_slices, opt_state) ->
      (new_param_slices, new_opt_state)`` — the update rule, applied to
      slices; must be elementwise/rowwise along dim 0 (every rule in
      this framework is).

    Returns ``(new_params, new_opt_state)`` with the parameters
    all-gathered back to replicated (the forward pass needs them whole)
    and the optimizer state still sharded.
    """
    import jax

    g = jax.tree_util.tree_map(lambda a: mr.reduce_scatter(a, axes), grads)
    p = jax.tree_util.tree_map(lambda a: owned_slice(a, axes), params)
    new_p, new_opt = apply_fn(g, p, opt_state)
    gathered = jax.tree_util.tree_map(
        lambda a: mr.all_gather(a, axes), new_p)
    return gathered, new_opt


def place_opt_state(mesh, tree, axes=None):
    """Host boundary: place full-length (padded) optimizer-state arrays
    onto the mesh sharded on dim 0 — each device holds only its ``1/N``
    slice. The map-body view under ``in_specs=P(data_pspec(mesh))`` is
    exactly the slice :func:`sharded_apply` carries."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from flink_ml_tpu.parallel.mesh import data_pspec

    spec0 = data_pspec(mesh)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(
            a, NamedSharding(mesh, P(spec0, *([None] * (a.ndim - 1))))),
        tree)


# -- accounting ---------------------------------------------------------------
#: last per-algo record: {"algo": {"bytesPerReplica", "sharded", "shards"}}
_last: dict = {}


def _leaf_bytes_per_replica(leaf) -> int:
    """MEASURED bytes one replica holds for ``leaf``: the first
    addressable shard's buffer size for a device array (full size when
    replicated, the 1/N slice when dim-0-sharded — so a regression that
    silently replicates 'sharded' state shows up as real bytes, not as
    wishful arithmetic), the whole array for a host leaf."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
        return int(shards[0].data.nbytes)
    return int(np.prod(getattr(leaf, "shape", np.shape(leaf)),
                       dtype=np.int64)
               * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize)


def record_state_bytes(algo: str, leaves, n_shards: int,
                       sharded: bool) -> int:
    """Record the per-replica bytes of a fit's update state (parameters
    + optimizer accumulators), MEASURED from the leaves' actual device
    buffers (:func:`_leaf_bytes_per_replica`) — replicated carries
    report their full size even when the sharded *update* ran (SGD
    coefficients and KMeans centroids all-gather back to replicated
    every step; only genuinely sharded state like FTRL's z/n slices
    shrinks). ``sharded`` labels whether the sharded update was armed.
    Lands as ``ml.update stateBytesPerReplica{algo=,sharded=}`` gauges
    and feeds benchmark provenance (``optStateBytesPerReplica`` on
    runner rows and the bench.py one-liner). Returns the byte count."""
    per_replica = int(sum(_leaf_bytes_per_replica(leaf)
                          for leaf in leaves))
    _last[algo] = {"bytesPerReplica": per_replica, "sharded": bool(sharded),
                   "shards": int(n_shards)}
    _last["__latest__"] = _last[algo]
    try:  # telemetry must never sink a fit
        from flink_ml_tpu.common.metrics import ML_GROUP, metrics

        grp = metrics.group(ML_GROUP, "update")
        labels = {"algo": algo, "sharded": str(int(sharded))}
        grp.gauge("stateBytesPerReplica", per_replica, labels=labels)
        grp.gauge("stateShards", n_shards if sharded else 1, labels=labels)
    except Exception:
        pass
    return per_replica


def last_state_bytes(algo: Optional[str] = None) -> Optional[int]:
    """The most recently recorded per-replica state bytes (for ``algo``,
    or of whichever fit recorded last) — benchmark provenance."""
    rec = _last.get(algo or "__latest__")
    return None if rec is None else rec["bytesPerReplica"]


def reset_last() -> None:
    """Forget the recorded state bytes. The benchmark runner calls this
    before each benchmark so a row only carries provenance from ITS own
    run — a transform-only row must not inherit the previous fit's
    ``optStateBytesPerReplica``."""
    _last.clear()


def provenance() -> dict:
    """Benchmark-row provenance: whether the sharded update is armed,
    the last recorded per-replica state bytes (absent if nothing has
    recorded yet), and the elastic-run fields (``elasticEvents`` /
    ``participationMin`` — parallel/elastic.py) that sit beside
    ``processCount`` on every row."""
    out = {"updateSharding": enabled()}
    b = last_state_bytes()
    if b is not None:
        out["optStateBytesPerReplica"] = b
    from flink_ml_tpu.parallel import elastic

    out.update(elastic.provenance())
    return out
