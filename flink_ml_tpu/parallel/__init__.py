"""Device mesh + collectives.

Ref parity: the reference's distributed substrate — chunked all-reduce
(flink-ml-core/.../common/datastream/AllReduceImpl.java:54), broadcast
variables (BroadcastUtils.java:65), and Flink's Netty shuffle transport —
replaced by a jax.sharding.Mesh with XLA collectives over ICI/DCN.
"""

from flink_ml_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    DCN_AXIS,
    MODEL_AXIS,
    create_hybrid_mesh,
    create_mesh,
    data_axes,
    data_pspec,
    data_shard_count,
    default_mesh,
    init_distributed,
    local_device_count,
    set_default_mesh,
)
from flink_ml_tpu.parallel.collective import (  # noqa: F401
    all_gather,
    all_reduce_max,
    all_reduce_mean,
    all_reduce_sum,
    broadcast_from,
    reduce_scatter,
    renormalized_sum,
    shard_batch,
    shard_index,
    replicate,
    termination_vote,
)
from flink_ml_tpu.parallel.shardmap import (  # noqa: F401
    axis_size,
    shard_map,
)
from flink_ml_tpu.parallel.mapreduce import (  # noqa: F401
    MapReduceProgram,
    map_shards,
)
from flink_ml_tpu.parallel import update_sharding  # noqa: F401
from flink_ml_tpu.parallel import distributed  # noqa: F401
from flink_ml_tpu.parallel.distributed import build_mesh  # noqa: F401
from flink_ml_tpu.parallel import elastic  # noqa: F401
