"""Elastic multi-process training: survive worker loss mid-fit.

The reference's iteration runtime rides Flink's supervised dataflow — a
lost TaskManager is rescheduled and the loop resumes from the aligned
checkpoint. Our multi-process runtime (distributed.py) is SPMD lockstep
instead: one process stops answering and every survivor wedges inside
the next inter-process psum, forever. This module turns that hang into
a supervised, observable recovery in three pieces:

**Detection** — a configurable collective deadline
(``FLINK_ML_TPU_COLLECTIVE_TIMEOUT_S``): the iteration drivers guard
their boundary fetches through :func:`guard_fetch`, which runs the
device sync on a watchdog thread and, past the deadline, consults the
per-process heartbeat files (beaten at every epoch boundary via
:func:`on_boundary`) to NAME the dead/stale process index — raising a
retryable :class:`~flink_ml_tpu.resilience.policy.WorkerLost` instead
of hanging. A timeout cannot fire *inside* XLA; the boundary fetch is
the host seam where the wedged reduce leg becomes observable.

**Recovery** — :func:`run_elastic` drives a launched fit through
``resilience.run_supervised``: when a child dies (SIGKILL, crash) or
hangs (the launcher's per-child liveness grace kills it), the parent
classifies the loss, shrinks the world by one, and relaunches the
survivors as a smaller ``(dcn, data)`` mesh. The children resume from
the newest v2 checkpoint manifest with the 1/N-sharded optimizer/
accumulator slices re-placed across the CHANGED N
(``CheckpointManager(repad_dim0=True)`` — the dim-0 pad of
``update_sharding.padded_len`` is inert zeros, so trim/re-extend is
lossless). Below ``min_processes`` the elastic budget is exhausted:
:class:`~flink_ml_tpu.resilience.policy.RestartsExhausted` with
``budget="elastic"``.

**Partial participation** — straggler-aware rounds (JiT Aggregation,
arXiv:2208.09740): :class:`RoundParticipation` turns the PR 6 skew
*detector* into an *actuator*. A shard whose previous-round readiness
exceeded ``FLINK_ML_TPU_ROUND_DEADLINE_MS`` is dropped for the round —
its ``include`` flag goes to 0 and ``collective.renormalized_sum``
rescales the survivors so the update stays unbiased — with staleness
bookkeeping that force-readmits a shard after ``max_staleness``
consecutive drops (a stale contribution must eventually rejoin, and a
round never drops every shard). SPMD lockstep means inclusion is
decided on HOST from the *previous* round's timings: a shard cannot
skip a psum it is already compiled into.

Telemetry rides ``ml.elastic``: ``participation{round=}`` gauges,
``droppedContributions{shard=}`` counters, ``workerLost`` /
``relaunches`` counters, and ``elastic.worker-lost`` /
``elastic.relaunch`` / ``elastic.participation`` trace events (surfaced
in the ``mltrace summary`` timeline). :func:`provenance` feeds
``elasticEvents`` / ``participationMin`` onto benchmark rows through
``update_sharding.provenance``.
"""

from __future__ import annotations

import functools
import os
import signal
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from flink_ml_tpu.resilience import faults
from flink_ml_tpu.resilience.policy import (
    RestartsExhausted,
    RetryPolicy,
    WorkerLost,
)

#: env mapping (docs/resilience.md "Elastic recovery")
COLLECTIVE_TIMEOUT_ENV = "FLINK_ML_TPU_COLLECTIVE_TIMEOUT_S"
ROUND_DEADLINE_ENV = "FLINK_ML_TPU_ROUND_DEADLINE_MS"
HEARTBEAT_DIR_ENV = "FLINK_ML_TPU_HEARTBEAT_DIR"
#: which process index the worker-loss/worker-hang chaos sites strike
#: (every process advances the SAME deterministic schedule; only the
#: victim acts, so exactly one worker dies per scheduled fault)
CHAOS_VICTIM_ENV = "FLINK_ML_TPU_CHAOS_VICTIM"
#: how long a worker-hang victim stalls (default: well past the
#: collective deadline, which is the point)
CHAOS_HANG_ENV = "FLINK_ML_TPU_CHAOS_HANG_S"
#: set by run_elastic in every child: 0-based attempt index, so a
#: worker can tell a first launch from a post-loss relaunch (the smoke
#: disarms its one scheduled kill on relaunch)
ATTEMPT_ENV = "FLINK_ML_TPU_ELASTIC_ATTEMPT"

__all__ = [
    "COLLECTIVE_TIMEOUT_ENV", "ROUND_DEADLINE_ENV", "HEARTBEAT_DIR_ENV",
    "CHAOS_VICTIM_ENV", "CHAOS_HANG_ENV", "ATTEMPT_ENV",
    "collective_timeout_s",
    "round_deadline_ms", "beat", "stale_processes", "on_boundary",
    "guard_fetch", "wait_with_deadline", "RoundParticipation",
    "repad_or_rescale",
    "ElasticCheckpointManager", "run_elastic", "provenance",
    "reset_stats",
]

#: fit-scoped elastic provenance (reset per benchmark run like
#: update_sharding.reset_last): how many elastic events fired and the
#: worst round-participation fraction observed
_STATS = {"workerLost": 0, "relaunches": 0, "droppedRounds": 0,
          "participationMin": 1.0}


def _elastic_group():
    from flink_ml_tpu.common.metrics import ML_GROUP, metrics

    return metrics.group(ML_GROUP, "elastic")


def _event(name: str, **attrs) -> None:
    """Best-effort trace event — telemetry must never sink the
    recovery path it describes."""
    try:
        from flink_ml_tpu.observability import tracing

        tracing.tracer.event(name, **attrs)
    except Exception:
        pass


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "%s=%r is not a number; ignoring it", name, raw)
        return None


def collective_timeout_s() -> Optional[float]:
    """The collective deadline in seconds, or None (detection off —
    the default: a deadline only makes sense where a peer can die)."""
    val = _env_float(COLLECTIVE_TIMEOUT_ENV)
    return val if val and val > 0 else None


def round_deadline_ms() -> Optional[float]:
    """The straggler round deadline in ms, or None (actuator off)."""
    val = _env_float(ROUND_DEADLINE_ENV)
    return val if val and val > 0 else None


# -- heartbeats ---------------------------------------------------------------
# ONE liveness mechanism: a "heartbeat" IS a fleet beacon
# (observability/fleet.py) written into the heartbeat dir — the elastic
# watchdog and ``mltrace fleet`` read the same stamp, so they can never
# disagree about who is dead. The beacon carries role/epoch/windowed
# metric slices on top of the liveness stamp for free.

def _hb_dir() -> Optional[str]:
    return os.environ.get(HEARTBEAT_DIR_ENV) or None


def beat(epoch: Optional[int] = None) -> None:
    """Write this process's liveness stamp — a fleet beacon (atomic
    replace, so a reader never sees a torn beat). No-op without
    ``FLINK_ML_TPU_HEARTBEAT_DIR`` — the launcher/driver opts a fit
    in. Never raises: an unwritable heartbeat dir must not kill the
    fit (the fleet writer swallows write failures)."""
    base = _hb_dir()
    if not base:
        return
    try:
        from flink_ml_tpu.observability import fleet

        fleet.write_beacon(base, role="trainer", epoch=epoch)
    except Exception:
        pass  # liveness reporting must never sink the fit it reports on


def stale_processes(timeout_s: float,
                    num_processes: Optional[int] = None) -> List[int]:
    """Process indices whose beacon stamp is missing or older than
    ``timeout_s`` — the detection side's evidence for WHO died. Empty
    when no heartbeat dir is configured (the caller then reports an
    unidentified loss)."""
    base = _hb_dir()
    if not base:
        return []
    from flink_ml_tpu.observability import fleet
    from flink_ml_tpu.parallel import distributed

    n = num_processes if num_processes is not None \
        else distributed.process_count()
    return fleet.stale_member_indices(base, timeout_s,
                                      num_processes=int(n))


# -- detection ----------------------------------------------------------------

def wait_with_deadline(tree, timeout_s: float, what: str = "collective"):
    """Block until ``tree``'s device computation is ready, but give up
    after ``timeout_s``: the sync runs on a watchdog thread, and a
    deadline miss consults the heartbeats to name the dead peer and
    raises :class:`WorkerLost` (retryable — run_supervised and the
    elastic driver both know what to do with it). The host-side seam
    where a wedged inter-process psum becomes a failure instead of a
    hang — a timeout cannot fire inside XLA itself."""
    import jax

    box = {}
    done = threading.Event()

    def work():
        try:
            jax.block_until_ready(tree)
        except Exception as e:  # noqa: BLE001 — re-raised on the caller
            box["err"] = e
        done.set()

    t = threading.Thread(target=work, daemon=True,
                         name="flink-ml-tpu-collective-watchdog")
    t.start()
    if not done.wait(timeout_s):
        stale = stale_processes(timeout_s)
        idx = stale[0] if stale else None
        _STATS["workerLost"] += 1
        _elastic_group().counter("collectiveTimeouts")
        _event("elastic.worker-lost", process=idx, timeout_s=timeout_s,
               what=what)
        raise WorkerLost(idx, f"{what} deadline exceeded",
                         timeout_s=timeout_s)
    if "err" in box:
        raise box["err"]
    return tree


def guard_fetch(tree, what: str = "boundary"):
    """The iteration drivers' hook: :func:`wait_with_deadline` when the
    collective deadline is armed, a free no-op otherwise (the default —
    single-process fits never pay a watchdog thread)."""
    timeout = collective_timeout_s()
    if timeout is None:
        return tree
    return wait_with_deadline(tree, timeout, what=what)


# -- the boundary hook (heartbeat + chaos probe) ------------------------------

def _chaos_probe(epoch: Optional[int]) -> None:
    """The worker-loss / worker-hang injection sites. Gated on a
    multi-process runtime: a SIGKILL site must never fire inside a
    single-process pytest run, however the ambient chaos env is armed.
    Every process advances the same deterministic schedule (counts stay
    in sync); only the configured victim acts."""
    from flink_ml_tpu.parallel import distributed

    if distributed.process_count() <= 1:
        return
    victim_raw = os.environ.get(CHAOS_VICTIM_ENV, "").strip()
    victim = int(victim_raw) if victim_raw.lstrip("-").isdigit() else 1
    if faults.decide("worker-loss"):
        if distributed.process_index() == victim:
            _event("elastic.chaos", site="worker-loss", epoch=epoch,
                   process=victim)
            os.kill(os.getpid(), signal.SIGKILL)
    if faults.decide("worker-hang"):
        if distributed.process_index() == victim:
            hang = _env_float(CHAOS_HANG_ENV)
            if hang is None:
                hang = 3.0 * (collective_timeout_s() or 40.0)
            _event("elastic.chaos", site="worker-hang", epoch=epoch,
                   process=victim, hang_s=hang)
            time.sleep(hang)


def on_boundary(epoch: Optional[int] = None) -> None:
    """Called by the iteration drivers at every epoch/segment boundary:
    beat the heartbeat (liveness evidence for the survivors' detection)
    and consult the worker-loss/worker-hang chaos sites. Near-free when
    neither heartbeats nor chaos are armed."""
    beat(epoch)
    if faults.active_plan() is not None:
        _chaos_probe(epoch)


# -- partial participation (the straggler actuator) ---------------------------

class RoundParticipation:
    """Straggler-aware round inclusion with JiT-style staleness
    bookkeeping (arXiv:2208.09740).

    Per round, :meth:`decide` returns the per-shard 0/1 include vector
    for ``collective.renormalized_sum``, computed from the PREVIOUS
    round's readiness timings (fed through :meth:`observe` — e.g. the
    per-shard ``ml.shard readyMs`` series of
    ``meshstats.observe_shard_ready``): a shard slower than the round
    deadline is dropped for one round, its staleness counter ticks up,
    and after ``max_staleness`` consecutive drops it is force-included
    (its next contribution is stale but the alternative is divergence
    of the dropped shard's slice — JiT's bounded-staleness rule). A
    round never drops every shard.
    """

    def __init__(self, n_shards: int, deadline_ms: Optional[float] = None,
                 max_staleness: int = 3):
        self.n_shards = int(n_shards)
        self.deadline_ms = (deadline_ms if deadline_ms is not None
                            else round_deadline_ms())
        self.max_staleness = int(max_staleness)
        self._last_ms: Optional[np.ndarray] = None
        self._staleness = np.zeros(self.n_shards, dtype=np.int64)
        self.rounds = 0
        self.dropped_rounds = 0
        self.participation_min = 1.0

    def observe(self, ready_ms: Sequence[float]) -> None:
        """Record this round's per-shard readiness (ms); informs the
        NEXT round's inclusion. Also feeds the PR 6 skew detector so
        ``ml.skew`` events keep firing alongside the actuation."""
        vals = np.asarray(list(ready_ms), dtype=np.float64)
        if vals.shape != (self.n_shards,):
            raise ValueError(
                f"expected {self.n_shards} per-shard timings, got "
                f"shape {vals.shape}")
        self._last_ms = vals
        try:
            from flink_ml_tpu.observability import meshstats

            meshstats.detect_skew("elastic-round", vals.tolist())
        except Exception:
            pass

    def decide(self, round_idx: int) -> np.ndarray:
        """The include vector (float 0/1, length ``n_shards``) for this
        round. Records ``ml.elastic participation{round=}`` and
        ``droppedContributions{shard=}``; an ``elastic.participation``
        event fires whenever a shard is dropped."""
        include = np.ones(self.n_shards, dtype=np.float64)
        if self.deadline_ms and self._last_ms is not None:
            slow = self._last_ms > float(self.deadline_ms)
            drop = slow & (self._staleness < self.max_staleness)
            if drop.all():  # never drop every shard
                drop[:] = False
            include[drop] = 0.0
            self._staleness = np.where(drop, self._staleness + 1, 0)
        else:
            self._staleness[:] = 0
        self.rounds += 1
        participating = int(include.sum())
        fraction = participating / self.n_shards
        self.participation_min = min(self.participation_min, fraction)
        _STATS["participationMin"] = min(_STATS["participationMin"],
                                         fraction)
        group = _elastic_group()
        group.gauge("participation", participating,
                    labels={"round": str(int(round_idx))})
        if participating < self.n_shards:
            self.dropped_rounds += 1
            _STATS["droppedRounds"] += 1
            dropped = [int(k) for k in np.flatnonzero(include == 0.0)]
            for k in dropped:
                group.counter("droppedContributions",
                              labels={"shard": str(k)})
            _event("elastic.participation", round=int(round_idx),
                   participating=participating, dropped=dropped,
                   staleness_max=int(self._staleness.max()))
        return include


# -- multi-process checkpointing (the re-placement seam) ----------------------

def repad_or_rescale(host: np.ndarray, target_shape) -> np.ndarray:
    """One carry leaf re-placed across a CHANGED shard count.

    Float state (coefficients, the 1/N-sharded adam m/v slices) carries
    the update-sharding layer's inert dim-0 zero padding: trim or
    re-extend it (``checkpoint.repad_leading``). A 1-D INTEGER leaf
    whose entries are all equal is per-shard round-robin progress (the
    fit carry's ``offsets``: every shard advances ``global_batch /
    n_shards`` per round over ``n / n_shards`` local rows, so the
    entries stay uniform): its global position is ``offset * n_old``,
    and the new world's per-shard offset is that divided by ``n_new`` —
    exact whenever ``n_new`` divides the global progress, else the
    checkpoint genuinely does not fit the new world
    (:class:`~flink_ml_tpu.iteration.checkpoint.CorruptCheckpoint`,
    routed to quarantine + fallback). Non-uniform integer progress
    cannot be re-placed either way."""
    from flink_ml_tpu.iteration.checkpoint import (CorruptCheckpoint,
                                                   repad_leading)

    target_shape = tuple(int(s) for s in target_shape)
    if (tuple(host.shape) == target_shape or host.ndim != 1
            or len(target_shape) != 1
            or not np.issubdtype(host.dtype, np.integer)):
        return repad_leading(host, target_shape)
    n_old, n_new = host.shape[0], target_shape[0]
    if n_old == 0 or n_new == 0:
        return repad_leading(host, target_shape)
    if np.any(host != host[0]):
        raise CorruptCheckpoint(
            f"per-shard integer progress {host.tolist()} is not uniform"
            f" — cannot re-place {n_old} shards onto {n_new}")
    progress = int(host[0]) * n_old
    if progress % n_new:
        raise CorruptCheckpoint(
            f"per-shard progress {int(host[0])} x {n_old} shards does "
            f"not divide across {n_new} shards")
    return np.full(target_shape, progress // n_new, dtype=host.dtype)


@functools.lru_cache(maxsize=8)
def _gather_program(sharding):
    """One compiled identity per target sharding (a fresh jit per leaf
    would defeat the compile cache)."""
    import jax

    return jax.jit(lambda a: a, out_shardings=sharding)


def _replicated_host(leaves) -> List[np.ndarray]:
    """Every leaf as a full host array on every process: leaves whose
    sharding spans processes are first gathered to a fully-replicated
    layout by one compiled identity program (SPMD — every process must
    reach this call in lockstep, which the symmetric iteration drivers
    guarantee), then fetched. Already-addressable leaves fetch as-is."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = []
    for x in leaves:
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            x = _gather_program(
                NamedSharding(x.sharding.mesh, P()))(x)
        out.append(np.asarray(x))
    return out


def _import_checkpoint_base():
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager

    return CheckpointManager


class ElasticCheckpointManager(_import_checkpoint_base()):
    """Checkpointing that survives a mesh spanning processes AND a
    changed process count.

    Save: the carry's 1/N-sharded leaves (the sharded optimizer
    moments) are all-gathered to host (:func:`_replicated_host` — SPMD,
    so every process calls ``save`` in lockstep exactly as the
    iteration drivers do) and only process 0 writes the shared
    directory — one v2 manifest, no write races.

    Restore: every process reads the same manifest; leaves re-pad
    across a CHANGED N (``repad_dim0`` defaults ON here — the
    update-sharding pad is inert zeros) and land on the template's
    cross-process shardings via ``jax.make_array_from_callback``, each
    process placing only its addressable shards: the 1/N slice
    re-placement of the elastic recovery path."""

    def __init__(self, base_dir: str, keep: int = 2,
                 repad_dim0: bool = True):
        super().__init__(base_dir, keep=keep, repad_dim0=repad_dim0)

    def save(self, carry, epoch: int, extras=None) -> str:
        import jax

        from flink_ml_tpu.parallel import distributed

        leaves, treedef = jax.tree_util.tree_flatten(carry)
        host = _replicated_host(leaves)
        if distributed.process_index() != 0:
            return os.path.join(self.base_dir, f"ckpt-{epoch:08d}")
        host_carry = jax.tree_util.tree_unflatten(treedef, host)
        return super().save(host_carry, epoch, extras=extras)

    def clear(self) -> None:
        from flink_ml_tpu.parallel import distributed

        if distributed.process_index() == 0:
            super().clear()

    def _place(self, host, tmpl):
        import jax

        sharding = getattr(tmpl, "sharding", None)
        if sharding is None:
            return host
        if isinstance(tmpl, jax.Array) and not tmpl.is_fully_addressable:
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])
        return jax.device_put(host, sharding)

    def _repad(self, host, target_shape):
        return repad_or_rescale(host, target_shape)


# -- recovery (the supervised relaunch driver) --------------------------------

def run_elastic(argv: Sequence[str], num_processes: int,
                min_processes: int = 1, local_devices: int = 1,
                env: Optional[dict] = None, timeout: float = 900.0,
                policy: Optional[RetryPolicy] = None, listeners=(),
                heartbeat_dir: Optional[str] = None,
                child_grace_s: float = 30.0) -> List[dict]:
    """Drive a launched multi-process fit elastically: on worker loss,
    rebuild smaller and resume.

    Each attempt launches ``argv`` as the current world size through
    ``distributed.launch`` (with its per-child liveness grace). A child
    that dies by signal — SIGKILLed, crashed, or grace-killed after
    wedging its siblings — is a :class:`WorkerLost`: the world shrinks
    by one and ``run_supervised`` retries (backoff, restart/deadline
    budgets, ``on_restart`` listener events all apply), so the next
    attempt's children build an (N-1)-process ``(dcn, data)`` mesh and
    re-place their 1/N slices from the shared checkpoint dir (the
    worker script owns that — see scripts/elastic_smoke.py). A nonzero
    exit WITHOUT a signal death is an ordinary retryable failure at the
    SAME world size (the fleet is intact; the fit merely failed).

    Shrinking below ``min_processes`` exhausts the *elastic* budget:
    :class:`RestartsExhausted` with ``budget="elastic"`` — as does the
    supervisor's own restart budget running out while losses continue.

    Returns the successful attempt's launch records.
    """
    from flink_ml_tpu.parallel import distributed
    from flink_ml_tpu.resilience.supervisor import run_supervised

    if int(num_processes) < int(min_processes):
        raise ValueError(
            f"num_processes={num_processes} < min_processes="
            f"{min_processes}")
    state = {"n": int(num_processes), "attempt": 0}

    def attempt() -> List[dict]:
        n = state["n"]
        attempt_idx = state["attempt"]
        state["attempt"] += 1
        child_env = dict(env or {})
        child_env[ATTEMPT_ENV] = str(attempt_idx)
        if heartbeat_dir:
            # per-attempt subdir: a dead process's stale beat must not
            # haunt the next, smaller world's liveness evidence
            child_env[HEARTBEAT_DIR_ENV] = os.path.join(
                heartbeat_dir, f"attempt-{attempt_idx}")
        group = _elastic_group()
        group.gauge("processCount", n)
        if attempt_idx:
            _STATS["relaunches"] += 1
            group.counter("relaunches")
            _event("elastic.relaunch", attempt=attempt_idx, processes=n)
        records = distributed.launch(
            argv, n, local_devices=local_devices, env=child_env,
            timeout=timeout, child_grace_s=child_grace_s)
        failed = [r for r in records if r["returncode"] != 0]
        if not failed:
            return records
        signaled = [r for r in failed if r["returncode"] < 0]
        if not signaled:
            # the fleet is intact — this is a fit failure, not a lost
            # worker: retry at the same N under the ordinary taxonomy
            raise RuntimeError(
                f"elastic attempt {attempt_idx}: {len(failed)} of {n} "
                f"processes failed (rc={failed[0]['returncode']}) "
                f"without a signal death:\n{failed[0]['stderr'][-2000:]}")
        # the FIRST signal death is the victim; later ones are the
        # launcher's grace-kills of its wedged siblings
        first = min(signaled,
                    key=lambda r: (r.get("exitOrder") is None,
                                   r.get("exitOrder") or 0))
        dead = first["process"]
        _STATS["workerLost"] += 1
        group.counter("workerLost")
        _event("elastic.worker-lost", process=dead,
               returncode=first["returncode"], processes=n)
        if n - 1 < int(min_processes):
            raise RestartsExhausted(
                attempt_idx,
                f"elastic budget exhausted: lost process {dead} at "
                f"world size {n}, floor is min_processes="
                f"{min_processes}", budget="elastic")
        state["n"] = n - 1
        raise WorkerLost(
            dead, f"child killed by signal "
            f"{-first['returncode']} at world size {n}")

    try:
        return run_supervised(attempt, policy=policy, listeners=listeners)
    except RestartsExhausted as e:
        if e.budget == "elastic":
            raise
        # the supervisor's budget ran dry while losses continued: that
        # IS the elastic budget from the caller's point of view
        raise RestartsExhausted(
            e.attempts, "elastic restart budget exhausted",
            budget="elastic") from e


# -- provenance ---------------------------------------------------------------

def provenance() -> dict:
    """The elastic fields benchmark rows carry beside ``processCount``
    (spread through ``update_sharding.provenance``): ``elasticEvents``
    (worker losses + relaunches + straggler-dropped rounds this run)
    and ``participationMin`` (the worst round-participation fraction;
    1.0 when no round dropped a shard)."""
    events = (_STATS["workerLost"] + _STATS["relaunches"]
              + _STATS["droppedRounds"])
    return {"elasticEvents": int(events),
            "participationMin": float(_STATS["participationMin"])}


def reset_stats() -> None:
    """Zero the fit-scoped elastic stats (benchmark runner calls this
    beside ``update_sharding.reset_last`` so provenance is per-run)."""
    _STATS.update(workerLost=0, relaunches=0, droppedRounds=0,
                  participationMin=1.0)
