"""Mesh construction.

The Flink-subtask ≙ TPU-core mapping lives here (SURVEY.md §7 layer 3): the
reference's "parallelism" knob becomes the size of the ``data`` mesh axis.
Single-slice meshes ride ICI; multi-slice/multi-host meshes extend over DCN
via jax.distributed — same code path, the mesh just gets bigger.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"    # data parallelism (the reference's only training parallelism)
MODEL_AXIS = "model"  # tensor/model parallelism (TPU-native bonus axis)

_default_mesh: Optional[Mesh] = None


def local_device_count() -> int:
    return len(jax.devices())


def create_mesh(shape: Sequence[int] = None,
                axis_names: Sequence[str] = (DATA_AXIS,),
                devices=None) -> Mesh:
    """Create a mesh over the given devices (default: all of them).

    ``create_mesh()`` → 1-D data mesh over every device.
    ``create_mesh((4, 2), ("data", "model"))`` → 2-D mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices),)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def default_mesh() -> Mesh:
    """Process-wide default mesh (lazily: all devices on one data axis)."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = create_mesh()
    return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh
