"""Mesh construction.

The Flink-subtask ≙ TPU-core mapping lives here (SURVEY.md §7 layer 3): the
reference's "parallelism" knob becomes the size of the ``data`` mesh axis.
Single-slice meshes ride ICI; multi-slice/multi-host meshes extend over DCN
via jax.distributed — same code path, the mesh just gets bigger.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"    # data parallelism (the reference's only training parallelism)
MODEL_AXIS = "model"  # tensor/model parallelism (TPU-native bonus axis)
DCN_AXIS = "dcn"      # cross-slice axis (slow network between TPU slices)

_default_mesh: Optional[Mesh] = None

#: the jax_platforms value in force before the CPU fallback pinned it, so
#: reset_backend_fallback() can deliberately retry the accelerator later
_platforms_before_pin = None


def _distributed_client_live() -> bool:
    """True when this process joined a multi-host JAX runtime (no public
    API; same probe as init_distributed)."""
    try:
        from jax._src import distributed as _distributed
        return _distributed.global_state.client is not None
    except Exception:
        return False


def reset_backend_fallback() -> None:
    """Undo the CPU pin applied by ``_all_devices`` so the next mesh
    construction retries the accelerator plugin. Deliberate-retry only:
    the pin is not retried automatically because a broken axon init can
    hang for many minutes per attempt.

    Restoring the config string alone is not enough — once
    ``jax.devices()`` succeeds on the pinned CPU platform JAX caches
    that backend set (and this module caches a CPU default mesh), so
    both caches are dropped here too; the next ``jax.devices()`` call
    re-probes the accelerator plugin for real."""
    global _platforms_before_pin, _default_mesh
    if _platforms_before_pin is not None:
        jax.config.update("jax_platforms", _platforms_before_pin)
        _platforms_before_pin = None
        _default_mesh = None
        _clear_jax_backends()


def _clear_jax_backends() -> None:
    """Drop JAX's cached backend set so the next ``jax.devices()``
    re-probes the plugin list. NOTE: invalidates live device arrays —
    only called from the deliberate-retry path, never mid-computation."""
    try:
        import jax.extend.backend
        jax.extend.backend.clear_backends()
    except Exception:  # pragma: no cover — older jax layouts
        from jax._src import xla_bridge
        xla_bridge.backends.cache_clear()


def _all_devices():
    """All default-backend devices, degrading to the host CPU backend when
    an accelerator plugin registers but fails to initialize.

    With an explicit platform list (the axon sitecustomize pins
    ``jax_platforms="axon,cpu"``), a plugin whose init fails makes
    ``jax.devices()`` RAISE rather than fall through — observed live when
    the TPU tunnel dies: every host-tier op that touches ``default_mesh()``
    (e.g. CountVectorizerModel.transform's device counts) crashed with
    "Unable to initialize backend 'axon'". The framework's host tier must
    keep working without the chip, so on that failure this process is
    pinned to the CPU backend (config update — re-probing the broken
    plugin via ``jax.devices("cpu")`` would re-enter the same failing
    init) and the mesh comes up on host devices instead.

    The fallback is single-process only: a worker inside a multi-host
    runtime that silently came up on CPU while its peers run on the
    accelerator would build a divergent mesh and hang or corrupt the
    collectives, so there the error propagates. Set
    ``FLINK_ML_TPU_NO_CPU_FALLBACK=1`` to disable the fallback entirely,
    and call :func:`reset_backend_fallback` to retry the accelerator
    after a pin."""
    global _platforms_before_pin
    try:
        return jax.devices()
    except RuntimeError as e:
        import logging
        import os

        if _distributed_client_live():
            raise RuntimeError(
                "default JAX backend unavailable in a multi-process "
                "runtime; refusing the CPU fallback (peers would run a "
                "divergent mesh)") from e
        if os.environ.get("FLINK_ML_TPU_NO_CPU_FALLBACK"):
            raise
        logging.getLogger(__name__).warning(
            "default JAX backend unavailable (%s); pinning this process "
            "to the host CPU backend (reset_backend_fallback() retries "
            "the accelerator)", e)
        if _platforms_before_pin is None:
            _platforms_before_pin = jax.config.jax_platforms
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()


def local_device_count() -> int:
    return len(_all_devices())


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     **kwargs) -> bool:
    """Join the multi-host JAX runtime so ``jax.devices()`` sees every chip
    in the cluster (the coordinator role of the reference's JobManager —
    SharedProgressAligner RPC — maps onto jax's distributed service; SPMD
    lockstep then replaces the per-epoch alignment protocol entirely).

    Safe to call unconditionally: a single-process run (no coordinator
    configured and no cluster env detected) or an already-initialized
    runtime is a no-op. Returns True when a multi-process runtime is live.
    """
    if num_processes == 1 and coordinator_address is None:
        return False
    try:  # no public API for "is the distributed client live?"
        from jax._src import distributed as _distributed
        already = _distributed.global_state.client is not None
    except Exception:
        already = False
    if already:
        return jax.process_count() > 1
    if coordinator_address is None and num_processes is None:
        # rely on cluster auto-detection (TPU metadata, SLURM, ...); if no
        # cluster environment exists this raises, which we treat as
        # "single process" — but log it, since on a real pod a transient
        # join failure here would otherwise silently degrade this process
        # to single-host while its peers form the cluster
        try:
            jax.distributed.initialize(**kwargs)
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "jax.distributed.initialize auto-detection failed (%s); "
                "continuing single-process. Pass coordinator_address/"
                "num_processes/process_id explicitly to force a cluster "
                "join.", e)
            return False
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kwargs)
    return jax.process_count() > 1


def create_mesh(shape: Sequence[int] = None,
                axis_names: Sequence[str] = (DATA_AXIS,),
                devices=None) -> Mesh:
    """Create a mesh over the given devices (default: all of them).

    ``create_mesh()`` → 1-D data mesh over every device.
    ``create_mesh((4, 2), ("data", "model"))`` → 2-D mesh.
    """
    devices = list(devices if devices is not None else _all_devices())
    if shape is None:
        shape = (len(devices),)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def create_hybrid_mesh(ici_shape: Sequence[int] = None,
                       dcn_shape: Sequence[int] = None,
                       axis_names: Sequence[str] = None,
                       devices=None) -> Mesh:
    """Mesh spanning multiple TPU slices: DCN-connected axes outermost so
    XLA keeps the heavy collectives on ICI and only crosses the slow
    network on the explicitly-DCN axes (the scaling-book layout recipe).

    ``create_hybrid_mesh(ici_shape=(4,), dcn_shape=(2,))`` on 2 slices of 4
    chips → a ("dcn", "data") mesh of shape (2, 4): psum over "data" rides
    ICI inside each slice; psum over ("dcn", "data") is a hierarchical
    all-reduce (in-slice reduce, one cross-slice exchange, in-slice
    broadcast) — XLA decomposes it that way automatically because the DCN
    axis is outermost in device order.

    On a single-slice/CPU runtime (no slice topology) the same axes are
    laid out over the flat device list so multi-slice programs stay
    runnable in tests — sharding semantics identical, only the physical
    transport differs.
    """
    devices = list(devices if devices is not None else _all_devices())
    dcn_shape = tuple(dcn_shape or (1,))
    if ici_shape is None:
        ici_shape = (len(devices) // max(int(np.prod(dcn_shape)), 1),)
    ici_shape = tuple(ici_shape)
    if axis_names is None:
        axis_names = (DCN_AXIS,) * len(dcn_shape) + (DATA_AXIS,) * len(ici_shape)
        if len(dcn_shape) != 1 or len(ici_shape) != 1:
            raise ValueError(
                "default axis_names only cover 1 dcn + 1 ici axis; pass "
                "axis_names explicitly for higher-rank hybrid meshes")
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if n_slices > 1:
        from jax.experimental import mesh_utils
        # create_hybrid_device_mesh wants same-rank shapes and returns an
        # array of elementwise-product shape, so pad each side with 1s to
        # get a (*dcn_shape, *ici_shape) result
        arr = mesh_utils.create_hybrid_device_mesh(
            (1,) * len(dcn_shape) + ici_shape,
            dcn_shape + (1,) * len(ici_shape),
            devices=devices)
    else:
        arr = np.asarray(devices).reshape(dcn_shape + ici_shape)
    return Mesh(arr, tuple(axis_names))


def data_axes(mesh: Mesh) -> tuple:
    """The mesh axes that together form the data-parallel domain, DCN axis
    first. Algorithms shard batches and psum over ALL of these, so a flat
    ("data",) mesh and a ("dcn", "data") hybrid mesh with the same total
    device count run the identical SPMD program — the hybrid one simply
    routes the outer reduction leg over DCN."""
    axes = tuple(a for a in (DCN_AXIS, DATA_AXIS) if a in mesh.axis_names)
    if not axes:
        raise ValueError(
            f"mesh has no data-parallel axis: expected {DATA_AXIS!r} "
            f"(optionally with {DCN_AXIS!r}) among {mesh.axis_names}")
    return axes


def data_shard_count(mesh: Mesh) -> int:
    """Total data-parallel shard count (the reference's 'parallelism')."""
    return int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))


def data_pspec(mesh: Mesh):
    """The PartitionSpec dim-0 entry for batch sharding on this mesh: the
    single data axis name on a flat mesh, the (dcn, data) tuple on a hybrid
    one. Use as ``P(data_pspec(mesh), ...)``."""
    axes = data_axes(mesh)
    return axes[0] if len(axes) == 1 else axes


def model_axis_of(mesh: Mesh) -> Optional[str]:
    """The tensor-parallel axis name, or None on a DP-only mesh."""
    return MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None


def default_mesh() -> Mesh:
    """Process-wide default mesh (lazily: all devices on one data axis)."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = create_mesh()
    return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh, _local_mesh
    _default_mesh = mesh
    _local_mesh = None


_local_mesh: Optional[Mesh] = None


def local_mesh() -> Mesh:
    """The mesh the *transform/predict* tier places batches on: the
    default mesh single-process, a data mesh over THIS process's
    addressable devices when the runtime spans processes
    (jax.distributed — docs/distributed.md "Multi-process meshes").
    Training is SPMD across every process, but prediction is a
    per-process operation — each process scores its own traffic, and a
    prediction column sharded over a multi-process mesh could never be
    fetched by its local caller (jax refuses to materialize
    non-addressable shards)."""
    global _local_mesh
    if jax.process_count() <= 1:
        return default_mesh()
    if _local_mesh is None:
        _local_mesh = create_mesh(devices=jax.local_devices())
    return _local_mesh
