"""Version-portable ``shard_map`` — THE seam every SPMD program builds on.

``jax.shard_map`` moved twice across the JAX line this framework spans:
it lives at ``jax.experimental.shard_map.shard_map`` (replication check
spelled ``check_rep``) through 0.4.x/0.5.x and graduates to the
top-level ``jax.shard_map`` (the check renamed ``check_vma``) in 0.6+.
Every fit program and test in this repo goes through :func:`shard_map`
below so the whole multi-device tier runs on either line — on the
pre-graduation line the 90 shard_map paths used to fail collection-deep
with ``AttributeError: module 'jax' has no attribute 'shard_map'``; this
module is what un-froze them.

This is also the mesh-telemetry seam (docs/observability.md
"Distributed telemetry"): wrapping a program over a mesh is the moment
the runtime provably commits to a topology, so when tracing is armed the
mesh snapshot (device count, axis layout, platform) is recorded here —
once per mesh — as root-span attributes, ``ml.mesh`` gauges and a
``mesh.json`` trace artifact (observability/meshstats.py).
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size"]


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma: bool = True):
    """``jax.shard_map`` (0.6+) or ``jax.experimental.shard_map.shard_map``
    (0.4/0.5, where ``check_vma`` is spelled ``check_rep``) — same
    semantics either way. All arguments after ``f`` are keyword-style to
    match the graduated API."""
    _record_mesh(mesh)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis, from inside a traced body.

    ``jax.lax.axis_size`` where it exists (0.6+); on older lines
    ``psum(1, axis)`` constant-folds to the same Python int at trace
    time — no traced value escapes either way."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _record_mesh(mesh) -> None:
    """Mesh-topology telemetry at the program-build seam; free when the
    tracer is disarmed, once per mesh when armed."""
    if mesh is None:
        return
    try:
        from flink_ml_tpu.observability import meshstats

        meshstats.ensure_mesh_recorded(mesh)
    except Exception:  # telemetry must never sink a program build
        pass
