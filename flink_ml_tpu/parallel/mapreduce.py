"""Named map-reduce training primitives — THE programming layer for fits.

DrJAX (arXiv:2403.07128) showed that large-scale map-reduce learning
programs want *named first-class primitives* — ``broadcast`` / ``map`` /
``reduce`` — rather than ad-hoc SPMD bodies: the names are where sharding
decisions, telemetry and static analysis attach. This module is that
layer for every fit program in the framework:

- **in-axis primitives** (used inside map bodies): :func:`broadcast`,
  :func:`reduce_sum` / :func:`reduce_mean` / :func:`reduce_max`,
  :func:`reduce_scatter`, :func:`all_gather`, :func:`shard_index` /
  :func:`shard_count`, plus the padding-mask helper
  :func:`local_valid_mask`. All delegate to ``parallel/collective.py``,
  so each records its trace-time ``ml.collective`` accounting
  (op count + payload bytes labeled ``{op=,axis=,devices=}`` —
  docs/observability.md "Distributed telemetry") for free.
- :func:`map_shards` — the ONE way a fit program becomes SPMD: wraps a
  per-shard body in the version-portable ``parallel/shardmap.py`` seam
  (inheriting mesh-topology telemetry) and jits it, optionally through
  ``instrumented_jit`` with buffer donation for the sharded-update
  carries. jaxlint rule JL108 ``raw-collective`` enforces that nothing
  outside ``flink_ml_tpu/parallel/`` calls ``jax.lax.psum``-family
  collectives or ``shard_map`` directly — programs go through here.
- :class:`MapReduceProgram` — composes *partition → map → reduce →
  update* into ONE jittable per-step program. The same program runs
  identically on a 1-device mesh and an N-device mesh: the primitives
  degrade to identities/local ops at N=1, so the single-device hot path
  pays nothing for the abstraction (gated by ``mltrace diff --budget``
  in scripts/mapreduce_bench.py).

The cross-replica *sharded* update (reduce-scatter the gradients, update
a ``1/N`` parameter/optimizer-state slice per replica, all-gather fresh
parameters — arXiv:2004.13336) composes from these primitives in
``parallel/update_sharding.py``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax

from flink_ml_tpu.parallel import collective as _c
from flink_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    data_axes,
    data_pspec,
    data_shard_count,
    default_mesh,
)
from flink_ml_tpu.parallel.shardmap import axis_size
from flink_ml_tpu.parallel.shardmap import shard_map as _shard_map

__all__ = [
    "broadcast", "map_shards", "map_rows", "reduce_sum", "reduce_mean",
    "reduce_max", "reduce_scatter", "renormalized_sum", "all_gather",
    "shard_index", "shard_count", "local_valid_mask", "MapReduceProgram",
]


# -- in-axis primitives (inside map bodies) -----------------------------------

def broadcast(x, axis_name=DATA_AXIS, src: int = 0):
    """Shard ``src``'s value on every shard (DrJAX ``broadcast``: one
    replicated value entering the mapped computation). One masked psum
    on the wire; records ``ml.collective`` at trace time."""
    return _c.broadcast_from(x, src=src, axis_name=axis_name)


def reduce_sum(x, axis_name=DATA_AXIS):
    """Sum of the per-shard partials on every shard (map → reduce)."""
    return _c.all_reduce_sum(x, axis_name)


def reduce_mean(x, axis_name=DATA_AXIS):
    return _c.all_reduce_mean(x, axis_name)


def reduce_max(x, axis_name=DATA_AXIS):
    return _c.all_reduce_max(x, axis_name)


def reduce_scatter(x, axis_name=DATA_AXIS):
    """Sum of the per-shard partials, scattered: each shard keeps its
    own ``1/N`` slice of dim 0 (see collective.reduce_scatter)."""
    return _c.reduce_scatter(x, axis_name)


def renormalized_sum(x, include, axis_name=DATA_AXIS):
    """Partial-participation reduce: shards with ``include=0`` contribute
    zero and the sum is rescaled by ``n_shards / participants`` so the
    update stays unbiased — the straggler-aware round primitive
    (parallel/elastic.py decides ``include`` per round on host; see
    collective.renormalized_sum)."""
    return _c.renormalized_sum(x, include, axis_name)


def all_gather(x, axis_name=DATA_AXIS, axis: int = 0, tiled: bool = True):
    return _c.all_gather(x, axis_name, axis=axis, tiled=tiled)


def shard_index(axis_name=DATA_AXIS):
    """This shard's position along the data axes (tuple-capable)."""
    return _c.shard_index(axis_name)


def shard_count(axis_name=DATA_AXIS) -> int:
    """Static total shard count over the (possibly tuple of) axes, from
    inside a traced body — a Python int at trace time."""
    axes = ((axis_name,) if isinstance(axis_name, str)
            else tuple(axis_name))
    return int(np.prod([axis_size(a) for a in axes]))


def local_valid_mask(axes, local_n: int, n_valid, dtype=None):
    """Per-shard validity mask for zero-padded batches (re-exported from
    the collective layer so map bodies import one module)."""
    import jax.numpy as jnp

    return _c.local_valid_mask(axes, local_n, n_valid,
                               dtype if dtype is not None else jnp.float32)


# -- the SPMD program seam ----------------------------------------------------

def map_shards(fn, mesh, in_specs, out_specs, *, check_vma: bool = False,
               jit: bool = True, donate_argnums=None,
               name: Optional[str] = None):
    """Build the named SPMD map: ``fn`` runs once per shard of the
    mesh's data domain with its inputs partitioned per ``in_specs``.

    THE seam every fit program builds through (JL108): wraps ``fn`` in
    the version-portable ``shard_map`` (recording mesh topology when
    tracing is armed) and jits the result. ``donate_argnums`` (the
    iteration state carries) makes the donated buffers update in place —
    the first rung of the raw-speed ladder (docs/performance.md); with
    ``name`` the jit additionally goes through ``instrumented_jit`` for
    per-function compile accounting. Donation WITHOUT a name keeps
    plain ``jax.jit``'s C++ dispatch cache — the per-batch hot loops
    (replicated FTRL, unsharded SGD) donate without paying a Python
    signature lookup per call. ``jit=False`` returns the bare mapped
    callable for host loops that jit the round themselves
    (iteration.iterate_bounded)."""
    mapped = _shard_map(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=check_vma)
    if not jit:
        return mapped
    donate_kw = ({"donate_argnums": tuple(donate_argnums)}
                 if donate_argnums else {})
    if name is not None:
        from flink_ml_tpu.observability.compilestats import instrumented_jit

        return instrumented_jit(mapped, name=name, **donate_kw)
    return jax.jit(mapped, **donate_kw)


def map_rows(fn, mesh, *, n_extra: int = 0, name: Optional[str] = None,
             donate_argnums=None):
    """Row-parallel apply — the *serving* dispatch shape: argument 0 is
    sharded on dim 0 over the mesh's data axes, the ``n_extra``
    remaining arguments are replicated (model parameters), and the
    output is row-sharded, gathered to the host only when the caller
    fetches it.

    This is how a padded serving micro-batch (serving/batcher.py)
    spreads over the mesh: each device predicts its contiguous
    ``rows / N`` slice of the batch, no collective on the hot path at
    all — the gather happens on the fetch side of the dispatch. The
    caller guarantees dim 0 divides the data-shard count (the bucket
    table makes that a static property; non-divisible buckets stay on
    the single-device path). Embarrassingly row-parallel ``fn`` bodies
    need no primitives; a body that does reduce across rows would need
    the in-axis primitives above and should use :func:`map_shards`
    with explicit specs instead."""
    from jax.sharding import PartitionSpec as P

    spec0 = data_pspec(mesh)
    in_specs = (P(spec0),) + (P(),) * int(n_extra)
    return map_shards(fn, mesh, in_specs, P(spec0), name=name,
                      donate_argnums=donate_argnums)


class MapReduceProgram:
    """*partition → map → reduce → update* as ONE jittable SPMD step.

    The builder names the four phases of every distributed fit round
    (the reference's scatter / CalculateLocalGradient / all-reduce /
    UpdateModel pipeline, SURVEY.md §7) so a program is its composition,
    not an ad-hoc ``shard_map`` body::

        prog = MapReduceProgram(mesh, name="ftrl.dense")
        step = prog.build(map_fn, update_fn,
                          in_specs=(...), out_specs=(...))
        new_state = step(*data, *state)

    - ``map_fn(*args) -> partials`` runs per shard on the partitioned
      inputs and returns a pytree of local partials.
    - ``reduce`` (default :func:`reduce_sum`) is applied leaf-wise over
      the mesh's data axes; pass a pytree of reducers matching the
      partials to mix modes — e.g. ``reduce_scatter`` for the gradient
      leaf and ``reduce_sum`` for the loss scalar, the cross-replica
      sharded-update composition (update_sharding.py).
    - ``update_fn(reduced, *args) -> outputs`` consumes the reduced
      partials (on every shard, or each shard's slice) and produces the
      new state.

    The same built program runs identically on a 1-device and an
    N-device mesh — partition/reduce degrade to local ops at N=1.
    """

    def __init__(self, mesh=None, name: Optional[str] = None):
        self.mesh = mesh if mesh is not None else default_mesh()
        self.axes = data_axes(self.mesh)
        self.spec0 = data_pspec(self.mesh)
        self.n_shards = data_shard_count(self.mesh)
        self.name = name

    # -- partition (host boundary; records ml.collective opMs) ---------------
    def partition(self, array, dtype=None):
        """Place a batch on the mesh sharded on dim 0 (device-resident
        inputs reshard on device). Returns (device_array, true_rows)."""
        return _c.ensure_on_mesh(self.mesh, array, self.axes, dtype)

    def replicate(self, tree):
        """Broadcast-variable placement: the tree on every device."""
        return _c.replicate(self.mesh, tree)

    def data_spec(self, ndim: int = 1):
        """PartitionSpec for a dim-0-sharded operand of rank ``ndim``."""
        from jax.sharding import PartitionSpec as P

        return P(self.spec0, *([None] * (ndim - 1)))

    # -- the composed step ---------------------------------------------------
    def build(self, map_fn, update_fn, *, in_specs, out_specs,
              reduce=None, donate_argnums=None, check_vma: bool = False,
              jit: bool = True, name: Optional[str] = None):
        reducers = reduce if reduce is not None else reduce_sum
        axes = self.axes

        def per_shard(*args):
            partials = map_fn(*args)
            if callable(reducers):
                reduced = jax.tree_util.tree_map(
                    lambda p: reducers(p, axes), partials)
            else:  # pytree of per-leaf reducers matching the partials
                reduced = jax.tree_util.tree_map(
                    lambda r, p: r(p, axes), reducers, partials)
            return update_fn(reduced, *args)

        return map_shards(per_shard, self.mesh, in_specs, out_specs,
                          check_vma=check_vma, jit=jit,
                          donate_argnums=donate_argnums,
                          name=name or self.name)
