"""Mid-iteration checkpoint/resume.

Ref parity: the reference's deepest subsystem (SURVEY.md §5) — aligned
checkpoint barriers circulating through the feedback cycle
(HeadOperatorCheckpointAligner.java:42, checkpoint/Checkpoints.java:43),
feedback-record logs, and DataCacheSnapshot. On TPU there are no in-flight
records: a checkpoint is an atomic snapshot of (carry pytree, epoch) taken
between rounds, so the whole subsystem reduces to serializing a pytree.

Format: one directory per checkpoint, numpy arrays + a treedef manifest.
The manifest (version 2) records per-leaf sha256 digests, dtypes and
shapes; files are fsynced before the atomic rename publishes the
directory, so a torn write cannot masquerade as a valid checkpoint.
Restore rebuilds arrays onto the template carry's shardings, so resume
works on the same mesh topology (same-parallelism restore — the reference
has exactly the same restriction, ReplayOperator.java:163).

Failure behavior (docs/resilience.md): ``restore()`` validates the
newest checkpoint against its manifest and, on ANY corruption (missing
or unreadable manifest/leaves, digest mismatch, dtype/shape drift,
leaf-count mismatch), quarantines the directory as ``ckpt-*.corrupt``
and falls back to the next-older checkpoint — never raising mid-recovery.
No surviving checkpoint means a fresh start (returns None).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Any, List, Optional, Tuple

import time
from typing import Dict

import numpy as np

import jax

from flink_ml_tpu.resilience import faults

logger = logging.getLogger(__name__)

#: bucket bounds for checkpoint payload-size histograms (bytes)
_BYTE_BUCKETS = tuple(4.0 ** i for i in range(4, 19))  # 256 B .. 64 GB


def _ckpt_group():
    from flink_ml_tpu.common.metrics import ML_GROUP, metrics

    return metrics.group(ML_GROUP, "checkpoint")


def _observe(op: str, ms: float, nbytes: int) -> None:
    """Record one save/restore into the ml.checkpoint histograms,
    labeled by operation so both directions share one metric name."""
    labels: Dict[str, str] = {"op": op}
    group = _ckpt_group()
    group.histogram("opMs", labels=labels).observe(ms)
    group.histogram("opBytes", buckets=_BYTE_BUCKETS,
                    labels=labels).observe(nbytes)
    group.counter("ops", labels=labels)

#: manifest schema: 1 = epoch + num_leaves only (legacy, still
#: restorable); 2 = adds per-leaf {sha256, dtype, shape} integrity records
MANIFEST_VERSION = 2


class CorruptCheckpoint(Exception):
    """A checkpoint directory failed integrity validation. Never escapes
    ``restore()`` — it routes to quarantine + fallback. Public for the
    serving model registry (serving/registry.py), which validates
    candidate model data through :func:`load_validated` and must treat
    this as "reject the candidate", never "crash the server"."""


def _leaf_digest(arr: np.ndarray) -> Optional[str]:
    if arr.dtype == object:  # pointer bytes are not content — no digest
        return None
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def _fsync_path(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # e.g. a filesystem that won't open directories
    try:
        os.fsync(fd)
    except OSError:
        pass  # fsync unsupported here: durability is best-effort, the
        # digests still catch a torn write on restore
    finally:
        os.close(fd)


def load_validated(ckpt_dir: str, expected_leaves: Optional[int] = None
                   ) -> Tuple[List[np.ndarray], int]:
    """(host leaves, epoch) of one checkpoint directory, validated
    against its v2 manifest (per-leaf sha256/dtype/shape); raises
    :class:`CorruptCheckpoint` describing what failed — and ONLY that:
    any unexpected exception during validation (a manifest mangled into
    the wrong JSON shape raises KeyError/AttributeError, not json
    errors) is itself corruption evidence and is re-raised as
    CorruptCheckpoint, so every caller's reject/quarantine path fires.
    The shared integrity seam: :meth:`CheckpointManager.restore` uses
    it for resume, and the serving model registry (serving/registry.py)
    uses it to vet candidate model data before a hot-swap — a
    bit-flipped snapshot must never become the serving model.
    ``expected_leaves`` is optional there: the registry learns the leaf
    count from the manifest itself."""
    try:
        return _validate_checkpoint(ckpt_dir, expected_leaves)
    except CorruptCheckpoint:
        raise
    except Exception as e:  # noqa: BLE001 — see docstring
        raise CorruptCheckpoint(
            f"validation failed: {type(e).__name__}: {e}") from e


def _validate_checkpoint(ckpt_dir: str, expected_leaves: Optional[int]
                         ) -> Tuple[List[np.ndarray], int]:
    try:
        with open(os.path.join(ckpt_dir, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise CorruptCheckpoint(f"manifest unreadable: {e}") from e
    num = manifest.get("num_leaves")
    if not isinstance(num, int):
        raise CorruptCheckpoint("manifest lacks num_leaves")
    if expected_leaves is not None and num != expected_leaves:
        # an incompatible snapshot takes the same fallback path as a
        # failed digest; quarantine renames, never deletes — if EVERY
        # checkpoint trips this, the template (not the data) changed,
        # and the dirs can be renamed back by hand
        raise CorruptCheckpoint(
            f"checkpoint has {num} leaves, template has "
            f"{expected_leaves} (a mismatch on every checkpoint "
            "means the template/config changed, not the data)")
    records = manifest.get("leaves")
    try:
        with np.load(os.path.join(ckpt_dir, "leaves.npz")) as z:
            host_leaves = [z[f"leaf_{i}"] for i in range(num)]
    except Exception as e:  # noqa: BLE001 — BadZipFile, KeyError,
        # OSError, truncated-stream ValueError: all mean "unreadable"
        raise CorruptCheckpoint(f"leaves unreadable: {e}") from e
    if records is not None:  # version >= 2: verify integrity records
        if len(records) != num:
            raise CorruptCheckpoint("manifest leaf records truncated")
        for i, (arr, rec) in enumerate(zip(host_leaves, records)):
            if (rec.get("dtype") is not None
                    and str(arr.dtype) != rec["dtype"]):
                raise CorruptCheckpoint(
                    f"leaf_{i} dtype {arr.dtype} != manifest "
                    f"{rec['dtype']}")
            if (rec.get("shape") is not None
                    and list(arr.shape) != list(rec["shape"])):
                raise CorruptCheckpoint(
                    f"leaf_{i} shape {list(arr.shape)} != manifest "
                    f"{rec['shape']}")
            want = rec.get("sha256")
            if want is not None and _leaf_digest(arr) != want:
                raise CorruptCheckpoint(f"leaf_{i} sha256 mismatch")
    return host_leaves, manifest["epoch"]


def list_checkpoint_names(base_dir: str) -> List[str]:
    """Sorted ``ckpt-<number>`` directory names under ``base_dir``
    (empty when the directory is missing/unreadable) — THE naming
    scheme, shared by :meth:`CheckpointManager.list_checkpoints` and
    the serving registry's watcher so a future rename cannot split
    them."""
    try:
        names = os.listdir(base_dir)
    except OSError:
        return []
    return sorted(d for d in names
                  if d.startswith("ckpt-") and d[len("ckpt-"):].isdigit())


def quarantine_checkpoint(ckpt_dir: str, reason: str) -> str:
    """Rename a corrupt checkpoint directory to ``*.corrupt`` (never
    delete — forensic evidence), record the ``quarantined`` counter and
    the ``checkpoint.quarantine`` trace event; returns the quarantine
    path (or ``"<removed>"`` when the rename itself failed). Shared by
    restore-fallback and the serving registry's candidate vetting."""
    target = ckpt_dir + ".corrupt"
    n = 0
    while os.path.exists(target):
        n += 1
        target = f"{ckpt_dir}.corrupt{n}"
    try:
        os.rename(ckpt_dir, target)
    except OSError:  # already gone / unrenameable: drop it instead
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        target = "<removed>"
    logger.warning("corrupt checkpoint %s quarantined as %s (%s)",
                   ckpt_dir, target, reason)
    from flink_ml_tpu.observability import tracing

    _ckpt_group().counter("quarantined")
    tracing.tracer.event("checkpoint.quarantine",
                         checkpoint=os.path.basename(ckpt_dir),
                         reason=reason)
    return target


def repad_leading(host: np.ndarray, target_shape) -> np.ndarray:
    """Re-place one dim-0 zero-padded leaf onto a different padded
    length (the elastic cross-N re-placement seam): the update-sharding
    layer pads dim 0 to ``padded_len(n, n_shards)`` with trailing zeros
    that stay inert through every update rule, so a checkpoint written
    at N processes restores at M by trimming or re-extending that pad.
    A NONZERO trimmed tail is genuine incompatibility (real state would
    be lost) and raises :class:`CorruptCheckpoint`, routing the restore
    to quarantine + fallback like any other integrity failure."""
    target_shape = tuple(int(s) for s in target_shape)
    if tuple(host.shape) == target_shape:
        return host
    if (host.ndim != len(target_shape) or host.ndim == 0
            or tuple(host.shape[1:]) != target_shape[1:]):
        raise CorruptCheckpoint(
            f"leaf shape {tuple(host.shape)} cannot re-place onto "
            f"{target_shape}: only the leading (padded) dim may differ")
    n = target_shape[0]
    if host.shape[0] > n:
        tail = host[n:]
        if np.any(tail != np.zeros((), dtype=host.dtype)):
            raise CorruptCheckpoint(
                f"leaf shape {tuple(host.shape)} trim to {target_shape} "
                "would drop nonzero state (not dim-0 padding)")
        return np.ascontiguousarray(host[:n])
    pad = [(0, n - host.shape[0])] + [(0, 0)] * (host.ndim - 1)
    return np.pad(host, pad)


class CheckpointManager:
    """Saves/restores (carry, epoch) snapshots under a base directory.

    ``repad_dim0=True`` opts restore into cross-parallelism
    re-placement: leaves whose shapes differ from the template only in
    dim 0 are trimmed/zero-extended through :func:`repad_leading`
    before being device_put onto the template's shardings — how the
    elastic driver (parallel/elastic.py) resumes an N-process fit on a
    smaller replica set. Off by default: the same-parallelism
    restriction stays the safe baseline (a shape drift is corruption
    unless a caller explicitly declares its dim 0 to be padding)."""

    def __init__(self, base_dir: str, keep: int = 2,
                 repad_dim0: bool = False):
        self.base_dir = base_dir
        self.keep = keep
        self.repad_dim0 = repad_dim0
        os.makedirs(base_dir, exist_ok=True)
        # a crash between makedirs and the atomic rename strands a
        # ckpt-*.tmp dir; left alone they accumulate forever
        self.sweep_orphans()

    # -- write ---------------------------------------------------------------
    def save(self, carry: Any, epoch: int,
             extras: Optional[Dict[str, dict]] = None) -> str:
        """Save one checkpoint. ``extras`` maps artifact names to JSON
        documents written as ``<name>.json`` beside the manifest INSIDE
        the atomic rename — how the serving publish path ships a drift
        baseline (observability/drift.py) with the exact model snapshot
        it was captured from; a torn write can never publish leaves
        without their companion artifacts. Extra files are ignored by
        integrity validation (the manifest enumerates leaves only)."""
        from flink_ml_tpu.observability import tracing

        start = time.perf_counter()
        self._last_save_bytes = 0
        with tracing.tracer.span("checkpoint.save", epoch=epoch) as sp:
            ckpt_dir = self._save(carry, epoch, sp, extras=extras)
        _observe("save", (time.perf_counter() - start) * 1000.0,
                 self._last_save_bytes)
        return ckpt_dir

    def _save(self, carry: Any, epoch: int, sp,
              extras: Optional[Dict[str, dict]] = None) -> str:
        faults.inject("checkpoint-save", epoch=epoch)
        leaves, treedef = jax.tree_util.tree_flatten(carry)
        ckpt_dir = os.path.join(self.base_dir, f"ckpt-{epoch:08d}")
        tmp_dir = ckpt_dir + ".tmp"
        os.makedirs(tmp_dir, exist_ok=True)
        host_leaves = [np.asarray(x) for x in leaves]
        # stashed on self (not read off the span): the histogram must see
        # real bytes with the tracer disarmed too
        self._last_save_bytes = int(sum(x.nbytes for x in host_leaves))
        sp.set_attribute("bytes", self._last_save_bytes)
        sp.set_attribute("leaves", len(host_leaves))
        leaves_path = os.path.join(tmp_dir, "leaves.npz")
        np.savez(leaves_path,
                 **{f"leaf_{i}": x for i, x in enumerate(host_leaves)})
        manifest = {
            "version": MANIFEST_VERSION,
            "epoch": epoch,
            "num_leaves": len(leaves),
            "leaves": [{"sha256": _leaf_digest(x),
                        "dtype": str(x.dtype),
                        "shape": list(x.shape)} for x in host_leaves],
        }
        manifest_path = os.path.join(tmp_dir, "manifest.json")
        with open(manifest_path, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        for name, doc in (extras or {}).items():
            extra_path = os.path.join(tmp_dir, f"{name}.json")
            with open(extra_path, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
        # fsync data before the rename: the atomic publish must never
        # expose a directory whose contents still live in the page cache
        # only (a power cut would produce exactly the torn checkpoint the
        # digests exist to catch — cheaper to not write one)
        _fsync_path(leaves_path)
        faults.inject("checkpoint-publish", epoch=epoch)
        # atomic publish: rename makes partially-written checkpoints invisible
        if os.path.exists(ckpt_dir):
            shutil.rmtree(ckpt_dir)
        os.rename(tmp_dir, ckpt_dir)
        _fsync_path(self.base_dir)  # persist the directory entry itself
        self._gc()
        return ckpt_dir

    def clear(self) -> None:
        """Discard all checkpoints (called when an iteration completes)."""
        for name in self.list_checkpoints():
            shutil.rmtree(os.path.join(self.base_dir, name),
                          ignore_errors=True)

    def sweep_orphans(self) -> int:
        """Remove stranded ``ckpt-*.tmp`` dirs (a crash mid-save);
        returns how many were swept. Quarantined ``*.corrupt`` dirs are
        kept — they are forensic evidence, not debris."""
        swept = 0
        for name in os.listdir(self.base_dir):
            if name.startswith("ckpt-") and name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.base_dir, name),
                              ignore_errors=True)
                swept += 1
        return swept

    def _gc(self) -> None:
        ckpts = self.list_checkpoints()
        for stale in ckpts[:-self.keep]:
            shutil.rmtree(os.path.join(self.base_dir, stale), ignore_errors=True)

    # -- read ----------------------------------------------------------------
    def list_checkpoints(self):
        return list_checkpoint_names(self.base_dir)

    def _quarantine(self, ckpt_dir: str, reason: str) -> None:
        quarantine_checkpoint(ckpt_dir, reason)

    def _load_validated(self, ckpt_dir: str, expected_leaves: int
                        ) -> Tuple[List[np.ndarray], int]:
        """(host leaves, epoch) of one checkpoint dir, or raise
        :class:`CorruptCheckpoint` describing what failed validation.
        ANY unexpected exception during validation is itself corruption
        evidence (a manifest mangled into the wrong JSON shape raises
        AttributeError/KeyError, not json errors) — the recovery path
        must never crash on a bad checkpoint, only skip it."""
        try:
            return self._validate(ckpt_dir, expected_leaves)
        except CorruptCheckpoint:
            raise
        except Exception as e:  # noqa: BLE001 — see docstring
            raise CorruptCheckpoint(
                f"validation failed: {type(e).__name__}: {e}") from e

    def _validate(self, ckpt_dir: str, expected_leaves: int
                  ) -> Tuple[List[np.ndarray], int]:
        return load_validated(ckpt_dir, expected_leaves)

    def _place(self, host: np.ndarray, tmpl):
        """One restored host leaf onto the template leaf's placement —
        the seam the elastic manager (parallel/elastic.py) overrides to
        place shards of a mesh that spans processes."""
        if hasattr(tmpl, "sharding"):
            return jax.device_put(host, tmpl.sharding)
        return host

    def _repad(self, host: np.ndarray, target_shape) -> np.ndarray:
        """One leaf re-placed onto the template's shape (only consulted
        under ``repad_dim0``): the baseline treats every dim-0 mismatch
        as the sharded update's zero padding. The elastic manager
        overrides this to ALSO rescale per-shard integer progress
        counters across the changed shard count."""
        return repad_leading(host, target_shape)

    def restore(self, template_carry: Any) -> Optional[Tuple[Any, int]]:
        """Newest checkpoint that passes integrity validation, restored
        onto the template's structure and shardings; corrupt checkpoints
        are quarantined (``ckpt-*.corrupt``) and skipped in favor of the
        next-older one. None if no valid checkpoint exists."""
        from flink_ml_tpu.observability import tracing

        start = time.perf_counter()
        t_leaves, treedef = jax.tree_util.tree_flatten(template_carry)
        with tracing.tracer.span("checkpoint.restore") as sp:
            for name in reversed(self.list_checkpoints()):
                ckpt_dir = os.path.join(self.base_dir, name)
                try:
                    host_leaves, epoch = self._load_validated(
                        ckpt_dir, len(t_leaves))
                    if self.repad_dim0:
                        host_leaves = [
                            self._repad(h, np.shape(t))
                            for h, t in zip(host_leaves, t_leaves)]
                except CorruptCheckpoint as e:
                    self._quarantine(ckpt_dir, str(e))
                    continue
                restored = [self._place(host, tmpl)
                            for host, tmpl in zip(host_leaves, t_leaves)]
                nbytes = int(sum(x.nbytes for x in host_leaves))
                sp.set_attribute("epoch", epoch)
                sp.set_attribute("checkpoint", name)
                sp.set_attribute("bytes", nbytes)
                _observe("restore",
                         (time.perf_counter() - start) * 1000.0, nbytes)
                return (jax.tree_util.tree_unflatten(treedef, restored),
                        epoch)
            sp.set_attribute("result", "fresh-start")
        return None
