"""Mid-iteration checkpoint/resume.

Ref parity: the reference's deepest subsystem (SURVEY.md §5) — aligned
checkpoint barriers circulating through the feedback cycle
(HeadOperatorCheckpointAligner.java:42, checkpoint/Checkpoints.java:43),
feedback-record logs, and DataCacheSnapshot. On TPU there are no in-flight
records: a checkpoint is an atomic snapshot of (carry pytree, epoch) taken
between rounds, so the whole subsystem reduces to serializing a pytree.

Format: one directory per checkpoint, numpy arrays + a treedef manifest.
Restore rebuilds arrays onto the template carry's shardings, so resume
works on the same mesh topology (same-parallelism restore — the reference
has exactly the same restriction, ReplayOperator.java:163).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Optional, Tuple

import numpy as np

import jax


class CheckpointManager:
    """Saves/restores (carry, epoch) snapshots under a base directory."""

    def __init__(self, base_dir: str, keep: int = 2):
        self.base_dir = base_dir
        self.keep = keep
        os.makedirs(base_dir, exist_ok=True)

    # -- write ---------------------------------------------------------------
    def save(self, carry: Any, epoch: int) -> str:
        leaves, treedef = jax.tree_util.tree_flatten(carry)
        ckpt_dir = os.path.join(self.base_dir, f"ckpt-{epoch:08d}")
        tmp_dir = ckpt_dir + ".tmp"
        os.makedirs(tmp_dir, exist_ok=True)
        host_leaves = [np.asarray(x) for x in leaves]
        np.savez(os.path.join(tmp_dir, "leaves.npz"),
                 **{f"leaf_{i}": x for i, x in enumerate(host_leaves)})
        with open(os.path.join(tmp_dir, "manifest.json"), "w") as f:
            json.dump({"epoch": epoch, "num_leaves": len(leaves)}, f)
        # atomic publish: rename makes partially-written checkpoints invisible
        if os.path.exists(ckpt_dir):
            shutil.rmtree(ckpt_dir)
        os.rename(tmp_dir, ckpt_dir)
        self._gc()
        return ckpt_dir

    def clear(self) -> None:
        """Discard all checkpoints (called when an iteration completes)."""
        for name in self.list_checkpoints():
            shutil.rmtree(os.path.join(self.base_dir, name),
                          ignore_errors=True)

    def _gc(self) -> None:
        ckpts = self.list_checkpoints()
        for stale in ckpts[:-self.keep]:
            shutil.rmtree(os.path.join(self.base_dir, stale), ignore_errors=True)

    # -- read ----------------------------------------------------------------
    def list_checkpoints(self):
        return sorted(d for d in os.listdir(self.base_dir)
                      if d.startswith("ckpt-") and not d.endswith(".tmp"))

    def restore(self, template_carry: Any) -> Optional[Tuple[Any, int]]:
        """Latest checkpoint restored onto the template's structure and
        shardings; None if no checkpoint exists."""
        ckpts = self.list_checkpoints()
        if not ckpts:
            return None
        ckpt_dir = os.path.join(self.base_dir, ckpts[-1])
        with open(os.path.join(ckpt_dir, "manifest.json")) as f:
            manifest = json.load(f)
        with np.load(os.path.join(ckpt_dir, "leaves.npz")) as z:
            host_leaves = [z[f"leaf_{i}"] for i in range(manifest["num_leaves"])]
        t_leaves, treedef = jax.tree_util.tree_flatten(template_carry)
        if len(t_leaves) != len(host_leaves):
            raise ValueError(
                f"checkpoint has {len(host_leaves)} leaves, template has {len(t_leaves)}")
        restored = []
        for host, tmpl in zip(host_leaves, t_leaves):
            if hasattr(tmpl, "sharding"):
                restored.append(jax.device_put(host, tmpl.sharding))
            else:
                restored.append(host)
        return jax.tree_util.tree_unflatten(treedef, restored), manifest["epoch"]
