"""Iteration runtime.

Ref parity: flink-ml-iteration (13k LoC of head/tail operators, epoch
watermark trackers, feedback channels, draft-graph rewriting, in-loop
checkpoint barriers). On TPU the whole apparatus collapses (SURVEY.md §7):

- the *feedback edge* is the carry pytree of a compiled round function;
- *epoch alignment* is implicit — SPMD shards run the round in lockstep;
- the coordinator's *global termination vote* is a ``psum`` of per-shard
  counts checked between rounds;
- *checkpoint-through-the-cycle* is snapshotting (carry, epoch) between
  rounds — there are no in-flight records to drain;
- the *data cache* (DataCacheWriter/ListStateWithCache) is the training batch
  living on device HBM across rounds, sharded over the mesh.

What remains real and is implemented here: the IterationBody protocol, the
bounded loop driver (fully-on-device ``lax.while_loop`` or a host loop with
listener callbacks), termination criteria (max-iter / tol / empty-round
vote), per-round vs all-round state scoping, and checkpoint/resume.
"""

from flink_ml_tpu.iteration.iteration import (  # noqa: F401
    IterationConfig,
    IterationListener,
    Iterations,
    iterate_bounded,
)
from flink_ml_tpu.iteration.checkpoint import CheckpointManager  # noqa: F401
from flink_ml_tpu.iteration.streaming import (  # noqa: F401
    StreamTable,
    generate_batches,
    iterate_unbounded,
)
