"""Bounded iteration driver.

Ref parity map:
- ``Iterations.iterate_bounded_streams_until_termination``
  (Iterations.java:149) → :func:`iterate_bounded`.
- ``IterationBody.process`` (IterationBody.java:54) → the ``body`` callable:
  ``body(carry, epoch) -> carry`` traced once and compiled.
- ``IterationListener.onEpochWatermarkIncremented / onIterationTerminated``
  → :class:`IterationListener` callbacks (host mode).
- Termination (SharedProgressAligner.java:277-292 + TerminateOnMaxIterOrTol)
  → ``max_iter`` bound plus an optional ``terminate`` predicate on the carry
  (tol comparison, empty-round vote, ...), evaluated on device.
- ALL_ROUND vs PER_ROUND operator lifecycles (IterationConfig) → carry state
  persists across rounds (all-round) vs ``per_round_init`` resetting part of
  the carry each epoch (per-round).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from flink_ml_tpu.resilience import faults

Carry = Any
Body = Callable[[Carry, jnp.ndarray], Carry]
Terminate = Callable[[Carry, jnp.ndarray], jnp.ndarray]  # -> bool scalar


def segment_fusion_enabled() -> bool:
    """Segment-boundary fusion (default ON): the compiled segment
    programs stack their per-boundary scalars — epoch, stop flag, and
    (with health telemetry) the non-finite sentinel — into ONE int32
    vector, so each boundary costs one device→host transfer instead of
    one per scalar. ``FLINK_ML_TPU_SEGMENT_FUSION=0`` restores the
    scalar-by-scalar pre-fusion path (results are bit-identical either
    way — the fusion only changes how the already-computed scalars reach
    the host, never what the program computes)."""
    return os.environ.get("FLINK_ML_TPU_SEGMENT_FUSION", "1") != "0"


def read_boundary(boundary) -> list:
    """Fetch a segment boundary's host-visible scalars, counting the
    device→host transfers it costs into ``ml.iteration
    boundaryFetches`` (the quantity the perf ratchet gates on: 1 per
    boundary when fused). ``boundary`` is either one stacked device
    vector (the fused form — ONE transfer) or a tuple/list of separate
    scalars (the pre-fusion form — one transfer each). Returns the
    values as numpy scalars in order."""
    from flink_ml_tpu.common.metrics import ML_GROUP, metrics
    from flink_ml_tpu.parallel import elastic

    # the boundary fetch is where a wedged inter-process reduce leg
    # surfaces on host: with FLINK_ML_TPU_COLLECTIVE_TIMEOUT_S armed
    # the sync runs under a watchdog and a dead peer becomes a
    # retryable WorkerLost instead of a hang (parallel/elastic.py)
    boundary = elastic.guard_fetch(boundary, what="segment boundary")
    grp = metrics.group(ML_GROUP, "iteration")
    if isinstance(boundary, (tuple, list)):
        vals = [np.asarray(v) for v in boundary]
        grp.counter("boundaryFetches", len(vals))
        return vals
    vals = list(np.asarray(boundary))
    grp.counter("boundaryFetches")
    return vals


@dataclasses.dataclass
class IterationConfig:
    """Ref: iteration/IterationConfig.java + our driver knobs."""

    #: "device": one jitted lax.while_loop — zero host round-trips; fastest.
    #: "host": python loop over a jitted round — enables listeners,
    #: checkpoints and data-dependent host logic between rounds.
    mode: str = "device"

    #: checkpoint every N epochs (0 = never). Device mode runs N-round
    #: compiled segments with a snapshot between them (the fast path and
    #: fault tolerance compose); host mode snapshots between rounds.
    checkpoint_interval: int = 0
    checkpoint_manager: Optional[Any] = None

    #: host mode: reset part of the carry each round (PER_ROUND lifecycle).
    per_round_init: Optional[Callable[[Carry, int], Carry]] = None

    def __post_init__(self):
        if self.mode not in ("device", "host"):
            raise ValueError(
                f"IterationConfig.mode must be 'device' or 'host', "
                f"got {self.mode!r}")


class IterationListener:
    """Ref: iteration/IterationListener.java, extended with the restart/
    recovery events the reference gets from Flink's restart strategy
    (emitted by resilience.supervisor.run_supervised, not by the
    iteration drivers themselves)."""

    def on_epoch_watermark_incremented(self, epoch: int, carry: Carry) -> None:
        pass

    def on_iteration_terminated(self, carry: Carry) -> None:
        pass

    def on_restart(self, attempt: int, error: BaseException) -> None:
        """A supervised run failed retryably; restart ``attempt`` (1-based)
        is about to re-enter from the newest valid checkpoint."""

    def on_recovered(self, attempt: int) -> None:
        """A supervised run completed after ``attempt`` restart(s)."""


def iterate_bounded(initial_carry: Carry,
                    body: Body,
                    max_iter: int,
                    terminate: Optional[Terminate] = None,
                    config: IterationConfig = None,
                    listeners: Sequence[IterationListener] = (),
                    jit_round: bool = True,
                    donate_carry: bool = False) -> Carry:
    """Run ``body`` for up to ``max_iter`` epochs; stop early when
    ``terminate(carry, epoch)`` is True. Returns the final carry.

    The carry is an arbitrary pytree and may contain device arrays with any
    sharding — cached training data sharded over the data axis rides along
    exactly like the reference's in-loop data cache.

    ``jit_round=False`` runs the body as plain host code per round (no
    tracing) — for bodies whose math lives on host (the CSR sparse trainer:
    scipy matvecs have no XLA form). Such bodies always use the host loop.

    ``donate_carry=True`` donates the carry buffers through the compiled
    device/segment loops (the update happens in place — no fresh
    allocation per call). Opt-in because donation CONSUMES
    ``initial_carry``: only callers that build fresh carry buffers and
    never reuse them afterwards (the algorithm fast paths) may set it.
    The host loop never donates — listeners legitimately hold references
    to lagged carries (health.ConvergenceListener), which donation would
    delete out from under them."""
    config = config or IterationConfig()
    seg = device_checkpoint_segment(config, listeners)
    if jit_round and seg:
        return _segmented_device_loop(initial_carry, body, max_iter,
                                      terminate, config, seg,
                                      donate_carry=donate_carry)
    if jit_round and not needs_host_loop(config, listeners):
        return _device_loop(initial_carry, body, max_iter, terminate,
                            donate_carry=donate_carry)
    return _host_loop(initial_carry, body, max_iter, terminate, config,
                      listeners, jit_round)


def needs_host_loop(config: Optional[IterationConfig],
                    listeners: Sequence[IterationListener] = ()) -> bool:
    """True when any configured behavior requires host-driven rounds.
    The single source of truth for the device/host dispatch — algorithm fast
    paths (SGD, KMeans) must consult this instead of re-deriving it.

    Checkpointing alone no longer lands here: a device-mode fit with only
    interval checkpointing runs K-round compiled segments with a host
    snapshot between them (:func:`device_checkpoint_segment`) — fast paths
    must check that FIRST, then this."""
    if config is None:
        return bool(listeners)
    return bool(listeners) or config.mode == "host" \
        or config.checkpoint_interval != 0 \
        or config.checkpoint_manager is not None \
        or config.per_round_init is not None


def device_checkpoint_segment(
        config: Optional[IterationConfig],
        listeners: Sequence[IterationListener] = ()) -> int:
    """K (the checkpoint interval) when the ONLY host hook is interval
    checkpointing and the mode is "device": the iteration then runs as
    K-round compiled ``while_loop`` segments with the carry snapshotted on
    host between segments — fault tolerance composes with the fast path
    (ref bar: every reference job checkpoints *through* the iteration,
    Checkpoints.java:43, without leaving its execution mode).  0 when the
    configuration needs true per-round host hooks (listeners,
    per_round_init, mode="host") or no checkpointing is requested."""
    if config is None or listeners:
        return 0
    if (config.mode != "device" or config.per_round_init is not None
            or config.checkpoint_manager is None
            or config.checkpoint_interval <= 0):
        return 0
    return config.checkpoint_interval


def run_segmented(run_segment, initial_carry, max_iter: int, K: int, mgr):
    """Drive ``run_segment(carry, epoch0, limit) -> (carry, epoch, stop)``
    in K-round chunks with a checkpoint at every K-round boundary — the
    shared segment driver for the generic iteration and for algorithm fast
    paths that build their own compiled segment program (SGD does;
    KMeans rides the generic :func:`_segmented_device_loop` through
    ``iterate_bounded``, which wraps its shard_mapped round body in the
    segmented while_loop).

    ``run_segment`` implementations fetch their own boundary scalars
    (through :func:`read_boundary`, so the transfers are counted and —
    fused — cost ONE device→host round-trip per boundary) and return
    host values; legacy device scalars still work (``int``/``bool``
    coerce them, at one transfer each).

    Checkpoint cadence matches the host loop exactly: a snapshot lands
    after every K completed rounds — EXCEPT the final boundary of a
    completing run, whose snapshot ``mgr.clear()`` below would delete
    before anything could restore it: that save (a full carry
    device→host transfer) is skipped. An early stop mid-segment saves
    nothing, and a completed run clears its checkpoints. A restore
    landing off the K-grid (a snapshot from a different interval or
    mode) realigns at the first segment so later boundaries checkpoint
    on-grid again."""
    from flink_ml_tpu.common.metrics import ML_GROUP, metrics
    from flink_ml_tpu.observability import compilestats, tracing
    iter_group = metrics.group(ML_GROUP, "iteration")

    import time as _time

    carry, epoch = initial_carry, 0
    restored = mgr.restore(carry)
    if restored is not None:
        carry, epoch = restored
    stop = False
    prev_ctx = None
    while epoch < max_iter and not stop:
        # realign to the K-grid so `epoch % K == 0` keeps firing after an
        # off-phase restore
        limit = min(epoch + K - epoch % K, max_iter)
        seg_start = _time.perf_counter()
        # each segment follows from the previous one: the explicit
        # carry-handoff edge `flink-ml-tpu-trace path` walks
        with tracing.tracer.span("segment", epoch_from=epoch,
                                 epoch_to=limit,
                                 links=([prev_ctx] if prev_ctx
                                        else None)) as sp:
            carry, e, s = run_segment(carry, epoch, limit)
            if tracing.tracer.enabled:
                # per-shard time-to-ready at the boundary: the straggler
                # surface of the segment (ml.shard readyMs with
                # shard=/device= labels, ml.skew on spread). With fusion
                # the boundary scalars synced inside run_segment, so on
                # a real TPU this measures the residual drain of the
                # carry outputs (on CPU the program was always complete
                # by now either way).
                from flink_ml_tpu.observability import meshstats
                meshstats.observe_shard_ready(carry, span=sp,
                                              phase="segment")
            rounds = int(e) - epoch
            epoch, stop = int(e), bool(s)
            sp.set_attribute("rounds", rounds)
            iter_group.counter("boundaries")
            # chaos site: the segment boundary is this mode's epoch
            # boundary
            faults.inject("epoch-boundary", epoch=epoch)
            # heartbeat + worker-loss/worker-hang chaos probe
            # (multi-process only; see parallel/elastic.py)
            from flink_ml_tpu.parallel import elastic
            elastic.on_boundary(epoch)
            done = epoch >= max_iter or stop
            if epoch % K == 0 and not done:
                mgr.save(carry, epoch)
            if tracing.tracer.enabled:
                # HBM watermark at the segment boundary (the host-sync
                # point, so the sample costs no extra device round-trip;
                # silent no-op on CPU)
                compilestats.sample_memory("segment", span=sp)
            prev_ctx = tracing.context_of(sp)
        # per-segment metrics: the host-sync boundary is already here, so
        # the counters cost no extra device round-trip
        seg_ms = (_time.perf_counter() - seg_start) * 1000.0
        iter_group.counter("rounds", rounds)
        iter_group.gauge("lastSegmentMs", seg_ms)
        iter_group.gauge("lastRoundMs", seg_ms / max(rounds, 1))
        # histories survive the fit (last-value gauges don't): per-epoch
        # duration distribution, labeled by execution mode
        iter_group.histogram(
            "epochMs", labels={"mode": "device-segment"}).observe(
            seg_ms / max(rounds, 1))
        iter_group.histogram(
            "segmentMs", labels={"mode": "device-segment"}).observe(seg_ms)
    mgr.clear()
    return carry


def _segmented_device_loop(initial_carry, body, max_iter, terminate, config,
                           K: int, donate_carry: bool = False):
    """Device-mode iteration with interval checkpointing: one jitted
    ``while_loop`` per K-round segment (epoch bounds are device scalars, so
    every segment reuses one compilation), carry snapshotted between
    segments.  Numerically identical to :func:`_device_loop` by
    construction — both build on :func:`_loop_pieces`.

    The boundary scalars (epoch, stop) come back stacked as one int32
    vector when :func:`segment_fusion_enabled` — one transfer per
    boundary; ``FLINK_ML_TPU_SEGMENT_FUSION=0`` keeps them separate.
    With ``donate_carry`` the carry buffers are donated into each
    segment (in-place update; the previous segment's output is consumed
    only after its checkpoint snapshot, so restore still sees every
    saved state)."""
    cond, step = _loop_pieces(body, terminate)
    fused = segment_fusion_enabled()

    @functools.partial(jax.jit,
                       donate_argnums=(0,) if donate_carry else ())
    def seg(carry, epoch0, limit):
        carry, epoch, stop, _ = jax.lax.while_loop(
            cond, step, (carry, epoch0, jnp.asarray(False), limit))
        if fused:
            return carry, jnp.stack([epoch, stop.astype(jnp.int32)])
        return carry, epoch, stop

    def run_segment(carry, epoch0, limit):
        out = seg(carry, jnp.int32(epoch0), jnp.int32(limit))
        boundary = out[1] if fused else out[1:]
        vals = read_boundary(boundary)
        return out[0], int(vals[0]), bool(vals[1])

    return run_segmented(run_segment, initial_carry, max_iter, K,
                         config.checkpoint_manager)


def _loop_pieces(body, terminate):
    """The shared while_loop (cond, step) over state
    ``(carry, epoch, stop, limit)`` — ONE definition of the round/stop
    structure so the full device loop and the checkpointed segment loop
    cannot drift apart numerically.  Termination is evaluated *after*
    each round on the just-completed epoch, matching _host_loop exactly —
    all modes must be numerically interchangeable (a listener or a
    checkpoint must never change the result)."""

    def cond(state):
        carry, epoch, stop, limit = state
        return jnp.logical_and(epoch < limit, jnp.logical_not(stop))

    def step(state):
        carry, epoch, _, limit = state
        new_carry = body(carry, epoch)
        stop = (jnp.asarray(terminate(new_carry, epoch), dtype=bool)
                if terminate is not None else jnp.asarray(False))
        return new_carry, epoch + 1, stop, limit

    return cond, step


def _device_loop(initial_carry, body, max_iter, terminate,
                 donate_carry: bool = False):
    """Single compiled while_loop: the whole iteration is one XLA program
    (the K=max_iter degenerate case of the segmented loop). With
    ``donate_carry`` the carry buffers update in place (the caller's
    ``initial_carry`` is consumed)."""
    cond, step = _loop_pieces(body, terminate)

    @functools.partial(jax.jit,
                       donate_argnums=(0,) if donate_carry else ())
    def run(carry):
        final_carry, _, _, _ = jax.lax.while_loop(
            cond, step,
            (carry, jnp.int32(0), jnp.asarray(False), jnp.int32(max_iter)))
        return final_carry

    return run(initial_carry)


def _host_loop(initial_carry, body, max_iter, terminate, config, listeners,
               jit_round: bool = True):
    """Host-driven rounds with listener/checkpoint hooks.

    The jitted round returns (carry, stop) so the only host sync per round is
    one scalar — the same single-bit exchange as the reference's
    GloballyAlignedEvent, minus the RPC. With ``jit_round=False`` the body
    runs as plain host code (CSR math); the stop bit is then immediate.
    """

    if jit_round:
        def round_impl(carry, epoch):
            new_carry = body(carry, epoch)
            stop = (jnp.asarray(terminate(new_carry, epoch), dtype=bool)
                    if terminate is not None else jnp.asarray(False))
            return new_carry, stop

        round_fn = jax.jit(round_impl)
    else:
        # plain host rounds: no jnp anywhere, so a pure-host iteration
        # (CSR math) runs without ever initializing a device backend
        def round_fn(carry, epoch):
            new_carry = body(carry, epoch)
            stop = (bool(terminate(new_carry, epoch))
                    if terminate is not None else False)
            return new_carry, stop

    from flink_ml_tpu.common.metrics import ML_GROUP, metrics
    from flink_ml_tpu.observability import compilestats, tracing
    iter_group = metrics.group(ML_GROUP, "iteration")
    mode_label = {"mode": "host"}

    carry = initial_carry
    start_epoch = 0
    mgr = config.checkpoint_manager
    if mgr is not None:
        restored = mgr.restore(carry)
        if restored is not None:
            carry, start_epoch = restored

    import time as _time
    prev_ctx = None
    for epoch in range(start_epoch, max_iter):
        round_start = _time.perf_counter()
        # epoch N follows from epoch N-1: the carry-handoff edge the
        # critical-path view (`flink-ml-tpu-trace path`) walks
        with tracing.tracer.span("epoch", epoch=epoch,
                                 links=([prev_ctx] if prev_ctx
                                        else None)) as sp:
            if config.per_round_init is not None:
                carry = config.per_round_init(carry, epoch)
            carry, stop = round_fn(
                carry, jnp.int32(epoch) if jit_round else epoch)
            faults.inject("epoch-boundary", epoch=epoch)
            from flink_ml_tpu.parallel import elastic
            elastic.on_boundary(epoch)
            # listeners/checkpoints run while the async-dispatched device
            # round is still executing — host and device legs overlap
            host_start = _time.perf_counter()
            for lst in listeners:
                lst.on_epoch_watermark_incremented(epoch, carry)
            if mgr is not None and config.checkpoint_interval and \
                    (epoch + 1) % config.checkpoint_interval == 0:
                mgr.save(carry, epoch + 1)
            host_ms = (_time.perf_counter() - host_start) * 1000.0
            if jit_round and tracing.tracer.enabled:
                # per-shard time-to-ready while the async round drains:
                # per-replica epoch attribution + straggler detection
                # (ml.shard readyMs{shard=,device=}, ml.skew events)
                from flink_ml_tpu.observability import meshstats
                meshstats.observe_shard_ready(carry, span=sp,
                                              phase="epoch")
            # guarded host sync point (device round complete): a wedged
            # inter-process reduce becomes WorkerLost past the deadline
            stop = bool(elastic.guard_fetch(stop, what="round stop bit"))
            # per-round wall time split: hostMs = listener/checkpoint
            # work, deviceMs = dispatch + residual device wait after the
            # overlap — the profiling surface the reference lacks (its
            # per-round wrapper only feeds Flink's LatencyStats)
            total_ms = (_time.perf_counter() - round_start) * 1000.0
            sp.set_attribute("host_ms", round(host_ms, 3))
            sp.set_attribute("device_ms", round(total_ms - host_ms, 3))
            if tracing.tracer.enabled:
                # per-epoch HBM watermark, taken after the stop-bit sync
                # so the round's allocations are visible (no-op on CPU)
                compilestats.sample_memory("epoch", span=sp)
            prev_ctx = tracing.context_of(sp)
        iter_group.gauge("lastRoundMs", total_ms)
        iter_group.gauge("lastRoundHostMs", host_ms)
        iter_group.gauge("lastRoundDeviceMs", total_ms - host_ms)
        # last-value gauges keep only the final epoch; the labeled
        # histograms keep the whole fit's distribution
        iter_group.histogram("epochMs", labels=mode_label).observe(
            total_ms)
        iter_group.histogram("epochHostMs", labels=mode_label).observe(
            host_ms)
        iter_group.histogram("epochDeviceMs", labels=mode_label).observe(
            total_ms - host_ms)
        iter_group.counter("rounds")
        if stop:
            break
    for lst in listeners:
        lst.on_iteration_terminated(carry)
    if mgr is not None:
        # The iteration completed: discard its checkpoints so a later run
        # against the same manager starts fresh instead of restoring this
        # run's final state (the reference likewise discards checkpoints on
        # job success). A crash skips this, leaving the resume point intact.
        mgr.clear()
    return carry


class Iterations:
    """Namespace parity with iteration/Iterations.java."""

    iterate_bounded_streams_until_termination = staticmethod(iterate_bounded)

    @staticmethod
    def iterate_unbounded_streams(*args, **kwargs):
        from flink_ml_tpu.iteration.streaming import iterate_unbounded
        return iterate_unbounded(*args, **kwargs)
