"""Unbounded (online) runtime.

Ref parity: the pieces of Flink that have no XLA analog and therefore live as
a small host streaming runtime (SURVEY.md §7 "Hard parts"):

- ``StreamTable`` — an unbounded source: an iterator of host Tables
  (micro-batches), the equivalent of an unbounded DataStream.
- ``generate_batches`` — global-batch assembly: re-chunks arbitrary
  micro-batches into exact ``global_batch_size`` batches, the semantics of
  ``DataStreamUtils.generateBatchData`` (DataStreamUtils.java:734:
  countWindowAll(batchSize) → even split → scatter; here the "scatter" is
  ``shard_batch`` onto the mesh at consume time).
- ``iterate_unbounded`` — the unbounded iteration loop
  (Iterations.iterateUnboundedStreams, Iterations.java:123): per batch,
  update the model carry and emit a versioned model snapshot; model version
  increments per emission (ref: OnlineLogisticRegression.java
  CreateLrModelData:235-258).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Tuple

import numpy as np

from flink_ml_tpu.common.table import Table


class StreamTable:
    """An unbounded table: iterable of bounded Table chunks."""

    def __init__(self, chunks: Iterable[Table]):
        self._chunks = chunks

    def __iter__(self) -> Iterator[Table]:
        return iter(self._chunks)

    @staticmethod
    def from_table(table: Table, chunk_size: int) -> "StreamTable":
        """Chop a bounded table into a stream (test/bench fixture; the
        equivalent of the examples' PeriodicSourceFunction)."""
        def gen():
            for start in range(0, table.num_rows, chunk_size):
                yield table.take(slice(start, min(start + chunk_size,
                                                  table.num_rows)))
        return StreamTable(gen())


def generate_batches(stream: StreamTable, global_batch_size: int,
                     drop_remainder: bool = True) -> Iterator[Table]:
    """Re-chunk a stream into exact global batches.

    Ref: DataStreamUtils.generateBatchData (DataStreamUtils.java:734) — the
    global-batch assembly used by all online trainers. A trailing partial
    batch is dropped (an unbounded stream never "ends" in the reference;
    set drop_remainder=False for bounded test fixtures).
    """
    buffer: Optional[Table] = None
    cursor = 0  # consumed prefix of buffer; avoids re-copying the tail per batch
    for chunk in stream:
        if buffer is None or cursor == buffer.num_rows:
            # fully-consumed buffer: start fresh (also keeps a chunk's
            # column representation intact — concat with an empty table
            # of a different vector representation would fail)
            buffer, cursor = chunk, 0
        else:
            remaining = buffer.take(slice(cursor, buffer.num_rows)) \
                if cursor else buffer
            buffer, cursor = remaining.concat(chunk), 0
        while buffer.num_rows - cursor >= global_batch_size:
            yield buffer.take(slice(cursor, cursor + global_batch_size))
            cursor += global_batch_size
    if buffer is not None and buffer.num_rows - cursor > 0 and not drop_remainder:
        yield buffer.take(slice(cursor, buffer.num_rows))


def window_stream(stream: StreamTable, windows,
                  timestamp_col: Optional[str] = None,
                  with_end_ts: bool = False) -> Iterator:
    """Regroup a stream's rows into tumbling or session time windows.

    Ref: the Windows param consumed by OnlineStandardScaler (
    feature/standardscaler/OnlineStandardScaler.java — per-window model
    emission); session specs per common/window/SessionWindows.java.

    - Event-time tumbling windows bucket rows by ``timestamp_col //
      size_ms``; a window is emitted when a later window's first row
      arrives (in-order streams — the reference's watermark generator with
      zero out-of-orderness), the trailing window at end-of-stream.
    - Processing-time tumbling windows bucket whole chunks by wall-clock
      arrival time; no timestamp column is involved (reference semantics).
    - Session windows close when the time gap to the next row (event time)
      or next chunk arrival (processing time) exceeds ``gap_ms``, or when
      the stream ends (docs/deviations.md: Flink instead holds the final
      session until a watermark passes gap-end). A session's end timestamp
      is last-element-time + gap, matching Flink's session merge rule.

    Yields Tables, or ``(window_end_ms, Table)`` with ``with_end_ts=True``
    (the timestamp the reference stamps on each per-window model).
    """
    import time as _time

    from flink_ml_tpu.common.window import (
        EventTimeSessionWindows,
        EventTimeTumblingWindows,
        ProcessingTimeSessionWindows,
        ProcessingTimeTumblingWindows,
    )

    event_time = isinstance(windows, (EventTimeTumblingWindows,
                                      EventTimeSessionWindows))
    session = isinstance(windows, (EventTimeSessionWindows,
                                   ProcessingTimeSessionWindows))
    if not (event_time or isinstance(windows, (
            ProcessingTimeTumblingWindows, ProcessingTimeSessionWindows))):
        raise ValueError(f"window_stream supports tumbling and session time "
                         f"windows, got {type(windows).__name__}")
    if event_time and timestamp_col is None:
        raise ValueError(
            "event-time windows need timestamp_col to assign rows to "
            "windows")

    def emit(end_ms, table):
        return (int(end_ms), table) if with_end_ts else table

    if session:
        yield from _session_windows(stream, windows.gap_ms, event_time,
                                    timestamp_col, emit, _time)
        return

    size_ms = windows.size_ms
    pending: Optional[Table] = None
    pending_window = None
    for chunk in stream:
        if event_time:
            wids = np.asarray(chunk.column(timestamp_col),  # jaxlint: disable=host-sync -- window assignment must read timestamps on host; once per arriving chunk, not per training round
                              np.int64) // size_ms
            chunk_windows = [(wid, chunk.take(np.nonzero(wids == wid)[0]))
                             for wid in np.unique(wids)]
        else:
            chunk_windows = [(int(_time.time() * 1000) // size_ms, chunk)]
        for window_id, rows in chunk_windows:
            if pending_window is None or window_id == pending_window:
                pending = rows if pending is None else pending.concat(rows)
                pending_window = window_id
            else:
                yield emit((pending_window + 1) * size_ms, pending)
                pending, pending_window = rows, window_id
    if pending is not None and pending.num_rows:
        yield emit((pending_window + 1) * size_ms, pending)


def _session_windows(stream, gap_ms, event_time, timestamp_col, emit, _time):
    """Gap-based session assignment over an in-order stream. Event time:
    a gap between consecutive row timestamps > gap_ms closes the session;
    processing time: a gap between chunk arrivals does. The final partial
    session is emitted at end-of-stream (documented deviation)."""
    pending: Optional[Table] = None
    last_ts = None  # last event timestamp / last chunk arrival, ms
    for chunk in stream:
        if chunk.num_rows == 0:
            continue
        if event_time:
            ts = np.asarray(chunk.column(timestamp_col), np.int64)  # jaxlint: disable=host-sync -- session gaps are defined over host timestamps; one read per arriving chunk, not per training round
            # split the chunk at internal gaps; prepend the pending session
            starts = np.nonzero(np.diff(ts) > gap_ms)[0] + 1
            bounds = [0, *starts.tolist(), len(ts)]
            for i in range(len(bounds) - 1):
                # gap-free chunk (the common case): no copy
                seg = chunk if len(bounds) == 2 else chunk.take(
                    np.arange(bounds[i], bounds[i + 1]))
                seg_first, seg_last = int(ts[bounds[i]]), \
                    int(ts[bounds[i + 1] - 1])
                if pending is not None and seg_first - last_ts > gap_ms:
                    yield emit(last_ts + gap_ms, pending)
                    pending = None
                pending = seg if pending is None else pending.concat(seg)
                last_ts = seg_last
        else:
            now = int(_time.time() * 1000)
            if pending is not None and now - last_ts > gap_ms:
                yield emit(last_ts + gap_ms, pending)
                pending = None
            pending = chunk if pending is None else pending.concat(chunk)
            last_ts = now
    if pending is not None and pending.num_rows:
        yield emit(last_ts + gap_ms, pending)


class StreamCheckpointer:
    """Checkpoint/listener plumbing for unbounded fits (the reference
    checkpoints unbounded iterations the same way as bounded ones; here a
    checkpoint is the (state pytree, batch count) snapshot between batches).

    Resume semantics are at-least-once: the restored state continues from
    wherever the *incoming* stream currently is — replaying the exact
    source position is the source's concern, exactly as in the reference
    where the source operator holds its own offsets.
    """

    def __init__(self, config=None, listeners=()):
        self.mgr = getattr(config, "checkpoint_manager", None) \
            if config is not None else None
        self.interval = getattr(config, "checkpoint_interval", 0) \
            if config is not None else 0
        self.listeners = tuple(listeners)
        self.batches = 0

    def restore(self, template_state):
        """Latest (state, batch_count) or None."""
        if self.mgr is None:
            return None
        restored = self.mgr.restore(template_state)
        if restored is not None:
            self.batches = restored[1]
        return restored

    def after_batch(self, state_fn) -> None:
        """``state_fn`` is a zero-arg thunk producing the state pytree — it
        is only invoked when a listener or a due checkpoint actually needs
        the state, so an inert checkpointer adds no per-batch cost."""
        self.batches += 1
        due = (self.mgr is not None and self.interval
               and self.batches % self.interval == 0)
        if not self.listeners and not due:
            return
        state = state_fn()
        for lst in self.listeners:
            lst.on_epoch_watermark_incremented(self.batches - 1, state)
        if due:
            self.mgr.save(state, self.batches)

    def complete(self, state_fn) -> None:
        """The stream ended (bounded fixture = job success): notify and
        discard checkpoints. A crash mid-stream skips this, keeping the
        resume point."""
        if self.listeners:
            state = state_fn()
            for lst in self.listeners:
                lst.on_iteration_terminated(state)
        if self.mgr is not None:
            self.mgr.clear()


def iterate_unbounded(initial_model: Any,
                      batches: Iterable[Any],
                      step: Callable[[Any, Any], Any],
                      on_model: Optional[Callable[[Any, int], None]] = None,
                      initial_version: int = 0,
                      checkpointer: Optional[StreamCheckpointer] = None
                      ) -> Iterator[Tuple[Any, int]]:
    """Unbounded iteration: fold ``step`` over batches, yielding
    (model_carry, version) after every batch — the feedback edge of
    Iterations.iterateUnboundedStreams as a host generator.
    """
    model = initial_model
    version = initial_version
    if checkpointer is not None:
        restored = checkpointer.restore((model, version))
        if restored is not None:
            model, version = restored[0]
            version = int(version)  # np round-trip must not change the type
    for batch in batches:
        model = step(model, batch)
        version += 1
        if on_model is not None:
            on_model(model, version)
        if checkpointer is not None:
            checkpointer.after_batch(lambda: (model, version))
        yield model, version
    if checkpointer is not None:
        checkpointer.complete(lambda: (model, version))
