"""Termination criteria helpers.

Ref parity: flink-ml-core/.../common/iteration/{TerminateOnMaxIter.java:34,
TerminateOnMaxIterOrTol.java:34, ForwardInputsOfLastRound.java:34}. In the
reference these are dataflow UDFs feeding the coordinator's termination
vote; here they are predicate factories for ``iterate_bounded``'s
``terminate`` argument (epoch bounding is the driver's ``max_iter``; these
add the tol / data-dependent parts).
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp


def terminate_on_max_iter(max_iter: int) -> Callable:
    """Pure round-count bound (ref: TerminateOnMaxIter) — provided for
    symmetry; equivalent to passing ``max_iter`` to iterate_bounded."""
    def predicate(carry: Any, epoch) -> jnp.ndarray:
        return jnp.asarray(epoch + 1 >= max_iter)
    return predicate


def terminate_on_max_iter_or_tol(tol: float,
                                 loss_fn: Callable[[Any], Any] = None
                                 ) -> Callable:
    """Stop when the carry's loss drops below tol (ref:
    TerminateOnMaxIterOrTol — the maxIter half is the driver's bound).
    ``loss_fn`` extracts the loss scalar from the carry (default: carry
    itself, or its 'loss' entry for dict carries)."""
    def predicate(carry: Any, epoch) -> jnp.ndarray:
        loss = (loss_fn(carry) if loss_fn is not None
                else (carry["loss"] if isinstance(carry, dict) else carry))
        return jnp.asarray(loss) < tol
    return predicate


def terminate_on_empty_round(count_fn: Callable[[Any], Any]) -> Callable:
    """Stop when a round processed zero records — the coordinator's
    data-driven vote (ref: SharedProgressAligner.EpochStatus.isTerminated,
    SharedProgressAligner.java:277-292). ``count_fn`` extracts the global
    (already psum'd) record count from the carry."""
    def predicate(carry: Any, epoch) -> jnp.ndarray:
        return jnp.asarray(count_fn(carry)) == 0
    return predicate


def forward_inputs_of_last_round(final_carry: Any,
                                 extract: Callable[[Any], Any] = None):
    """The final carry IS the last round's value (ref:
    ForwardInputsOfLastRound buffers then emits at termination — on TPU
    nothing needs buffering; this helper just documents the mapping)."""
    return extract(final_carry) if extract is not None else final_carry
