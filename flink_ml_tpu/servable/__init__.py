"""Engine-free online inference (the "servable" path).

Ref parity: flink-ml-servable-core/.../servable/api/ (DataFrame.java:33,
Row.java, TransformerServable.java, ModelServable.java, DataTypes.java),
servable/builder/PipelineModelServable.java and flink-ml-servable-lib's
LogisticRegressionModelServable.java:62.

The serving path has no dependency on the training runtime: a servable
loads model data from files/streams and transforms in-memory DataFrames.
The same jitted/vectorized predict math as the full Models is reused.
"""

from flink_ml_tpu.servable.api import (  # noqa: F401
    BasicType,
    DataFrame,
    DataTypes,
    ModelServable,
    RejectedRequest,
    Row,
    TransformerServable,
    serving_name,
)
from flink_ml_tpu.servable.builder import (  # noqa: F401
    PipelineModelServable,
    load_servable,
)
from flink_ml_tpu.servable.lr import (  # noqa: F401
    LogisticRegressionModelServable,
)
