"""Servable API: DataFrame / Row / TransformerServable / ModelServable.

Ref parity: servable/api/DataFrame.java:33 (addColumn:100, collect:119),
Row.java, TransformerServable.java, ModelServable.java,
servable/types/DataTypes.java.
"""

from __future__ import annotations

import enum
import functools
import logging
import time
from typing import Any, List, Optional, Sequence

import numpy as np


class RejectedRequest(Exception):
    """A serving request was shed by admission control (serving/
    batcher.py): its deadline expired before dispatch, the queue was
    full, or its shape doesn't fit the bucket table. Carries the
    servable name and a machine-readable ``reason`` so the
    ``rejected{servable=,reason=}`` windowed counter (observability/
    health.py) can distinguish shed load from real errors — a loadgen
    SLO verdict must not count deliberate load-shedding against the
    error budget."""

    def __init__(self, servable: str, reason: str, detail: str = ""):
        self.servable = servable
        self.reason = reason
        tail = f": {detail}" if detail else ""
        super().__init__(
            f"request rejected by {servable} ({reason}){tail}")


def serving_name(servable) -> str:
    """The name a servable's telemetry is labeled with: the deployed
    ``serving_name`` attribute when the model registry (serving/
    registry.py) set one (``<model>@v<N>``), else the class name — so
    span attrs, latency histograms and SLO verdicts distinguish model
    versions, not just servable classes."""
    return (getattr(servable, "serving_name", None)
            or type(servable).__name__)


class BasicType(enum.Enum):
    """Ref: servable/types/BasicType.java."""
    BOOLEAN = "boolean"
    BYTE = "byte"
    SHORT = "short"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    STRING = "string"


class DataType:
    def __init__(self, basic: BasicType, shape: str = "scalar"):
        self.basic = basic
        self.shape = shape  # scalar | vector | matrix

    def __repr__(self):
        return f"DataType({self.basic.value}, {self.shape})"

    def __eq__(self, other):
        return (isinstance(other, DataType) and self.basic == other.basic
                and self.shape == other.shape)


class DataTypes:
    """Ref: servable/types/DataTypes.java factory constants."""
    BOOLEAN = DataType(BasicType.BOOLEAN)
    INT = DataType(BasicType.INT)
    LONG = DataType(BasicType.LONG)
    FLOAT = DataType(BasicType.FLOAT)
    DOUBLE = DataType(BasicType.DOUBLE)
    STRING = DataType(BasicType.STRING)

    @staticmethod
    def vector(basic: BasicType = BasicType.DOUBLE) -> DataType:
        return DataType(basic, "vector")

    @staticmethod
    def matrix(basic: BasicType = BasicType.DOUBLE) -> DataType:
        return DataType(basic, "matrix")


class Row:
    """Ref: servable/api/Row.java — positional values with add/get/set."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def get(self, index: int):
        return self.values[index]

    def get_as(self, index: int, _type=None):
        return self.values[index]

    def set(self, index: int, value) -> "Row":
        self.values[index] = value
        return self

    def add(self, value) -> "Row":
        self.values.append(value)
        return self

    def size(self) -> int:
        return len(self.values)

    def __eq__(self, other):
        return isinstance(other, Row) and self.values == other.values

    def __repr__(self):
        return f"Row({self.values})"


class _Column:
    def __init__(self, name, dtype, values):
        self.name = name
        self.dtype = dtype
        self.values = values


class DataFrame:
    """Ref: servable/api/DataFrame.java:33 — in-memory rows + schema."""

    def __init__(self, column_names: List[str],
                 data_types: List[DataType], rows: List[Row]):
        if len(column_names) != len(data_types):
            raise ValueError("columnNames and dataTypes must align")
        for row in rows:
            if row.size() != len(column_names):
                raise ValueError("row arity does not match schema")
        self._names = list(column_names)
        self._types = list(data_types)
        self._rows = list(rows)

    @property
    def column_names(self) -> List[str]:
        return list(self._names)

    @property
    def data_types(self) -> List[DataType]:
        return list(self._types)

    def get_index(self, name: str) -> int:
        try:
            return self._names.index(name)
        except ValueError:
            raise ValueError(f"no column {name!r}; available {self._names}")

    def get_data_type(self, name: str) -> DataType:
        return self._types[self.get_index(name)]

    def add_column(self, name: str, dtype: DataType,
                   values: Sequence[Any]) -> "DataFrame":
        """Ref: DataFrame.addColumn:100 — appends a column in place."""
        if len(values) != len(self._rows):
            raise ValueError("column length must equal number of rows")
        self._names.append(name)
        self._types.append(dtype)
        for row, v in zip(self._rows, values):
            row.add(v)
        return self

    def get(self, name: str) -> "_Column":
        idx = self.get_index(name)
        return _Column(name, self._types[idx],
                       [row.get(idx) for row in self._rows])

    def collect(self) -> List[Row]:
        """Ref: DataFrame.collect:119."""
        return list(self._rows)

    def num_rows(self) -> int:
        return len(self._rows)


def _served(method):
    """Wrap a servable ``transform`` with the live serving telemetry
    (observability/health.py; docs/observability.md "Live telemetry &
    SLOs"): windowed latency + row-count histograms and a
    prediction-distribution summary labeled by servable class — the
    ``MLMetrics`` role of the reference's servable core — feeds the
    windowed live sketches drift detection compares against the
    training-time baseline (observability/drift.py) — plus an
    in-flight gauge, per-exception-class
    error counters (the error-rate SLO input; the exception re-raises
    after being counted), a request-scoped span sampled at
    ``FLINK_ML_TPU_TRACE_SAMPLE``, and a best-effort start of the
    embedded metrics endpoint (``FLINK_ML_TPU_METRICS_PORT``).
    Telemetry failures are logged, never raised: recording must not
    sink a serving call."""

    @functools.wraps(method)
    def wrapper(self, df: DataFrame) -> DataFrame:
        servable = serving_name(self)
        log = logging.getLogger(__name__)
        span_cm, entered = None, False
        try:
            from flink_ml_tpu.observability import health, server, tracing

            server.maybe_start()
            health.serving_inflight(servable, +1)
            entered = True
            if tracing.tracer.active and health.trace_sampled():
                rows_in = df.num_rows() if isinstance(df, DataFrame) \
                    else 0
                span_cm = tracing.tracer.span(
                    "serving.request", servable=servable,
                    rows_in=rows_in)
        except Exception:  # noqa: BLE001 — see docstring
            span_cm = None
            log.warning("serving telemetry setup failed", exc_info=True)
        start = time.perf_counter()
        try:
            if span_cm is not None:
                with span_cm:
                    out = method(self, df)
            else:
                out = method(self, df)
        except Exception as e:
            elapsed_ms = (time.perf_counter() - start) * 1000.0
            try:
                from flink_ml_tpu.observability import health

                if isinstance(e, RejectedRequest):
                    # shed load is not an error: admission failures get
                    # their own windowed counter so SLO error budgets
                    # only pay for real failures
                    health.observe_serving_rejected(servable, e.reason)
                else:
                    health.observe_serving_error(servable,
                                                 type(e).__name__,
                                                 elapsed_ms)
            except Exception:  # noqa: BLE001 — see docstring
                log.warning("serving error recording failed",
                            exc_info=True)
            raise
        finally:
            if entered:
                try:
                    from flink_ml_tpu.observability import health

                    health.serving_inflight(servable, -1)
                except Exception:  # noqa: BLE001 — see docstring
                    log.warning("serving in-flight recording failed",
                                exc_info=True)
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        try:
            from flink_ml_tpu.observability import health

            predictions = None
            rows = df.num_rows() if isinstance(df, DataFrame) else 0
            if isinstance(out, DataFrame):
                rows = out.num_rows()
                col = getattr(self, "prediction_col", None)
                if col and col in out.column_names:
                    predictions = out.get(col).values
            health.observe_serving(servable, rows, elapsed_ms,
                                   predictions=predictions)
            # drift: sketch this transform's feature columns +
            # predictions into the servable's windowed live sketches
            # (observability/drift.py) — the live half the training-time
            # baseline is compared against
            from flink_ml_tpu.observability import drift

            # the micro-batcher pads batches by duplicating the tail
            # row and marks the real count — sketch only real rows, or
            # a 1-row request padded to bucket 8 would overweight one
            # sample 8x and inflate the min-count floor
            real = getattr(df, "drift_real_rows", None)
            features = None
            fcol = getattr(self, "features_col", None)
            if (fcol and isinstance(df, DataFrame)
                    and fcol in df.column_names):
                features = df.get(fcol).values
                if real is not None:
                    features = features[:real]
            drift_preds = predictions
            if real is not None and drift_preds is not None:
                drift_preds = list(drift_preds)[:real]
            if features is not None or drift_preds is not None:
                drift.observe_transform(servable, features=features,
                                        predictions=drift_preds)
            # quality: park this request's positive-class scores in the
            # evaluation join ring, keyed by the batcher's per-request
            # ordinals, so record_feedback(request_id, label) can join
            # delayed ground truth back to what was actually served
            from flink_ml_tpu.observability import evaluation

            segments = getattr(df, "request_segments", None)
            if segments and isinstance(out, DataFrame):
                raw_values = None
                rcol = getattr(self, "raw_prediction_col", None)
                if rcol and rcol in out.column_names:
                    raw_values = out.get(rcol).values
                scores = evaluation.positive_scores(
                    raw_values=raw_values, predictions=predictions)
                if scores is not None:
                    evaluation.observe_served(servable, scores,
                                              segments=segments)
        except Exception:  # noqa: BLE001 — see docstring
            logging.getLogger(__name__).warning(
                "serving metrics recording failed", exc_info=True)
        return out

    wrapper._served = True
    return wrapper


class TransformerServable:
    """Ref: servable/api/TransformerServable.java.

    Beyond the reference's interface: every concrete ``transform`` is
    wrapped with the ``ml.serving`` metrics of observability/health.py
    (latency/row histograms + prediction-distribution summary), the
    same pattern api/stage.py applies to Estimator/AlgoOperator."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        impl = cls.__dict__.get("transform")
        if impl is not None and not getattr(impl, "_served", False):
            cls.transform = _served(impl)

    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError


class ModelServable(TransformerServable):
    """Ref: servable/api/ModelServable.java — loads model data from
    streams/files; ``load(path)`` restores params + model data."""

    def set_model_data(self, *streams) -> "ModelServable":
        raise NotImplementedError

    @classmethod
    def load(cls, path: str) -> "ModelServable":
        raise NotImplementedError
