"""Logistic regression servable.

Ref parity: flink-ml-servable-lib/.../classification/logisticregression/
LogisticRegressionModelServable.java:62 — transform adds prediction +
rawPrediction columns (:106: prediction = 1 iff dot ≥ 0, raw = [1-p, p]);
model data loads from a byte stream (LogisticRegressionModelData
encode/decode) or from a saved model directory.
"""

from __future__ import annotations

import io as _io
import threading
from typing import Tuple

import numpy as np

from flink_ml_tpu.common.locks import make_lock
from flink_ml_tpu.linalg.vectors import DenseVector, Vector
from flink_ml_tpu.params.shared import (
    HasFeaturesCol,
    HasPredictionCol,
    HasRawPredictionCol,
)
from flink_ml_tpu.servable.api import (
    DataFrame,
    DataTypes,
    ModelServable,
    serving_name,
)
from flink_ml_tpu.utils import io as rw


class LogisticRegressionModelData:
    """Ref: LogisticRegressionModelData with encode/decode."""

    def __init__(self, coefficient: np.ndarray, model_version: int = 0):
        self.coefficient = np.asarray(coefficient, np.float64)
        self.model_version = int(model_version)

    def encode(self) -> bytes:
        vec = DenseVector(self.coefficient).to_bytes()
        return self.model_version.to_bytes(8, "little") + vec

    @staticmethod
    def decode(data: bytes) -> "LogisticRegressionModelData":
        version = int.from_bytes(data[:8], "little")
        vec = Vector.from_bytes(data[8:])
        return LogisticRegressionModelData(vec.to_array(), version)


_PREDICT_JIT = None
_PREDICT_LOCK = make_lock("servable.lr.predict")

#: one row-sharded predict twin per mesh (keyed by device ids + axes):
#: the executable is shared across model versions — a hot-swap only
#: re-places the coefficient vector, never recompiles — and across
#: buckets, with one compile-cache entry per (bucket, dim) signature
#: that serving/warmup.py pre-pays
_SHARDED_JITS: dict = {}


def _mesh_cache_key(mesh):
    return (tuple(int(d.id) for d in mesh.devices.flat),
            tuple(mesh.axis_names), mesh.devices.shape)


def _sharded_predict_jit(mesh):
    """The mesh-sharded twin of :func:`_predict_jit`: the same
    ``dots = x @ coef`` kernel built through
    :func:`~flink_ml_tpu.parallel.mapreduce.map_rows` — rows split over
    the mesh's data axes, the coefficient replicated, each device
    predicting its contiguous slice of the padded serving bucket with
    no collective on the hot path (results gather on the fetch side).
    Named ``lr.predict.sharded`` so its compiles are counted apart from
    the single-device kernel's — the warmup matrix (serving/warmup.py)
    and the steady-state zero-compile probe see both."""
    key = _mesh_cache_key(mesh)
    fn = _SHARDED_JITS.get(key)
    if fn is None:
        with _PREDICT_LOCK:
            fn = _SHARDED_JITS.get(key)
            if fn is None:
                from flink_ml_tpu.parallel import mapreduce as mr

                def _lr_dots(x, coef):
                    return x @ coef

                fn = mr.map_rows(_lr_dots, mesh, n_extra=1,
                                 name="lr.predict.sharded")
                _SHARDED_JITS[key] = fn
    return fn


def _predict_jit():
    """The shared jitted predict kernel (``dots = x @ coef``) wrapped in
    :func:`~flink_ml_tpu.observability.compilestats.instrumented_jit` —
    compiles are counted per abstract signature (``fn="lr.predict"``),
    which is exactly the serving bucket contract: with the micro-batcher
    padding to a fixed bucket table (serving/batcher.py) steady-state
    serving hits this cache on every request; without bucketing every
    distinct row count is a fresh compile and the recompile-storm
    detector fires. Built lazily so importing the servable never
    imports jax."""
    global _PREDICT_JIT
    if _PREDICT_JIT is None:
        with _PREDICT_LOCK:
            if _PREDICT_JIT is None:
                from flink_ml_tpu.observability.compilestats import (
                    instrumented_jit,
                )

                def _lr_dots(x, coef):
                    return x @ coef

                _PREDICT_JIT = instrumented_jit(_lr_dots,
                                                name="lr.predict")
    return _PREDICT_JIT


class LogisticRegressionModelServable(ModelServable, HasFeaturesCol,
                                      HasPredictionCol, HasRawPredictionCol):
    #: route the dot products through the jitted device kernel instead
    #: of host numpy — the serving runtime flips this so request batches
    #: ride one device dispatch per tick (serving/batcher.py)
    device_predict = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.model_data: LogisticRegressionModelData = None
        self._coef_dev = None
        self._mesh = None
        self._coef_mesh = None
        self._n_shards = 1

    def set_model_data(self, *streams) -> "LogisticRegressionModelServable":
        (stream,) = streams
        data = stream.read() if hasattr(stream, "read") else bytes(stream)
        self.model_data = LogisticRegressionModelData.decode(data)
        self._coef_dev = None
        self._coef_mesh = None
        return self

    def set_device_predict(self, enabled: bool = True
                           ) -> "LogisticRegressionModelServable":
        self.device_predict = bool(enabled)
        return self

    def set_mesh(self, mesh) -> "LogisticRegressionModelServable":
        """Mesh-sharded dispatch (docs/serving.md "Mesh-sharded
        dispatch"): batches whose row count divides the mesh's
        data-shard count predict through the row-sharded twin — each
        device scores its slice of the padded serving bucket — while
        non-divisible shapes (bucket 1 on an 8-way mesh) keep the
        single-device kernel. ``None`` reverts to single-device.
        Idempotent on the same mesh object, so the dispatcher can
        re-assert it per tick without churning the coefficient
        placement."""
        if mesh is self._mesh:
            return self
        self._mesh = mesh
        self._coef_mesh = None
        if mesh is None:
            self._n_shards = 1
        else:
            from flink_ml_tpu.parallel.mesh import data_shard_count

            self._n_shards = data_shard_count(mesh)
        return self

    def _use_sharded(self, rows: int) -> bool:
        return (self._mesh is not None and self._n_shards > 1
                and rows % self._n_shards == 0)

    def _device_coef(self):
        # one H2D per model version, not one per request
        if self._coef_dev is None:
            import jax.numpy as jnp

            self._coef_dev = jnp.asarray(self.model_data.coefficient,
                                         jnp.float32)
        return self._coef_dev

    def _mesh_coef(self):
        # the sharded twin's parameter placement: the coefficient
        # replicated on every mesh device, once per (version, mesh)
        if self._coef_mesh is None:
            from flink_ml_tpu.parallel import collective

            self._coef_mesh = collective.replicate(
                self._mesh,
                np.asarray(self.model_data.coefficient, np.float32))
        return self._coef_mesh

    def _sharded_dots(self, x, real_rows: int, record: bool = True):
        """One mesh dispatch: place the padded batch row-sharded (each
        device receives exactly its slice — ONE transfer leg per
        device, no broadcast-then-slice), predict per device, gather on
        fetch. The padded input buffer is consumed by the dispatch:
        deleted as soon as the results are fetched, so the pipelined
        dispatcher (serving/batcher.py) holds at most ``depth + 1``
        live input buffers."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_ml_tpu.observability import health, meshstats
        from flink_ml_tpu.parallel.mesh import data_pspec

        mesh = self._mesh
        sharding = NamedSharding(mesh, P(data_pspec(mesh)))
        x_dev = jax.device_put(x, sharding)
        try:
            dots = np.asarray(
                _sharded_predict_jit(mesh)(x_dev, self._mesh_coef()),
                np.float64)
        finally:
            x_dev.delete()
        if record:
            per_shard = x.shape[0] // self._n_shards
            counts = meshstats.record_shard_rows(
                mesh, real_rows, local_n=per_shard, skew=False)
            health.observe_serving_shards(
                serving_name(self), counts,
                [int(d.id) for d in mesh.devices.flat])
        return dots

    def aot_warm(self, rows: int) -> None:
        """Compile the device predict kernel for a ``(rows, dim)`` batch
        now (serving/warmup.py calls this once per bucket shape at
        server start, so the first real request is a compile-cache
        hit) — the SAME kernel ``transform`` will route this shape to:
        the mesh-sharded twin when a mesh is set and ``rows`` divides
        its shard count, the single-device kernel otherwise. No-op
        without model data or with host predict."""
        if not self.device_predict or self.model_data is None:
            return
        import jax.numpy as jnp

        dim = self.model_data.coefficient.shape[0]
        if self._use_sharded(int(rows)):
            # warm with the SAME committed row-sharded placement the
            # dispatcher uses — an uncommitted zeros array would compile
            # a second executable for the differently-placed input and
            # the first real request would pay a steady-state compile.
            # record=False: a synthetic warm batch must not write the
            # shardRows/ml.shard series real traffic is gated on
            self._sharded_dots(
                np.zeros((int(rows), dim), np.float32), int(rows),
                record=False)
        else:
            _predict_jit()(jnp.zeros((int(rows), dim), jnp.float32),
                           self._device_coef())

    def transform(self, df: DataFrame) -> DataFrame:
        if self.model_data is None:
            raise ValueError("servable has no model data")
        features = df.get(self.features_col).values
        x = np.stack([f.to_array() if isinstance(f, Vector)
                      else np.asarray(f, np.float64) for f in features])
        if self.device_predict:
            xf = np.asarray(x, np.float32)
            if self._use_sharded(xf.shape[0]):
                real = getattr(df, "drift_real_rows", None)
                dots = self._sharded_dots(
                    xf, int(real) if real is not None else xf.shape[0])
            else:
                import jax.numpy as jnp

                dots = np.asarray(
                    _predict_jit()(jnp.asarray(xf), self._device_coef()),
                    np.float64)
        else:
            dots = x @ self.model_data.coefficient
        prob = 1.0 - 1.0 / (1.0 + np.exp(dots))
        # probability-distribution drift baseline (observability/
        # health.py): the 0/1 prediction column the _served wrapper
        # summarizes hides a NaN margin ((nan >= 0) is False), so the
        # probabilities are summarized here explicitly — a model serving
        # garbage raises the ml.health non-finite-probability event
        from flink_ml_tpu.observability import health

        health.summarize_values(type(self).__name__, "probability", prob)
        predictions = (dots >= 0).astype(np.float64)
        raw = [DenseVector([1 - p, p]) for p in prob]
        df.add_column(self.prediction_col, DataTypes.DOUBLE,
                      predictions.tolist())
        df.add_column(self.raw_prediction_col, DataTypes.vector(), raw)
        return df

    @classmethod
    def load(cls, path: str) -> "LogisticRegressionModelServable":
        meta = rw.load_metadata(path)
        servable = cls()
        servable.params_from_json(meta["paramMap"])
        arrays = rw.load_model_arrays(path, "model")
        version = int(arrays.get("modelVersion", [0])[0]) \
            if "modelVersion" in arrays else 0
        servable.model_data = LogisticRegressionModelData(
            arrays["coefficient"], version)
        return servable
