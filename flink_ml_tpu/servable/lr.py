"""Logistic regression servable.

Ref parity: flink-ml-servable-lib/.../classification/logisticregression/
LogisticRegressionModelServable.java:62 — transform adds prediction +
rawPrediction columns (:106: prediction = 1 iff dot ≥ 0, raw = [1-p, p]);
model data loads from a byte stream (LogisticRegressionModelData
encode/decode) or from a saved model directory.
"""

from __future__ import annotations

import io as _io
import threading
from typing import Tuple

import numpy as np

from flink_ml_tpu.linalg.vectors import DenseVector, Vector
from flink_ml_tpu.params.shared import (
    HasFeaturesCol,
    HasPredictionCol,
    HasRawPredictionCol,
)
from flink_ml_tpu.servable.api import (
    DataFrame,
    DataTypes,
    ModelServable,
)
from flink_ml_tpu.utils import io as rw


class LogisticRegressionModelData:
    """Ref: LogisticRegressionModelData with encode/decode."""

    def __init__(self, coefficient: np.ndarray, model_version: int = 0):
        self.coefficient = np.asarray(coefficient, np.float64)
        self.model_version = int(model_version)

    def encode(self) -> bytes:
        vec = DenseVector(self.coefficient).to_bytes()
        return self.model_version.to_bytes(8, "little") + vec

    @staticmethod
    def decode(data: bytes) -> "LogisticRegressionModelData":
        version = int.from_bytes(data[:8], "little")
        vec = Vector.from_bytes(data[8:])
        return LogisticRegressionModelData(vec.to_array(), version)


_PREDICT_JIT = None
_PREDICT_LOCK = threading.Lock()


def _predict_jit():
    """The shared jitted predict kernel (``dots = x @ coef``) wrapped in
    :func:`~flink_ml_tpu.observability.compilestats.instrumented_jit` —
    compiles are counted per abstract signature (``fn="lr.predict"``),
    which is exactly the serving bucket contract: with the micro-batcher
    padding to a fixed bucket table (serving/batcher.py) steady-state
    serving hits this cache on every request; without bucketing every
    distinct row count is a fresh compile and the recompile-storm
    detector fires. Built lazily so importing the servable never
    imports jax."""
    global _PREDICT_JIT
    if _PREDICT_JIT is None:
        with _PREDICT_LOCK:
            if _PREDICT_JIT is None:
                from flink_ml_tpu.observability.compilestats import (
                    instrumented_jit,
                )

                def _lr_dots(x, coef):
                    return x @ coef

                _PREDICT_JIT = instrumented_jit(_lr_dots,
                                                name="lr.predict")
    return _PREDICT_JIT


class LogisticRegressionModelServable(ModelServable, HasFeaturesCol,
                                      HasPredictionCol, HasRawPredictionCol):
    #: route the dot products through the jitted device kernel instead
    #: of host numpy — the serving runtime flips this so request batches
    #: ride one device dispatch per tick (serving/batcher.py)
    device_predict = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.model_data: LogisticRegressionModelData = None
        self._coef_dev = None

    def set_model_data(self, *streams) -> "LogisticRegressionModelServable":
        (stream,) = streams
        data = stream.read() if hasattr(stream, "read") else bytes(stream)
        self.model_data = LogisticRegressionModelData.decode(data)
        self._coef_dev = None
        return self

    def set_device_predict(self, enabled: bool = True
                           ) -> "LogisticRegressionModelServable":
        self.device_predict = bool(enabled)
        return self

    def _device_coef(self):
        # one H2D per model version, not one per request
        if self._coef_dev is None:
            import jax.numpy as jnp

            self._coef_dev = jnp.asarray(self.model_data.coefficient,
                                         jnp.float32)
        return self._coef_dev

    def aot_warm(self, rows: int) -> None:
        """Compile the device predict kernel for a ``(rows, dim)`` batch
        now (serving/warmup.py calls this once per bucket shape at
        server start, so the first real request is a compile-cache
        hit). No-op without model data or with host predict."""
        if not self.device_predict or self.model_data is None:
            return
        import jax.numpy as jnp

        dim = self.model_data.coefficient.shape[0]
        _predict_jit()(jnp.zeros((int(rows), dim), jnp.float32),
                       self._device_coef())

    def transform(self, df: DataFrame) -> DataFrame:
        if self.model_data is None:
            raise ValueError("servable has no model data")
        features = df.get(self.features_col).values
        x = np.stack([f.to_array() if isinstance(f, Vector)
                      else np.asarray(f, np.float64) for f in features])
        if self.device_predict:
            import jax.numpy as jnp

            dots = np.asarray(
                _predict_jit()(jnp.asarray(x, jnp.float32),
                               self._device_coef()), np.float64)
        else:
            dots = x @ self.model_data.coefficient
        prob = 1.0 - 1.0 / (1.0 + np.exp(dots))
        # probability-distribution drift baseline (observability/
        # health.py): the 0/1 prediction column the _served wrapper
        # summarizes hides a NaN margin ((nan >= 0) is False), so the
        # probabilities are summarized here explicitly — a model serving
        # garbage raises the ml.health non-finite-probability event
        from flink_ml_tpu.observability import health

        health.summarize_values(type(self).__name__, "probability", prob)
        predictions = (dots >= 0).astype(np.float64)
        raw = [DenseVector([1 - p, p]) for p in prob]
        df.add_column(self.prediction_col, DataTypes.DOUBLE,
                      predictions.tolist())
        df.add_column(self.raw_prediction_col, DataTypes.vector(), raw)
        return df

    @classmethod
    def load(cls, path: str) -> "LogisticRegressionModelServable":
        meta = rw.load_metadata(path)
        servable = cls()
        servable.params_from_json(meta["paramMap"])
        arrays = rw.load_model_arrays(path, "model")
        version = int(arrays.get("modelVersion", [0])[0]) \
            if "modelVersion" in arrays else 0
        servable.model_data = LogisticRegressionModelData(
            arrays["coefficient"], version)
        return servable
