"""PipelineModelServable.

Ref parity: servable/builder/PipelineModelServable.java — chains servable
twins of pipeline stages; ``load(path)`` reads a directory written by
``PipelineModel.save`` and resolves each stage to its servable class.
"""

from __future__ import annotations

from typing import List

from flink_ml_tpu.servable.api import DataFrame, TransformerServable
from flink_ml_tpu.utils import io as rw

#: training-model class name → servable class path (the reference resolves
#: via a loadServable() static on each model class)
_SERVABLE_TWINS = {
    "LogisticRegressionModel":
        "flink_ml_tpu.servable.lr.LogisticRegressionModelServable",
    "OnlineLogisticRegressionModel":
        "flink_ml_tpu.servable.lr.LogisticRegressionModelServable",
}


def load_servable(path: str) -> TransformerServable:
    """Load the servable twin of a stage saved at ``path``."""
    meta = rw.load_metadata(path)
    class_name = meta["className"].rsplit(".", 1)[-1]
    if class_name == "PipelineModel":
        return PipelineModelServable.load(path)
    twin = _SERVABLE_TWINS.get(class_name)
    if twin is None:
        raise ValueError(
            f"stage {meta['className']} has no servable; servables exist "
            f"for: {sorted(_SERVABLE_TWINS)} and PipelineModel")
    return rw.load_class(twin).load(path)


class PipelineModelServable(TransformerServable):
    def __init__(self, stages: List[TransformerServable]):
        self.stages = list(stages)

    def transform(self, df: DataFrame) -> DataFrame:
        for stage in self.stages:
            df = stage.transform(df)
        return df

    @classmethod
    def load(cls, path: str) -> "PipelineModelServable":
        meta = rw.load_metadata(path)
        num = meta["extra"]["numStages"]
        return cls([load_servable(rw.stage_path(path, i))
                    for i in range(num)])
