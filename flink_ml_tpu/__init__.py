"""flink_ml_tpu — a TPU-native ML framework with the capabilities of Apache Flink ML.

A from-scratch JAX/XLA/Pallas re-design of the Flink ML feature set
(reference: flink-ml 2.4-SNAPSHOT). The reference is a library on top of a
JVM dataflow engine; this framework replaces that engine with:

- SPMD ``pjit`` programs over a ``jax.sharding.Mesh`` (data parallelism,
  broadcast, collectives over ICI/DCN) instead of Flink network shuffles,
- a compiled round function driven by a host loop (or fully on-device
  ``lax.while_loop``) instead of the Flink iteration runtime,
- a host-side columnar ``Table`` instead of the Flink Table API,
- Orbax-style pytree checkpointing of the round carry instead of
  checkpoint barriers circulating through a dataflow cycle.

Layers (bottom-up, see SURVEY.md §7):
  params    — typed hyperparameter system (ref: flink-ml-servable-core param/)
  linalg    — vectors/matrices + BLAS-equivalent ops (ref: linalg/)
  parallel  — mesh + collectives (ref: AllReduceImpl, BroadcastUtils)
  iteration — bounded/unbounded iteration runtime (ref: flink-ml-iteration)
  api       — Stage/Estimator/Transformer/Model, Pipeline, Graph (ref: flink-ml-core)
  ops       — losses, SGD/FTRL optimizers, shared numeric kernels
  models    — the algorithm library (ref: flink-ml-lib)
  servable  — engine-free online inference (ref: flink-ml-servable-*)
  benchmark — JSON-config benchmark harness (ref: flink-ml-benchmark)
  analysis  — jaxlint static analyzer for JAX/TPU hazards (docs/jaxlint.md;
              no reference equivalent: the JVM had a type system where we
              have tracing)
"""

__version__ = "0.1.0"

from flink_ml_tpu.api import (  # noqa: F401
    AlgoOperator,
    Estimator,
    Model,
    Stage,
    Transformer,
)
from flink_ml_tpu.common.table import Table  # noqa: F401
from flink_ml_tpu.common.functions import (  # noqa: F401
    array_to_vector,
    vector_to_array,
)
